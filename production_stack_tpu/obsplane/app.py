"""Obsplane application: the ``/fleet`` HTTP surface + wiring + CLI.

One aiohttp Application hosting the fleet aggregator task; endpoint
surface (docs/observability.md "Fleet observability"):

- ``GET /health``            — aggregator liveness + per-process
                               reachability summary (probe surface)
- ``GET /fleet``             — the fleet snapshot: processes, firing
                               alerts, stitch stats, incident index
- ``GET /fleet/traces``      — online-stitched chains: per-class
                               per-phase fleet percentiles + the
                               current slowest complete chains
                               (``slowest=N``, ``class=``)
- ``GET /fleet/incidents``   — the bounded on-disk bundle index
- ``GET /fleet/incidents/{id}`` — one full bundle
- ``POST /fleet/capture``    — operator-triggered capture (bypasses
                               the alert cooldown)
- ``GET /metrics``           — the ``tpu:fleet_*`` families

Closed loop: ``python -m production_stack_tpu.loadgen incident``.
"""

import argparse
import asyncio
import signal
from typing import Optional

from aiohttp import web

from production_stack_tpu.obsplane.aggregator import FleetAggregator
from production_stack_tpu.obsplane.metrics import FleetMetrics
from production_stack_tpu.obsplane.recorder import IncidentRecorder
from production_stack_tpu.obsplane.stitch import ChainStore
from production_stack_tpu.utils import (init_logger,
                                        parse_comma_separated,
                                        set_ulimit)
from production_stack_tpu.version import __version__

logger = init_logger(__name__)


async def health(request: web.Request) -> web.Response:
    state = request.app["state"]
    agg: FleetAggregator = state["aggregator"]
    problems = []
    if not agg.healthy():
        problems.append("fleet poll task dead")
    unreachable = [p.url for p in agg.processes.values()
                   if p.state == "unreachable"]
    body = {
        "status": "ok" if not problems else "unhealthy",
        "problems": problems,
        "version": __version__,
        "polls_total": agg.polls_total,
        "processes": {p.url: p.state
                      for p in agg.processes.values()},
        "unreachable": unreachable,
        "incidents_held": len(state["recorder"].index()),
    }
    return web.json_response(body,
                             status=200 if not problems else 503)


async def fleet(request: web.Request) -> web.Response:
    agg = request.app["state"]["aggregator"]
    return web.json_response(agg.fleet_snapshot(full=False))


async def fleet_traces(request: web.Request) -> web.Response:
    agg = request.app["state"]["aggregator"]
    try:
        slowest = max(1, int(request.query.get("slowest", "10")))
    except ValueError:
        slowest = 10
    cls = request.query.get("class") or None
    return web.json_response({
        "stats": agg.chains.stats(),
        "fleet_percentiles": agg.chains.fleet_percentiles(),
        "slowest": agg.chains.slowest(slowest, cls=cls),
    })


async def fleet_incidents(request: web.Request) -> web.Response:
    """The bounded bundle index, filterable for machine consumers
    (autoscaler/remediator.py): ``?since=<captured_at>`` returns only
    strictly-newer incidents, ``?confidence=high`` (or a comma list)
    filters on attribution confidence, ``?role=engine,prefill`` on
    the attributed role. Rows stay newest-last."""
    recorder = request.app["state"]["recorder"]
    rows = recorder.index()
    q = request.query
    if "since" in q:
        try:
            since = float(q["since"])
        except ValueError:
            return web.json_response(
                {"error": {"message": "since must be a captured_at "
                                      "float",
                           "type": "invalid_request_error"}},
                status=400)
        rows = [r for r in rows
                if (r.get("captured_at") or 0.0) > since]
    confidences = {c.strip() for c in q.get("confidence", "").split(",")
                   if c.strip()}
    if confidences:
        rows = [r for r in rows
                if (r.get("attribution") or {}).get("confidence")
                in confidences]
    roles = {r.strip() for r in q.get("role", "").split(",")
             if r.strip()}
    if roles:
        rows = [r for r in rows
                if (r.get("attribution") or {}).get("role") in roles]
    return web.json_response({"incidents": rows})


async def fleet_incident(request: web.Request) -> web.Response:
    recorder = request.app["state"]["recorder"]
    bundle = recorder.load(request.match_info["incident_id"])
    if bundle is None:
        return web.json_response(
            {"error": {"message": "unknown incident id",
                       "type": "invalid_request_error"}}, status=404)
    return web.json_response(bundle)


async def fleet_capture(request: web.Request) -> web.Response:
    """Operator-triggered capture; always produces a bundle (the
    alert-path cooldown exists to absorb alert storms, not humans)."""
    state = request.app["state"]
    reason = "manual"
    try:
        body = await request.json()
        if isinstance(body, dict) and body.get("reason"):
            reason = f"manual:{str(body['reason'])[:80]}"
    except ValueError:
        pass
    row = state["aggregator"].capture(trigger=reason, force=True)
    state["manual_captures"] += 1
    return web.json_response({"captured": row})


async def metrics(request: web.Request) -> web.Response:
    state = request.app["state"]
    state["metrics"].refresh(state["aggregator"], state["recorder"],
                             state["manual_captures"])
    return web.Response(body=state["metrics"].render(),
                        content_type="text/plain")


def build_app(args: argparse.Namespace) -> web.Application:
    recorder = IncidentRecorder(
        args.incident_dir, retention=args.incident_retention,
        cooldown_s=args.capture_cooldown)
    chains = ChainStore(max_chains=args.chain_entries)
    aggregator = FleetAggregator(
        routers=parse_comma_separated(args.routers),
        engines=parse_comma_separated(args.engines),
        prefill=parse_comma_separated(args.prefill_backends),
        poll_interval_s=args.poll_interval,
        timeout_s=args.scrape_timeout,
        trace_batch=args.trace_batch,
        attribution_lookback_s=args.attribution_lookback,
        capture_severities=tuple(
            parse_comma_separated(args.capture_severities)),
        capture_on_alerts=not args.no_capture_on_alert,
        chain_store=chains,
        recorder=recorder,
        engines_config=args.engines_config or None)
    app = web.Application()
    app["state"] = {
        "aggregator": aggregator,
        "recorder": recorder,
        "metrics": FleetMetrics(),
        "manual_captures": 0,
    }
    app.router.add_get("/health", health)
    app.router.add_get("/fleet", fleet)
    app.router.add_get("/fleet/traces", fleet_traces)
    app.router.add_get("/fleet/incidents", fleet_incidents)
    app.router.add_get("/fleet/incidents/{incident_id}", fleet_incident)
    app.router.add_post("/fleet/capture", fleet_capture)
    app.router.add_get("/metrics", metrics)

    async def on_startup(app):
        await aggregator.start()

    async def on_cleanup(app):
        await aggregator.close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        "pstpu-obsplane",
        description="fleet observability aggregator: online trace "
                    "stitching + alert-triggered incident snapshots")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--routers", default="",
                   help="comma-separated router base URLs to scrape "
                        "(/health, /alerts, /debug/traces)")
    p.add_argument("--engines", default="",
                   help="comma-separated engine base URLs to scrape "
                        "(/load, /debug/perf, /debug/traces)")
    p.add_argument("--prefill-backends", default="",
                   help="comma-separated prefill-pool engine URLs "
                        "(scraped like engines, stitched as the "
                        "prefill side of a chain)")
    p.add_argument("--engines-config", default="",
                   help="path to the autoscaler's dynamic-config JSON "
                        "(static_backends): re-read every poll so the "
                        "scraped engine set follows an elastic fleet "
                        "without an obsplane restart")
    p.add_argument("--poll-interval", type=float, default=1.0,
                   help="seconds between fleet scrape passes")
    p.add_argument("--scrape-timeout", type=float, default=3.0,
                   help="per-request scrape timeout; a process "
                        "missing this twice in a row is marked "
                        "unreachable")
    p.add_argument("--trace-batch", type=int, default=500,
                   help="max trace rows read per process per pass "
                        "through the /debug/traces since_seq cursor")
    p.add_argument("--chain-entries", type=int, default=4096,
                   help="stitched chains held in memory (oldest "
                        "evicted)")
    p.add_argument("--incident-dir", default="incidents",
                   help="directory incident bundles are written to")
    p.add_argument("--incident-retention", type=int, default=32,
                   help="bundles kept on disk (oldest deleted)")
    p.add_argument("--capture-cooldown", type=float, default=30.0,
                   help="seconds after a capture during which further "
                        "alert-triggered captures are suppressed (an "
                        "incident firing several alerts yields ONE "
                        "bundle); POST /fleet/capture bypasses it")
    p.add_argument("--capture-severities", default="page",
                   help="comma-separated alert severities whose "
                        "firing transition triggers a capture "
                        "(default: page — tickets describe the same "
                        "burn more slowly)")
    p.add_argument("--attribution-lookback", type=float, default=60.0,
                   help="seconds of per-process phase evidence the "
                        "attribution scoreboard ranks at capture time")
    p.add_argument("--no-capture-on-alert", action="store_true",
                   help="disable alert-triggered captures (manual "
                        "POST /fleet/capture only)")
    args = p.parse_args(argv)
    if not (args.routers or args.engines or args.engines_config):
        p.error("need --routers, --engines and/or --engines-config "
                "to scrape")
    return args


def main(argv=None) -> None:
    args = parse_args(argv)
    set_ulimit()
    app = build_app(args)

    async def _serve():
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, args.host, args.port)
        await site.start()
        logger.info("obsplane listening on %s:%d (%d processes, "
                    "poll every %.1fs, incidents -> %s)",
                    args.host, args.port,
                    len(app["state"]["aggregator"].processes),
                    args.poll_interval, args.incident_dir)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
        await runner.cleanup()

    asyncio.run(_serve())


if __name__ == "__main__":
    main()
