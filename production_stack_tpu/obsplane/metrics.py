"""Obsplane Prometheus surface: the ``tpu:fleet_*`` families.

Refreshed from the aggregator's counters at scrape time (the stack's
delta-free variant of the scrape-time-sync idiom — all values here are
either gauges or cumulative counters the aggregator already holds, so
the exposition just copies them; nothing prometheus-shaped sits near
the poll loop). Documented in docs/observability.md "Fleet
observability".
"""

from prometheus_client import CollectorRegistry, Gauge, generate_latest


class FleetMetrics:
    def __init__(self):
        self.registry = CollectorRegistry()
        self.processes = Gauge(
            "tpu:fleet_processes",
            "Fleet processes the obsplane scrapes, by role and "
            "reachability state (live / unreachable / pending)",
            ["role", "state"], registry=self.registry)
        self.scrape_errors = Gauge(
            "tpu:fleet_scrape_errors_total",
            "Cumulative failed scrape passes against fleet processes, "
            "by role", ["role"], registry=self.registry)
        self.chains_stitched = Gauge(
            "tpu:fleet_chains_stitched_total",
            "Cumulative cross-process trace chains completed by the "
            "online stitcher (router + engine sides joined on "
            "trace id)", registry=self.registry)
        self.traces_ingested = Gauge(
            "tpu:fleet_traces_ingested_total",
            "Cumulative trace rows read through the /debug/traces "
            "since_seq cursor across the fleet",
            registry=self.registry)
        self.alerts_firing = Gauge(
            "tpu:fleet_alerts_firing",
            "SLO alerts currently firing across every scraped "
            "router", registry=self.registry)
        self.incidents = Gauge(
            "tpu:fleet_incidents_total",
            "Cumulative incident bundles captured by the flight "
            "recorder, by trigger kind (alert / manual)",
            ["trigger"], registry=self.registry)
        self.incidents_suppressed = Gauge(
            "tpu:fleet_incidents_suppressed_total",
            "Alert transitions that would have captured a bundle but "
            "fell inside the capture cooldown",
            registry=self.registry)
        self.incidents_held = Gauge(
            "tpu:fleet_incidents_held",
            "Incident bundles currently on disk (bounded by "
            "--incident-retention)", registry=self.registry)

    def refresh(self, aggregator, recorder=None,
                manual_captures: int = 0) -> None:
        counts = {}
        for proc in aggregator.processes.values():
            counts[(proc.role, proc.state)] = \
                counts.get((proc.role, proc.state), 0) + 1
        # zero out stale label pairs by setting every known role/state
        for role in ("router", "engine", "prefill"):
            for state in ("live", "unreachable", "pending"):
                self.processes.labels(role=role, state=state).set(
                    counts.get((role, state), 0))
        for role, n in aggregator.scrape_errors_total.items():
            self.scrape_errors.labels(role=role).set(n)
        self.chains_stitched.set(aggregator.chains.chains_complete)
        self.traces_ingested.set(aggregator.chains.traces_ingested)
        self.alerts_firing.set(len(aggregator._iter_firing()))
        if recorder is not None:
            alert_captures = recorder.captured_total - manual_captures
            self.incidents.labels(trigger="alert").set(alert_captures)
            self.incidents.labels(trigger="manual").set(manual_captures)
            self.incidents_suppressed.set(recorder.suppressed_total)
            self.incidents_held.set(len(recorder.index()))

    def render(self) -> bytes:
        return generate_latest(self.registry)
