"""In-process request tracing: spans, W3C traceparent, phase histograms.

Every aggregate loop this stack has closed (overhead, chaos, overload,
autoscale, kvshare, disagg) reports *end-to-end* percentiles; when a
percentile moves, nothing says which phase moved it. This module is the
attribution substrate: a dependency-free span recorder each process
(router, engine, fake engine) threads through its request path, plus
W3C ``traceparent`` propagation so one request's spans join up across
processes.

Design constraints, in order:

- **Hot-path cost ~zero.** A span is one tuple append; sealing a trace
  is a handful of bisects into plain-int bucket arrays. Nothing here
  touches prometheus objects, locks the event loop, or renders JSON
  per request — rendering happens at ``GET /debug/traces`` read time.
- **Bounded.** Completed traces live in a ring (``ring_entries``,
  ``collections.deque(maxlen=...)``); an unread ring costs a fixed
  amount of memory forever.
- **Cross-process correlation.** The router parses an inbound
  ``traceparent`` (or mints one), forwards a child context to the
  engine, and stamps ``x-trace-id`` on every response so a client-side
  harness can join client-observed latency to server-side spans. The
  sampled flag (``-01``) propagates: the engine records whatever the
  router sampled, so chains are never half-recorded by disagreeing
  sampling decisions.
- **Phases vs events.** Spans carry a ``kind``: ``"phase"`` spans are
  mutually non-overlapping slices of the request's wall time and feed
  the ``tpu:*_phase_seconds`` histograms at seal time (so an abandoned
  failover attempt — an ``"event"`` span — shows up in the trace but
  never double-counts a phase); unattributed time is the trace's
  duration minus the phase sum, the honesty metric ``loadgen trace``
  gates on.

The wire format follows https://www.w3.org/TR/trace-context/ level 1:
``traceparent: 00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>``.

Operator surface: docs/observability.md "Tracing".
"""

import collections
import os
import random
import threading
import time
from bisect import bisect_right
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# phase-duration histogram bucket bounds (seconds). Identical for the
# router and engine families so stacked dashboards line up.
PHASE_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0)

_FLAG_SAMPLED = 0x01


# ---------------------------------------------------------------- context

def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str, bool]]:
    """``(trace_id, parent_span_id, sampled)`` or None when absent or
    malformed (a bad header starts a fresh trace, never an error)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(version, 16)
        int(trace_id, 16)
        int(span_id, 16)
        flag_bits = int(flags, 16)
    except ValueError:
        return None
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None                      # spec: invalid sentinels
    return trace_id, span_id, bool(flag_bits & _FLAG_SAMPLED)


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


# ---------------------------------------------------------------- spans

class RequestTrace:
    """One request's spans inside one process.

    Spans append as ``(name, kind, start_mono, dur_s, status, attrs)``
    tuples — no objects on the hot path. ``start_mono`` may be None for
    duration-only spans (work measured elsewhere, e.g. the KV prefetch
    that ran on another thread). A sealed trace ignores late appends
    (a head-started prefill finishing after the response is gone is
    counted in the orchestrator's counters, not in a trace that has
    already been read)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled", "name",
                 "started_at", "t0", "spans", "status", "attrs",
                 "_sealed", "duration_s", "seq")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], sampled: bool, name: str,
                 attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.name = name
        self.started_at = time.time()
        self.t0 = time.monotonic()
        self.spans: List[tuple] = []
        self.status = "ok"
        self.attrs = attrs or {}
        self._sealed = False
        self.duration_s = 0.0
        # per-ring monotonic sequence number, assigned when the trace
        # enters the recorder's ring (0 = never ringed) — the
        # /debug/traces since_seq cursor an incremental scraper pages on
        self.seq = 0

    # -- recording -------------------------------------------------------

    def add_span(self, name: str, start: Optional[float],
                 dur_s: float, kind: str = "phase", status: str = "ok",
                 attrs: Optional[dict] = None) -> None:
        if self._sealed:
            return
        self.spans.append((name, kind, start, dur_s, status, attrs))

    def add_phase(self, name: str, start: float, end: float,
                  status: str = "ok",
                  attrs: Optional[dict] = None) -> None:
        self.add_span(name, start, end - start, "phase", status, attrs)

    def add_event(self, name: str, start: Optional[float], dur_s: float,
                  status: str = "ok",
                  attrs: Optional[dict] = None) -> None:
        self.add_span(name, start, dur_s, "event", status, attrs)

    def child_traceparent(self) -> str:
        """Context the NEXT hop parents onto (this process's span)."""
        return format_traceparent(self.trace_id, self.span_id,
                                  self.sampled)

    def seal(self, status: str = "ok",
             end: Optional[float] = None) -> None:
        if self._sealed:
            return
        self.status = status
        self.duration_s = (end if end is not None
                           else time.monotonic()) - self.t0
        self._sealed = True

    # -- reads (off the hot path) ---------------------------------------

    def phase_totals(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name, kind, _start, dur, _status, _attrs in self.spans:
            if kind == "phase":
                out[name] = out.get(name, 0.0) + dur
        return out

    def unattributed_s(self) -> float:
        return max(0.0, self.duration_s
                   - sum(self.phase_totals().values()))

    def render(self) -> dict:
        """JSON-ready dict (the /debug/traces row)."""
        spans = []
        for name, kind, start, dur, status, attrs in self.spans:
            row = {
                "name": name,
                "kind": kind,
                "start_ms": (None if start is None
                             else round(1e3 * (start - self.t0), 3)),
                "duration_ms": round(1e3 * dur, 3),
                "status": status,
            }
            if attrs:
                row["attrs"] = attrs
            spans.append(row)
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "seq": self.seq,
            "name": self.name,
            "status": self.status,
            "started_at": round(self.started_at, 3),
            "duration_ms": round(1e3 * self.duration_s, 3),
            "unattributed_ms": round(1e3 * self.unattributed_s(), 3),
            "attrs": self.attrs,
            "spans": spans,
        }


# ---------------------------------------------------------------- histograms

class PhaseHistograms:
    """Plain-int phase-duration histograms, one series per label tuple.

    The hot path does one bisect + two adds per observation; the
    prometheus exposition reads the arrays at scrape time through
    ``PhaseHistogramCollector`` (a custom collector — recorder totals
    are rendered at scrape, the delta-sync idiom every other family in
    this stack uses, with zero prometheus objects near the hot loop).

    ``labelnames`` is usually ``("phase",)`` (engine) or
    ``("phase", "server")`` (router — per-endpoint series must be
    evictable when an endpoint leaves the fleet, see
    ``evict_except``)."""

    def __init__(self, labelnames: Sequence[str] = ("phase",),
                 buckets: Sequence[float] = PHASE_BUCKETS):
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets)
        # labels tuple -> [counts per bucket + overflow], sum, count
        self._series: Dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(self, *args: object) -> None:
        """``observe(label1, ..., dur_s)``. The lock is uncontended in
        practice (router: event loop only; engine: loop + writer +
        server threads at request granularity) — cheaper to hold for
        three increments than to defend lock-free float accumulation."""
        labels, dur = tuple(args[:-1]), float(args[-1])  # type: ignore
        idx = bisect_right(self.buckets, dur)
        with self._lock:
            series = self._series.get(labels)
            if series is None:
                series = self._series.setdefault(
                    labels, [[0] * (len(self.buckets) + 1), 0.0, 0])
            series[0][idx] += 1
            series[1] += dur
            series[2] += 1

    def snapshot(self) -> Dict[tuple, tuple]:
        """{labels: (cumulative bucket counts, sum, count)}."""
        out = {}
        with self._lock:
            items = list(self._series.items())
        for labels, (counts, total, n) in items:
            acc, cum = 0, []
            for c in counts:
                acc += c
                cum.append(acc)
            out[labels] = (tuple(cum), total, n)
        return out

    def evict_except(self, live: Iterable[str],
                     label_index: int = 1) -> int:
        """Drop series whose ``label_index`` label (the ``server``
        label) is not in ``live`` — per-endpoint phase series must not
        outlive the endpoint across dynamic-config swaps (the r8
        ``refresh_resilience`` precedent). Series with an empty label
        (router-local phases) are never evicted. Returns how many
        series were dropped."""
        live = set(live)
        with self._lock:
            dead = [labels for labels in self._series
                    if len(labels) > label_index
                    and labels[label_index]
                    and labels[label_index] not in live]
            for labels in dead:
                del self._series[labels]
        return len(dead)


class PhaseHistogramCollector:
    """prometheus_client custom collector over a ``PhaseHistograms``."""

    def __init__(self, name: str, documentation: str,
                 phases: PhaseHistograms):
        self.name = name
        self.documentation = documentation
        self.phases = phases

    def _family(self):
        from prometheus_client.core import HistogramMetricFamily
        return HistogramMetricFamily(self.name, self.documentation,
                                     labels=self.phases.labelnames)

    def describe(self):
        # registration must not trigger a collect; also feeds
        # registry._collector_to_names so the exposition-name checks in
        # tests/test_observability.py see the family
        return [self._family()]

    def collect(self):
        fam = self._family()
        for labels, (cum, total, _n) in self.phases.snapshot().items():
            buckets = [(str(b), c) for b, c in
                       zip(self.phases.buckets, cum)]
            buckets.append(("+Inf", cum[-1]))
            fam.add_metric(list(labels), buckets, sum_value=total)
        yield fam


# ---------------------------------------------------------------- recorder

class TraceRecorder:
    """Per-process recorder: mints/continues trace contexts, keeps the
    bounded ring of completed traces.

    ``sample_rate`` gates which traces enter the ring (phase histograms
    always record — they are aggregates, not exemplars). An inbound
    sampled flag wins in both directions so cross-process chains are
    complete-or-absent, never half-recorded."""

    def __init__(self, service: str, ring_entries: int = 2048,
                 sample_rate: float = 1.0):
        self.service = service
        self.sample_rate = max(0.0, min(1.0, sample_rate))
        self.ring: "collections.deque[RequestTrace]" = \
            collections.deque(maxlen=max(1, ring_entries))
        self.traces_started = 0
        self.traces_recorded = 0
        # last ring sequence number handed out: a scraper that read up
        # to seq N asks /debug/traces?since_seq=N next pass and never
        # re-reads (or misses, while its scrape interval outruns ring
        # rotation) a trace
        self.last_seq = 0
        self._rng = random.Random(os.urandom(8))

    def begin(self, traceparent: Optional[str] = None,
              name: str = "request",
              attrs: Optional[dict] = None) -> RequestTrace:
        self.traces_started += 1
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, parent_id, sampled = parsed
        else:
            trace_id, parent_id = new_trace_id(), None
            sampled = (self.sample_rate >= 1.0
                       or self._rng.random() < self.sample_rate)
        return RequestTrace(trace_id, new_span_id(), parent_id, sampled,
                            name, attrs)

    def finish(self, trace: RequestTrace, status: str = "ok") -> None:
        if trace._sealed:
            return                    # double-finish must not re-ring
        trace.seal(status)
        if trace.sampled:
            self.last_seq += 1
            trace.seq = self.last_seq
            self.ring.append(trace)
            self.traces_recorded += 1

    # -- reads ----------------------------------------------------------

    def snapshot(self, trace_id: Optional[str] = None,
                 slowest: Optional[int] = None,
                 limit: int = 100,
                 since_seq: Optional[int] = None) -> List[dict]:
        traces = list(self.ring)
        if since_seq is not None:
            # cursor read: only traces ringed after the caller's last
            # read; composes with the other filters (the ring is
            # append-ordered, so this is a suffix scan)
            traces = [t for t in traces if t.seq > since_seq]
        if trace_id:
            traces = [t for t in traces if t.trace_id == trace_id]
        if slowest:
            traces = sorted(traces, key=lambda t: t.duration_s,
                            reverse=True)[:slowest]
        else:
            traces = traces[-limit:]
        return [t.render() for t in traces]


def debug_traces_handler(get_recorder):
    """aiohttp handler factory for ``GET /debug/traces``.

    Query params: ``trace_id=<32 hex>`` (exact match), ``slowest=N``
    (N slowest in the ring), ``limit=N`` (most recent N, default 100),
    ``since_seq=N`` (only traces ringed after sequence number N — the
    incremental-scrape cursor; the response's ``last_seq`` is the next
    cursor value). ``get_recorder`` is a zero-arg callable so app
    wiring can late-bind."""
    from aiohttp import web

    async def handler(request: web.Request) -> web.Response:
        rec: TraceRecorder = get_recorder()

        def intq(key, default=None, floor=1):
            raw = request.query.get(key)
            if raw is None:
                return default
            try:
                return max(floor, int(raw))
            except ValueError:
                return default

        traces = rec.snapshot(
            trace_id=request.query.get("trace_id"),
            slowest=intq("slowest"),
            limit=intq("limit", 100) or 100,
            since_seq=intq("since_seq", None, floor=0))
        return web.json_response({
            "service": rec.service,
            "ring_entries": rec.ring.maxlen,
            "traces_started": rec.traces_started,
            "traces_recorded": rec.traces_recorded,
            "last_seq": rec.last_seq,
            "sample_rate": rec.sample_rate,
            "returned": len(traces),
            "traces": traces,
        })

    return handler
