"""In-process SLO engine: declarative objectives, multi-window burn
rates, and an alert state machine the serving loop itself evaluates.

Until now every invariant this stack enforces (r8 chaos, r9 overload,
r10 autoscale) lived in an *offline* loadgen exit code — the running
system never decided "this is unhealthy". This module closes that gap
with the Google SRE multi-window multi-burn-rate method:

- **SLO definitions** (``SLODef``) are declarative: per-class
  availability (non-5xx fraction), latency (TTFT / e2e under a
  threshold), shed-rate, and engine load-signal objectives. The
  default set (``default_config``) covers the classes the workload
  models drive (chat, rag) plus fleet-wide shed-rate and the r9 queue
  delay signal.
- **Good/bad accounting** is a bucketed sliding ring
  (``RollingCounts``): one append-or-increment per event on the hot
  path, window reads walk whole buckets (never individual events).
  Every read takes an injectable ``now`` — deterministic tests drive
  the clock explicitly, like the stats plane's ``_Window``.
- **Burn rate** = (bad fraction over a window) / (1 - objective):
  burn 1.0 spends the error budget exactly over the SLO period,
  burn 14.4 spends a 30-day budget in ~2 days. Each alert requires
  the burn to exceed its threshold over BOTH a short and a long
  window — the short window makes detection (and resolution) fast,
  the long window keeps one bad minute from paging.
- **Alert state machine**: inactive -> pending (condition holds) ->
  firing (held for ``for_s``) -> resolved (condition clear for
  ``resolve_s``) -> pending again on re-breach. Pending that clears
  before ``for_s`` flaps back to inactive without firing.
- **Window scale** (``window_scale``): one knob multiplies every
  window / hold duration so the fire-drill rig can run the REAL
  engine against seconds-long windows. Canonical labels ("5m", "1h")
  are kept so dashboards, the exposition, and the generated
  Prometheus rules agree on series names at any scale.

The same definitions compile (``compile_prometheus_rules``, via
``tools/gen_alert_rules.py``) to ``observability/alert-rules.yaml``
over the exported ``tpu:slo_burn_rate{slo,window}`` series — the
cluster alert and the in-process alert read the same accounting, so
they cannot drift (``tools/check_alert_rules.py`` enforces sync).

Closed loop: ``python -m production_stack_tpu.loadgen firedrill``
(docs/observability.md "SLOs and alerting"; per-alert diagnosis steps
in docs/runbooks.md).
"""

import collections
import json
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

# canonical burn-rate windows (label -> seconds at window_scale 1.0).
# The fast pair (5m short / 1h long) backs page alerts, the slow pair
# (30m short / 6h long) backs tickets — the SRE-workbook shape.
WINDOWS: Dict[str, float] = {
    "5m": 300.0,
    "30m": 1800.0,
    "1h": 3600.0,
    "6h": 21600.0,
}

# (severity, short window, long window, burn threshold, for_s,
#  resolve_s) — for_s/resolve_s are canonical seconds, scaled with the
# windows. Thresholds follow the SRE workbook's 30-day-budget table:
# 14.4x burns a month's budget in 2 days (wake a human), 6x in 5 days
# (file a ticket).
ALERT_PAIRS: Tuple[Tuple[str, str, str, float, float, float], ...] = (
    ("page", "5m", "1h", 14.4, 120.0, 60.0),
    ("ticket", "30m", "6h", 6.0, 300.0, 120.0),
)

# request classes: the `x-slo-class` header wins (the loadgen rigs and
# tiered clients set it); otherwise the endpoint path names the class
_PATH_CLASS = {
    "/v1/chat/completions": "chat",
    "/v1/completions": "completions",
    "/v1/embeddings": "embeddings",
    "/v1/rerank": "rerank",
    "/v2/rerank": "rerank",
    "/v1/score": "score",
}

CLASS_HEADER = "x-slo-class"

INACTIVE, PENDING, FIRING, RESOLVED = ("inactive", "pending", "firing",
                                       "resolved")
# /metrics encoding of the state machine (tpu:alert_state)
STATE_CODE = {INACTIVE: 0, RESOLVED: 0, PENDING: 1, FIRING: 2}


def classify_request(path: str, headers) -> str:
    """SLO class of one request: explicit header, else path family."""
    cls = headers.get(CLASS_HEADER) if headers is not None else None
    if cls:
        return str(cls)[:32]
    return _PATH_CLASS.get(path, "other")


# ---------------------------------------------------------------- defs

@dataclass
class SLODef:
    """One declarative objective.

    kind:
      availability — good = response below 500 and not truncated;
                     sheds (429/503 + Retry-After, deadline 504) are
                     EXCLUDED (intentional backpressure is the
                     shed_rate SLO's business, not an outage)
      latency      — good = ``metric`` ("ttft" | "e2e") of an OK
                     response <= ``threshold_s``
      shed_rate    — good = request admitted (not shed)
      signal       — good = an engine /load sample's ``metric``
                     ("est_queue_delay_ms") <= ``bound``
    ``request_class`` filters request-fed kinds (None = every class).
    """

    name: str
    kind: str
    objective: float
    request_class: Optional[str] = None
    metric: Optional[str] = None
    threshold_s: Optional[float] = None
    bound: Optional[float] = None
    description: str = ""

    def validate(self) -> "SLODef":
        if self.kind not in ("availability", "latency", "shed_rate",
                             "signal"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"SLO {self.name}: objective must be in "
                             f"(0, 1), got {self.objective}")
        if self.kind == "latency":
            if self.metric not in ("ttft", "e2e"):
                raise ValueError(f"SLO {self.name}: latency metric must "
                                 f"be 'ttft' or 'e2e'")
            if not self.threshold_s or self.threshold_s <= 0:
                raise ValueError(f"SLO {self.name}: latency needs a "
                                 f"positive threshold_s")
        if self.kind == "signal" and (self.bound is None
                                      or self.bound <= 0):
            raise ValueError(f"SLO {self.name}: signal needs a positive "
                             f"bound")
        return self

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective

    def to_json(self) -> dict:
        out = {"name": self.name, "kind": self.kind,
               "objective": self.objective}
        for k in ("request_class", "metric", "threshold_s", "bound",
                  "description"):
            v = getattr(self, k)
            if v not in (None, ""):
                out[k] = v
        return out


@dataclass
class SLOConfig:
    """The SLO set plus the evaluation knobs one engine instance runs.

    ``window_scale`` multiplies every window and hold duration
    (labels stay canonical); ``min_events`` is the volume floor BOTH
    windows of an alert must hold before its condition can be true —
    one bad event against an empty window must never page.
    """

    slos: List[SLODef] = field(default_factory=list)
    window_scale: float = 1.0
    min_events: int = 12

    def validate(self) -> "SLOConfig":
        if self.window_scale <= 0:
            raise ValueError("window_scale must be positive")
        seen = set()
        for slo in self.slos:
            slo.validate()
            if slo.name in seen:
                raise ValueError(f"duplicate SLO name {slo.name!r}")
            seen.add(slo.name)
        return self

    def window_s(self, label: str) -> float:
        return WINDOWS[label] * self.window_scale

    @property
    def horizon_s(self) -> float:
        return max(WINDOWS.values()) * self.window_scale

    @classmethod
    def from_json(cls, data: dict) -> "SLOConfig":
        slos = [SLODef(**s) for s in data.get("slos", [])]
        return cls(slos=slos,
                   window_scale=float(data.get("window_scale", 1.0)),
                   min_events=int(data.get("min_events", 12))).validate()

    @classmethod
    def from_file(cls, path: str) -> "SLOConfig":
        with open(path) as f:
            return cls.from_json(json.load(f))


def default_slos() -> List[SLODef]:
    return [
        SLODef("chat_availability", "availability", 0.99,
               request_class="chat",
               description="chat requests answered without a 5xx or a "
                           "truncated stream"),
        SLODef("rag_availability", "availability", 0.99,
               request_class="rag",
               description="rag requests answered without a 5xx or a "
                           "truncated stream"),
        SLODef("chat_ttft", "latency", 0.99, request_class="chat",
               metric="ttft", threshold_s=2.0,
               description="chat time-to-first-token under 2 s "
                           "(router-observed backend TTFB)"),
        SLODef("rag_e2e", "latency", 0.99, request_class="rag",
               metric="e2e", threshold_s=30.0,
               description="rag end-to-end latency under 30 s"),
        SLODef("shed_rate", "shed_rate", 0.99,
               description="requests admitted rather than shed "
                           "(429/503 + Retry-After, expired "
                           "deadlines) across every class"),
        SLODef("tier0_shed_rate", "shed_rate", 0.99,
               request_class="tier0",
               description="top-priority (tier0) requests admitted "
                           "rather than shed — with QoS tiers on "
                           "(router/qos.py), the one tier the "
                           "low-tier-first contract says must hold "
                           "under saturation"),
        SLODef("engine_queue_delay", "signal", 0.99,
               metric="est_queue_delay_ms", bound=5000.0,
               description="scraped engine /load queue-delay estimate "
                           "under 5 s"),
        SLODef("router_peer_lost", "signal", 0.99,
               metric="peer_age_s", bound=10.0,
               description="peer router replicas answering gossip "
                           "within 10 s (router/shared_state.py; "
                           "fed only once a peer has been seen, so "
                           "single-router deployments stay silent)"),
    ]


def default_config(window_scale: float = 1.0,
                   min_events: int = 12) -> SLOConfig:
    return SLOConfig(slos=default_slos(), window_scale=window_scale,
                     min_events=min_events).validate()


# ---------------------------------------------------------------- windows

class RollingCounts:
    """Bucketed sliding good/bad counters over ``horizon_s``.

    The hot path increments the newest bucket (appending a fresh one
    when the clock crossed a bucket boundary); window reads walk at
    most ``horizon_s / bucket_s`` buckets newest-first and stop at the
    window edge. A sample at time ``t`` counts toward a window ``W``
    read at ``now`` iff its bucket overlaps ``(now - W, now]`` — edge
    resolution is one bucket, which ``bucket_s`` sizes well inside the
    shortest window. ``now`` is injectable everywhere (tests drive a
    synthetic clock; ``0.0`` is a timestamp, not "not provided").
    """

    def __init__(self, horizon_s: float, bucket_s: Optional[float] = None):
        if bucket_s is None:
            # fine enough for the shortest canonical window at this
            # horizon's scale: 6h horizon -> 1.08 s buckets vs the 5 m
            # short window; a 0.005-scaled drill gets 54 ms buckets
            bucket_s = max(0.05, horizon_s / 20000.0)
        self.horizon = horizon_s
        self.bucket_s = bucket_s
        # each bucket: [start_ts, good, bad]
        self._buckets: collections.deque = collections.deque()

    def _bucket_start(self, now: float) -> float:
        return now - (now % self.bucket_s)

    def add(self, good: int, bad: int,
            now: Optional[float] = None) -> None:
        if now is None:
            now = time.time()
        start = self._bucket_start(now)
        if self._buckets and self._buckets[-1][0] == start:
            b = self._buckets[-1]
            b[1] += good
            b[2] += bad
        else:
            self._buckets.append([start, good, bad])
            self._trim(now)

    def _trim(self, now: float) -> None:
        cutoff = now - self.horizon - self.bucket_s
        while self._buckets and self._buckets[0][0] < cutoff:
            self._buckets.popleft()

    def counts(self, window_s: float,
               now: Optional[float] = None) -> Tuple[int, int]:
        """(good, bad) over the trailing ``window_s``."""
        if now is None:
            now = time.time()
        edge = now - window_s
        good = bad = 0
        for start, g, b in reversed(self._buckets):
            if start + self.bucket_s <= edge:
                break
            if start > now:        # clock moved backwards in a test
                continue
            good += g
            bad += b
        return good, bad


def burn_rate(good: int, bad: int, error_budget: float) -> float:
    """Bad fraction over the window divided by the error budget.
    An empty window burns nothing (there is no traffic to be bad)."""
    total = good + bad
    if total <= 0 or bad <= 0:
        return 0.0
    return (bad / total) / error_budget


# ---------------------------------------------------------------- alerts

@dataclass
class AlertRule:
    """One multi-window burn-rate alert over one SLO (scaled seconds)."""

    name: str
    slo: str
    severity: str
    short_window: str
    long_window: str
    burn_threshold: float
    for_s: float
    resolve_s: float

    def runbook(self) -> str:
        return f"docs/runbooks.md#{self.name}"


class AlertState:
    """The pending -> firing -> resolved machine for one rule.

    ``evaluate(condition, now)`` is the only transition point; it is
    idempotent for a constant condition at a constant clock. A pending
    alert whose condition clears before ``for_s`` flaps back to
    inactive without firing; a firing alert resolves only after the
    condition has stayed clear for ``resolve_s`` (so a flapping burn
    cannot resolve-and-refire every tick); a resolved alert re-enters
    pending on the next breach.
    """

    def __init__(self, rule: AlertRule):
        self.rule = rule
        self.state = INACTIVE
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.fired_total = 0

    def evaluate(self, condition: bool, now: float) -> str:
        if condition:
            self.clear_since = None
            if self.state in (INACTIVE, RESOLVED):
                self.state = PENDING
                self.pending_since = now
            if self.state == PENDING and \
                    now - self.pending_since >= self.rule.for_s:
                self.state = FIRING
                self.firing_since = now
                self.fired_total += 1
                logger.warning("SLO alert FIRING: %s (burn > %.1fx over "
                               "%s and %s)", self.rule.name,
                               self.rule.burn_threshold,
                               self.rule.short_window,
                               self.rule.long_window)
        else:
            if self.state == PENDING:       # flap: never fired
                self.state = INACTIVE
                self.pending_since = None
            elif self.state == FIRING:
                if self.clear_since is None:
                    self.clear_since = now
                elif now - self.clear_since >= self.rule.resolve_s:
                    self.state = RESOLVED
                    self.resolved_at = now
                    self.firing_since = None
                    self.clear_since = None
                    logger.info("SLO alert resolved: %s", self.rule.name)
        return self.state

    def to_json(self) -> dict:
        r = self.rule
        return {
            "name": r.name, "slo": r.slo, "severity": r.severity,
            "state": self.state,
            "short_window": r.short_window, "long_window": r.long_window,
            "burn_threshold": r.burn_threshold,
            "for_s": r.for_s, "resolve_s": r.resolve_s,
            "pending_since": self.pending_since,
            "firing_since": self.firing_since,
            "resolved_at": self.resolved_at,
            "fired_total": self.fired_total,
            "runbook": r.runbook(),
        }


def build_alert_rules(config: SLOConfig) -> List[AlertRule]:
    """Two rules (page + ticket) per SLO, durations scaled."""
    s = config.window_scale
    rules = []
    for slo in config.slos:
        for severity, short, long_, thr, for_s, resolve_s in ALERT_PAIRS:
            rules.append(AlertRule(
                name=f"{slo.name}_{severity}", slo=slo.name,
                severity=severity, short_window=short, long_window=long_,
                burn_threshold=thr, for_s=for_s * s,
                resolve_s=resolve_s * s))
    return rules


# ---------------------------------------------------------------- engine

class SLOEngine:
    """Good/bad accounting + burn evaluation + alert states, one per
    router process.

    Request-path cost is a handful of bucket increments
    (``observe_response``); everything windowed happens in
    ``evaluate()``, which the router runs on a short interval task and
    every ``/alerts`` / ``/metrics`` read refreshes too.
    """

    def __init__(self, config: Optional[SLOConfig] = None):
        self.config = (config or default_config()).validate()
        self._counts: Dict[str, RollingCounts] = {
            slo.name: RollingCounts(self.config.horizon_s)
            for slo in self.config.slos}
        self.alerts: Dict[str, AlertState] = {
            r.name: AlertState(r) for r in build_alert_rules(self.config)}
        self._by_class: Dict[Tuple[str, str], List[SLODef]] = {}
        for slo in self.config.slos:
            if slo.kind == "signal":
                continue
            self._by_class.setdefault((slo.kind, slo.request_class or ""),
                                      []).append(slo)
        # (kind, cls) -> resolved SLO tuple, memoized per observed
        # class: the hot path must not rebuild lists per request
        self._resolved: Dict[Tuple[str, str], tuple] = {}
        self._signal_slos = [s for s in self.config.slos
                             if s.kind == "signal"]
        # last /load sample timestamp ingested per engine URL, so an
        # interval-old scrape read every eval tick counts once
        self._last_scrape: Dict[str, float] = {}
        # last evaluate() burn/volume maps: {slo: {window: value}}
        self.burns: Dict[str, Dict[str, float]] = {}
        self.volumes: Dict[str, Dict[str, int]] = {}
        self._last_eval = float("-inf")

    # -- feeding (hot path) ---------------------------------------------

    def _class_slos(self, kind: str, cls: str) -> tuple:
        key = (kind, cls)
        resolved = self._resolved.get(key)
        if resolved is None:
            out = list(self._by_class.get(key, ()))
            if cls:  # class-agnostic SLOs see every class exactly once
                out += self._by_class.get((kind, ""), ())
            resolved = tuple(out)
            # the class comes off a client header: bound the memo so
            # junk classes cannot grow it without limit
            if len(self._resolved) < 256:
                self._resolved[key] = resolved
        return resolved

    def observe_response(self, path: str, req_headers, status: int,
                         resp_headers, *,
                         ttft_s: Optional[float] = None,
                         e2e_s: Optional[float] = None,
                         truncated: bool = False,
                         now: Optional[float] = None,
                         cls: Optional[str] = None) -> None:
        """One finished (or shed) proxied request.

        Shed detection reads the response itself — 429/503 with
        ``Retry-After`` (the router's and the relayed engine's shed
        shape) or the 504 deadline marker — so the caller does not
        thread shed flags through every return path. ``cls`` overrides
        classification (the proxy passes the QoS tier name for tiered
        traffic so per-tier objectives like tier0_shed_rate see it).
        """
        if now is None:
            now = time.time()       # one clock read for every bucket add
        if cls is None:
            cls = classify_request(path, req_headers)
        shed = ((status in (429, 503)
                 and resp_headers is not None
                 and "Retry-After" in resp_headers)
                or (status == 504 and resp_headers is not None
                    and "x-deadline-expired" in resp_headers))
        for slo in self._class_slos("shed_rate", cls):
            self._counts[slo.name].add(0 if shed else 1,
                                       1 if shed else 0, now)
        if shed:
            return      # intentional backpressure: not an availability
        ok = status < 500 and not truncated
        for slo in self._class_slos("availability", cls):
            self._counts[slo.name].add(1 if ok else 0,
                                       0 if ok else 1, now)
        if not ok or status >= 400:
            return      # failed requests have no latency to judge
        for slo in self._class_slos("latency", cls):
            value = ttft_s if slo.metric == "ttft" else e2e_s
            if value is None:
                continue
            good = value <= slo.threshold_s
            self._counts[slo.name].add(1 if good else 0,
                                       0 if good else 1, now)

    def ingest_engine_loads(self, stats: Dict[str, object],
                            now: Optional[float] = None) -> int:
        """Feed signal SLOs from a scraper snapshot ({url: record with
        ``est_queue_delay_ms`` + ``scraped_at``}). Each (url, scrape)
        sample counts once no matter how often the snapshot is read.
        Returns how many fresh samples were ingested."""
        if not self._signal_slos:
            return 0
        if now is None:
            now = time.time()
        fresh = 0
        for url, rec in stats.items():
            at = getattr(rec, "scraped_at", 0.0)
            if self._last_scrape.get(url) == at:
                continue
            self._last_scrape[url] = at
            fresh += 1
            for slo in self._signal_slos:
                # a record only feeds the signal SLOs whose metric it
                # actually carries: engine /load records have
                # est_queue_delay_ms but no peer_age_s, peer gossip
                # records (shared_state.signal_records) the reverse —
                # defaulting the absent one to 0.0 would pad the other
                # family's volume with vacuous good samples
                raw = getattr(rec, slo.metric, None)
                if raw is None:
                    continue
                value = float(raw)
                good = value <= slo.bound
                self._counts[slo.name].add(1 if good else 0,
                                           0 if good else 1, now)
        for gone in set(self._last_scrape) - set(stats):
            del self._last_scrape[gone]
        return fresh

    # -- evaluation ------------------------------------------------------

    def window_counts(self, slo_name: str, label: str,
                      now: Optional[float] = None) -> Tuple[int, int]:
        if now is None:
            now = time.time()
        return self._counts[slo_name].counts(self.config.window_s(label),
                                             now)

    def burn(self, slo: SLODef, label: str, now: float) -> float:
        good, bad = self.window_counts(slo.name, label, now)
        return burn_rate(good, bad, slo.error_budget)

    def evaluate(self, now: Optional[float] = None,
                 max_age_s: float = 0.0) -> List[str]:
        """Recompute every burn, step every alert; returns the firing
        alert names. ``max_age_s`` serves the cached result when the
        last full evaluation is at least that fresh — the eval task
        already recomputes every interval, so probes/scrapes/pollers
        stacked on top need not each walk every window again."""
        if now is None:
            now = time.time()
        if max_age_s > 0 and now - self._last_eval < max_age_s:
            return self.firing()
        self._last_eval = now
        slos = {s.name: s for s in self.config.slos}
        burns: Dict[str, Dict[str, float]] = {}
        volumes: Dict[str, Dict[str, int]] = {}
        for slo in self.config.slos:
            burns[slo.name] = {}
            volumes[slo.name] = {}
            for label in WINDOWS:
                good, bad = self.window_counts(slo.name, label, now)
                burns[slo.name][label] = burn_rate(good, bad,
                                                   slo.error_budget)
                volumes[slo.name][label] = good + bad
        self.burns = burns
        self.volumes = volumes
        firing = []
        floor = self.config.min_events
        for alert in self.alerts.values():
            r = alert.rule
            slo = slos[r.slo]
            cond = (volumes[r.slo][r.short_window] >= floor
                    and volumes[r.slo][r.long_window] >= floor
                    and burns[r.slo][r.short_window] > r.burn_threshold
                    and burns[r.slo][r.long_window] > r.burn_threshold)
            if alert.evaluate(cond, now) == FIRING:
                firing.append(r.name)
        return firing

    def firing(self) -> List[str]:
        return sorted(name for name, a in self.alerts.items()
                      if a.state == FIRING)

    def fired_totals(self) -> Dict[str, int]:
        return {name: a.fired_total for name, a in self.alerts.items()}

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The GET /alerts payload (evaluates first, so a poll always
        reads current states)."""
        if now is None:
            now = time.time()
        self.evaluate(now)
        slo_rows = []
        for slo in self.config.slos:
            windows = {}
            for label in WINDOWS:
                good, bad = self.window_counts(slo.name, label, now)
                windows[label] = {
                    "good": good, "bad": bad,
                    "burn_rate": round(
                        self.burns[slo.name][label], 4),
                }
            slo_rows.append({**slo.to_json(), "windows": windows})
        return {
            "window_scale": self.config.window_scale,
            "min_events": self.config.min_events,
            "windows_s": {lbl: self.config.window_s(lbl)
                          for lbl in WINDOWS},
            "slos": slo_rows,
            "alerts": [a.to_json() for a in self.alerts.values()],
            "firing": self.firing(),
        }


# ---------------------------------------------------------------- task

class SLOTask:
    """The router's evaluation loop: step alert states and pull fresh
    engine /load samples into the signal SLOs on a short interval
    (asyncio task, the StatLogger ownership idiom)."""

    def __init__(self, engine: SLOEngine,
                 scraper_get: Optional[Callable[[], Dict]] = None,
                 interval_s: float = 1.0,
                 peers_get: Optional[Callable[[], Dict]] = None):
        self.engine = engine
        self.scraper_get = scraper_get
        # peer-router gossip freshness (shared_state.signal_records)
        # rides the same signal path as engine /load samples
        self.peers_get = peers_get
        self.interval_s = interval_s
        self._task = None

    async def start(self) -> None:
        import asyncio
        self._task = asyncio.create_task(self._loop(), name="slo-eval")

    async def close(self) -> None:
        import asyncio
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def healthy(self) -> bool:
        return self._task is not None and not self._task.done()

    async def _loop(self) -> None:
        import asyncio
        while True:
            try:
                self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("SLO evaluation failed")
            await asyncio.sleep(self.interval_s)

    def tick(self) -> List[str]:
        stats: Dict = {}
        if self.scraper_get is not None:
            stats.update(self.scraper_get())
        if self.peers_get is not None:
            # one merged ingest: the per-(url, scrape) dedup evicts
            # urls absent from the snapshot, so feeding engine and
            # peer records in separate calls would evict each other's
            # dedup stamps every tick
            stats.update(self.peers_get())
        if stats or self.scraper_get is not None \
                or self.peers_get is not None:
            self.engine.ingest_engine_loads(stats)
        return self.engine.evaluate()


# ---------------------------------------------------------------- rules

def compile_prometheus_rules(config: Optional[SLOConfig] = None) -> dict:
    """The cluster-side mirror of the in-process alerts: Prometheus
    alerting rules over the exported ``tpu:slo_burn_rate{slo,window}``
    series. Always compiled at canonical (scale-1) durations — the
    window_scale knob exists for drills, not production rules.
    ``tools/gen_alert_rules.py`` writes this to
    ``observability/alert-rules.yaml``; ``tools/check_alert_rules.py``
    fails CI when the committed file drifts from this compilation."""
    config = config or default_config()
    slos = {s.name: s for s in config.slos}
    floor = config.min_events
    rules = []
    for r in build_alert_rules(SLOConfig(slos=config.slos)):
        slo = slos[r.slo]

        def series(window: str) -> str:
            return (f'max(tpu:slo_burn_rate{{slo="{r.slo}",'
                    f'window="{window}"}})')

        def volume(window: str) -> str:
            return (f'max(tpu:slo_window_events{{slo="{r.slo}",'
                    f'window="{window}"}})')

        # the volume floor mirrors the in-process min_events gate —
        # without it, one bad request against an empty window would
        # page the cluster while the in-process alert stays silent
        rules.append({
            "alert": r.name,
            "expr": (f"{series(r.short_window)} > {r.burn_threshold}\n"
                     f"and\n"
                     f"{series(r.long_window)} > {r.burn_threshold}\n"
                     f"and\n"
                     f"{volume(r.short_window)} >= {floor}\n"
                     f"and\n"
                     f"{volume(r.long_window)} >= {floor}"),
            "for": f"{int(r.for_s)}s",
            "labels": {"severity": r.severity, "slo": r.slo},
            "annotations": {
                "summary": (f"{r.slo} burning error budget at >"
                            f"{r.burn_threshold}x over {r.short_window} "
                            f"and {r.long_window}"),
                "description": slo.description or slo.name,
                "runbook": r.runbook(),
            },
        })
    # static (non-burn-rate) rules ride in their own group: symptoms
    # with a dedicated control loop rather than an error budget
    kvplane_rules = [{
        # fragmentation, not exhaustion: admissions failing while the
        # pool still holds free blocks. With the kvplane planner
        # deployed this should self-heal within a poll interval — a
        # firing alert means the planner is down, cooldown-pinned, or
        # the fleet has no destination with headroom.
        "alert": "KVPoolFragmented",
        "expr": ('sum by (model_name) (rate(\n'
                 '  tpu:kvpool_alloc_failures_total{reason="fragmented"}'
                 '[5m]\n)) > 0\n'
                 'and\n'
                 'sum by (model_name) '
                 '(tpu:kvpool_blocks{state="free"}) > 0'),
        "for": "120s",
        "labels": {"severity": "ticket", "component": "kvplane"},
        "annotations": {
            "summary": ("KV pool refusing admissions while free "
                        "blocks exist — fragmented, not exhausted"),
            "description": ("alloc failures with reason=fragmented "
                            "rising while the pool reports free "
                            "capacity; live migration / defrag is "
                            "not reclaiming it"),
            "runbook": "docs/runbooks.md#kv-fragmentation",
        },
    }]
    autoscaler_rules = [{
        # the remediation loop's own failure is an alert, not a log
        # line: an executed runbook that did not resolve its alert
        # (or failed mid-way) means the pilot is actuating on the
        # fleet without fixing it — a human must take the incident
        # over before the rate limit resets and it tries again
        "alert": "RemediationFailing",
        "expr": ('sum by (action) (increase(\n'
                 '  tpu:autoscaler_remediations_total'
                 '{outcome=~"failed|unresolved"}[30m]\n)) > 0'),
        "for": "60s",
        "labels": {"severity": "ticket", "component": "autoscaler"},
        "annotations": {
            "summary": ("auto-remediation executed but did not "
                        "resolve its alert (or failed mid-runbook)"),
            "description": ("remediations with outcome failed/"
                            "unresolved in the last 30m; the bounded "
                            "policy rate-limits retries, so the "
                            "incident is now a human's"),
            "runbook": "docs/runbooks.md#auto-remediation",
        },
    }]
    tenancy_rules = [{
        # tenant-bucket sheds are the containment WORKING, not failing
        # — the router refuses one tenant's overflow so its tier peers
        # keep their goodput (router/qos.py). The alert exists because
        # a tenant shedding for this long has outgrown its flat
        # --qos-tenant-rate (or is misbehaving), and either way the
        # conversation is with an account, not a pager storm: ticket.
        "alert": "NoisyTenantShedding",
        "expr": ('sum by (tenant, tier) (rate(\n'
                 '  tpu:router_tenant_sheds_total[10m]\n)) > 1'),
        "for": "600s",
        "labels": {"severity": "ticket", "component": "router"},
        "annotations": {
            "summary": ("tenant {{ $labels.tenant }} shedding on its "
                        "per-tenant budget in tier {{ $labels.tier }} "
                        "for 10m+"),
            "description": ("sustained tenant-bucket sheds: the noisy-"
                            "neighbor containment is holding (peers "
                            "are protected) but this tenant's traffic "
                            "has outgrown its rate"),
            "runbook": "docs/runbooks.md#noisy-neighbor",
        },
    }]
    return {"groups": [{"name": "tpu-stack-slo-burn", "rules": rules},
                       {"name": "tpu-stack-kvplane",
                        "rules": kvplane_rules},
                       {"name": "tpu-stack-autoscaler",
                        "rules": autoscaler_rules},
                       {"name": "tpu-stack-tenancy",
                        "rules": tenancy_rules}]}
