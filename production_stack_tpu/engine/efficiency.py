"""Engine efficiency accounting: hardware-level attribution for the
step loop (roofline accounting, compile tracking, window waste).

The aggregate loops this stack has closed measure *requests*; r13's
tracing attributes *request* wall time to phases. What neither says is
where the **device's** time and bandwidth go: a fused decode window
always computes ``max_num_seqs x decode_window`` token positions, but
only the live, still-generating rows' positions are useful — parked
slots (padding rows), finished rows' tails, and rejected speculative
drafts burn the same HBM traffic and emit nothing. This module is the
measure-before-optimize substrate for the roofline push (ROADMAP item
2) and the fragmentation work (item 3): every decode window and prefill
dispatch is classified into real / pad / dead token-steps, rolled into
effective-bandwidth and MBU estimates against the configured HBM peak,
and every XLA compile is stamped (kind, window, kv bucket, duration) so
a compile-stalled serving window is attributable instead of invisible.

Design constraints (the r13 rules, verbatim):

- **Hot-loop cost ~zero.** The engine calls ``note_window`` once per
  fused window and ``note_prefill`` once per prefill bucket group —
  plain-int adds and one bounded-ring append per *window* (never per
  token), under a lock that is only ever held for those adds (never
  across a compile or dispatch). No prometheus objects anywhere near
  the loop: the exposition reads totals at scrape time and advances
  counters by deltas (``EngineMetrics.sync_eff``).
- **Bounded.** Window breakdowns and compile events live in
  ``collections.deque(maxlen=...)`` rings served on ``GET /debug/perf``.
- **Lock-free-ish reads.** ``perf_block()`` (the ``/load`` ``perf``
  block) must answer while the engine lock is held across a
  multi-second compile — it takes only this module's micro-lock.

The byte model is deliberately simple and documented (docs/engine.md
"Efficiency telemetry"): one decode step streams the full weight set
once plus, for every batch row, the KV prefix up to the window's kv
bucket. Effective bytes are total bytes scaled by the window's live
fraction; MBU is effective bytes/s over the configured
``hbm_peak_gbps``. On CPU hosts the absolute numbers are meaningless
but the *fractions* (live/pad/dead) are exact.
"""

import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# XLA compile durations (seconds): compiles are seconds-scale events,
# not milliseconds — a distinct bucket ladder from PHASE_BUCKETS
COMPILE_BUCKETS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

# KV-pool occupancy observed at allocation time (fraction of non-trash
# blocks held by live sequences)
OCCUPANCY_BUCKETS: Tuple[float, ...] = (
    0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)


class EngineEffAccounting:
    """Plain-int efficiency totals + bounded rings.

    ``kv_position_bytes`` is the HBM bytes one cache position costs one
    attention read (2 x layers x kv-heads x head-dim x itemsize, plus
    scales for the int8 cache); ``weight_bytes`` the full parameter
    set. ``compile_hist`` is an optional PhaseHistograms with labels
    ``(kind, window, kv_bucket)`` fed at compile completion (the
    metrics layer owns it so the family is registered standalone).

    ``now_fn`` is injectable for deterministic tests; ``wall_fn``
    (wall clock) stamps ring entries with an ``at_unix`` timestamp so
    an external reader — the obsplane flight recorder — can align
    engine windows/compiles with trace spans and other processes'
    rings without sharing this process's monotonic epoch.
    """

    def __init__(self, *, weight_bytes: int = 0,
                 kv_position_bytes: int = 0,
                 hbm_peak_bytes_per_s: float = 0.0,
                 ring_entries: int = 256,
                 compile_hist=None,
                 now_fn: Callable[[], float] = time.monotonic,
                 wall_fn: Callable[[], float] = time.time):
        self.weight_bytes = int(weight_bytes)
        self.kv_position_bytes = int(kv_position_bytes)
        self.hbm_peak_bytes_per_s = float(hbm_peak_bytes_per_s)
        self.compile_hist = compile_hist
        self._now = now_fn
        self._wall = wall_fn
        self._started_at = now_fn()
        # decode-window token-step classification (cumulative ints).
        # token_steps_total accumulates batch*steps*positions in a
        # separate adder from the kind counters. NOTE the engine
        # derives `dead` by subtraction, so for the real engine the
        # effwatch sum-to-1 gate is a *plumbing* check (every adder,
        # the /load serialization, the scrape deltas — and it is
        # falsifiable, via the fake's skew knob), not a
        # classification proof; classification truth is held by the
        # client-reconciliation gate (real vs tokens received) and
        # the unit tests.
        self.decode_real = 0
        self.decode_pad = 0
        self.decode_dead = 0
        self.decode_token_steps_total = 0
        self.decode_windows = 0
        self.decode_busy_s = 0.0
        # prefill bucket-padding waste (idle rows + right padding)
        self.prefill_real = 0
        self.prefill_pad = 0
        self.prefill_dispatches = 0
        # modeled HBM traffic (decode windows only — see module doc)
        self.bytes_total = 0
        self.bytes_effective = 0
        # XLA compile tracking:
        # (kind, window, kv, batch) -> [count, total_s]
        self.compiles: Dict[Tuple[str, int, int, int], List] = {}
        self.compiles_total = 0
        self.compile_s_total = 0.0
        self.compile_in_flight = 0
        self.last_compile_at: Optional[float] = None
        self._windows: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, ring_entries))
        # (start_mono, dur_s, kind, window, kv, batch, start_unix)
        self._compile_events: "collections.deque[tuple]" = \
            collections.deque(maxlen=128)
        self._lock = threading.Lock()

    # -- step-loop writes ------------------------------------------------

    def note_window(self, *, steps: int, positions: int, batch: int,
                    live_rows: int, kv_len: int, real: int, pad: int,
                    dead: int, window_s: float) -> None:
        """One fused decode window: ``batch * steps * positions``
        token-step computations, of which ``real`` emitted tokens the
        client keeps, ``pad`` ran on parked rows, and ``dead`` ran on
        finished rows' tails / discarded rows / rejected draft
        positions."""
        total = batch * steps * positions
        useful = real / total if total else 0.0
        win_bytes = steps * (self.weight_bytes
                             + batch * self.kv_position_bytes * kv_len)
        eff_bytes = int(win_bytes * useful)
        entry = {
            "at": self._now(),
            "at_unix": round(self._wall(), 4),
            "steps": steps,
            "positions": positions,
            "batch": batch,
            "live_rows": live_rows,
            "kv_len": kv_len,
            "real": real,
            "pad": pad,
            "dead": dead,
            "window_s": round(window_s, 6),
            "bytes": win_bytes,
            "effective_bytes": eff_bytes,
        }
        with self._lock:
            self.decode_real += real
            self.decode_pad += pad
            self.decode_dead += dead
            self.decode_token_steps_total += total
            self.decode_windows += 1
            self.decode_busy_s += window_s
            self.bytes_total += win_bytes
            self.bytes_effective += eff_bytes
            self._windows.append(entry)

    def note_prefill(self, *, bucket: int, batch: int,
                     real_tokens: int) -> None:
        """One prefill bucket group: ``batch * bucket`` token positions
        were computed; ``real_tokens`` were actual prompt-chunk tokens,
        the rest bucket right-padding and idle parked rows."""
        total = batch * bucket
        with self._lock:
            self.prefill_real += real_tokens
            self.prefill_pad += max(0, total - real_tokens)
            self.prefill_dispatches += 1

    # -- compile observer (ModelRunner hook) -----------------------------

    def compile_started(self, kind: str, window: int, kv_len: int,
                        batch: int = 0) -> None:
        with self._lock:
            self.compile_in_flight += 1

    def compile_finished(self, kind: str, window: int, kv_len: int,
                         started_at: float, dur_s: float,
                         batch: int = 0) -> None:
        key = (kind, int(window), int(kv_len), int(batch))
        with self._lock:
            self.compile_in_flight = max(0, self.compile_in_flight - 1)
            slot = self.compiles.setdefault(key, [0, 0.0])
            slot[0] += 1
            slot[1] += dur_s
            self.compiles_total += 1
            self.compile_s_total += dur_s
            self.last_compile_at = started_at + dur_s
            # wall-clock stamp of the compile START (this call runs at
            # compile END, so subtract the duration)
            self._compile_events.append(
                (started_at, dur_s, kind, int(window), int(kv_len),
                 int(batch), round(self._wall() - dur_s, 4)))
        if self.compile_hist is not None:
            self.compile_hist.observe(kind, str(window), str(kv_len),
                                      dur_s)

    # -- reads (off the hot path) ----------------------------------------

    def report(self) -> Dict[str, object]:
        """Cumulative totals (the scrape-time delta-sync source)."""
        with self._lock:
            return {
                "decode": {"real": self.decode_real,
                           "pad": self.decode_pad,
                           "dead": self.decode_dead,
                           "token_steps_total":
                               self.decode_token_steps_total,
                           "windows": self.decode_windows,
                           "busy_s": round(self.decode_busy_s, 4)},
                "prefill": {"real": self.prefill_real,
                            "pad": self.prefill_pad,
                            "dispatches": self.prefill_dispatches},
                "bytes_total": self.bytes_total,
                "bytes_effective": self.bytes_effective,
                "compiles_total": self.compiles_total,
                "compile_s_total": round(self.compile_s_total, 4),
                "compile_in_flight": self.compile_in_flight,
                "compiles": {f"{k}|{w}|{kv}|{b}":
                             {"count": c[0],
                              "seconds": round(c[1], 4)}
                             for (k, w, kv, b), c in
                             self.compiles.items()},
                "weight_bytes": self.weight_bytes,
                "kv_position_bytes": self.kv_position_bytes,
                "hbm_peak_bytes_per_s": self.hbm_peak_bytes_per_s,
            }

    def rates(self, horizon_s: float = 10.0,
              now: Optional[float] = None) -> Dict[str, float]:
        """Ring-derived recent rates: effective/total bytes per
        wall-clock second over the last ``horizon_s`` (idle time counts
        against the rate — this is what a roofline comparison wants),
        MBU against the configured peak, and the recent live
        fraction.

        The divisor is clamped to what the ring can actually witness:
        uptime when younger than the horizon, and — on a busy engine
        whose ring evicts entries faster than the horizon drains —
        the age of the oldest resident entry. Without the clamp a
        full ring would sum only its resident windows while dividing
        by the whole horizon, understating every rate by the eviction
        ratio."""
        if now is None:
            now = self._now()
        window = min(horizon_s, max(1e-9, now - self._started_at))
        eff = tot = real = pad = dead = 0
        with self._lock:
            if (self._windows
                    and len(self._windows) == self._windows.maxlen):
                oldest = self._windows[0]["at"]
                window = min(window, max(1e-9, now - oldest))
            cutoff = now - window
            for e in self._windows:
                if e["at"] >= cutoff:
                    eff += e["effective_bytes"]
                    tot += e["bytes"]
                    real += e["real"]
                    pad += e["pad"]
                    dead += e["dead"]
        all_steps = real + pad + dead
        eff_rate = eff / window
        return {
            "horizon_s": round(window, 3),
            "effective_bytes_per_s": round(eff_rate, 1),
            "total_bytes_per_s": round(tot / window, 1),
            "mbu_perc": round(100.0 * eff_rate
                              / self.hbm_peak_bytes_per_s, 4)
            if self.hbm_peak_bytes_per_s > 0 else 0.0,
            "live_fraction": round(real / all_steps, 6)
            if all_steps else 0.0,
            "decode_tokens_per_s": round(real / window, 3),
        }

    def perf_block(self, horizon_s: float = 10.0) -> Dict[str, object]:
        """The ``/load`` ``perf`` block: totals + recent rates, cheap
        and engine-lock-free (signals.EngineLoad parses this)."""
        r = self.report()
        out = {
            "token_steps": r["decode"],
            "prefill_tokens": r["prefill"],
            "bytes_total": r["bytes_total"],
            "bytes_effective": r["bytes_effective"],
            "compiles_total": r["compiles_total"],
            "compile_s_total": r["compile_s_total"],
            "compile_in_flight": r["compile_in_flight"],
            "weight_bytes": r["weight_bytes"],
        }
        out.update(self.rates(horizon_s))
        return out

    def recent_windows(self, limit: int = 50) -> List[dict]:
        with self._lock:
            return list(self._windows)[-max(1, limit):]

    def recent_compiles(self, limit: int = 50) -> List[dict]:
        with self._lock:
            events = list(self._compile_events)[-max(1, limit):]
        return [{"at": round(t, 4), "at_unix": wall,
                 "duration_s": round(d, 4),
                 "kind": k, "window": w, "kv_bucket": kv, "batch": b}
                for t, d, k, w, kv, b, wall in events]

    def compile_events_between(self, t0: float, t1: float
                               ) -> List[Tuple[float, float, str, int,
                                               int, int]]:
        """Compile events overlapping the monotonic interval
        ``[t0, t1]`` — the trace seal hook that makes a compile-stalled
        request visible in ``/debug/traces``. Rows are
        ``(start_mono, dur_s, kind, window, kv, batch)`` — the ring's
        wall-clock stamp is an exporter concern, not a span one."""
        with self._lock:
            events = list(self._compile_events)
        return [e[:6] for e in events
                if e[0] < t1 and e[0] + e[1] > t0]
