"""Host-side allocator + prefix cache for the paged KV pool.

Pure bookkeeping over the block pool in models/kv.py — never touches the
device. Called only under the engine lock (admission, decode-window
extension, finish/abort), so it needs no locking of its own.

Prefix caching here is block *sharing*: a finished sequence's full
blocks stay in the pool, registered under chain hashes of their token
content (kvcache/chunks.ChunkHasher — chunk i's key digests chunk i's
tokens AND chunk i-1's key, so equal keys imply an identical full
prefix). A new prompt that matches a chain of registered blocks simply
points its block table at them (refcount++), paying zero copies and
zero HBM — the reference's --enable-prefix-caching semantics
(reference: helm/templates/deployment-vllm-multi.yaml:73-75) the way
vLLM's own paged KV implements them, rebuilt for the static-shape TPU
pool. This replaces the earlier HBMPrefixPool, which kept a separate
pool buffer and *copied* matched prefixes into slots (doubling resident
bytes for hot prefixes).

Invariants:
- Block 0 (trash) is never allocated.
- A sequence writes only into blocks it exclusively owns: matching is
  capped so shared blocks are always fully-written full blocks, and a
  prompt always recomputes at least its final position (a sampled
  token needs live logits).
- Registered blocks with refcount 0 sit in an LRU; allocation prefers
  the free list and evicts LRU-registered blocks only when it is empty.
"""

import collections
from typing import Dict, List, Optional, Sequence, Tuple

from production_stack_tpu.kvcache.chunks import ChunkHasher
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class BlockManager:
    def __init__(self, num_blocks: int, block_size: int,
                 enable_prefix_caching: bool = False,
                 namespace: str = ""):
        if num_blocks < 2:
            raise ValueError("pool needs at least one non-trash block")
        self.num_blocks = num_blocks          # includes trash block 0
        self.block_size = block_size
        self.hasher = (ChunkHasher(block_size, namespace="blk|" + namespace)
                       if enable_prefix_caching else None)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}        # block -> refcount (>= 1)
        self._by_key: Dict[bytes, int] = {}   # chain key -> block
        self._key_of: Dict[int, bytes] = {}   # block -> chain key
        # registered blocks with refcount 0, insertion order = LRU
        self._evictable: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        # fragmentation telemetry (plain ints — read at scrape time and
        # on /debug/perf; docs/observability.md "Engine efficiency"):
        # allocation failures split by WHY the pool refused. A request
        # arriving at a pool with zero allocatable blocks hit true
        # exhaustion; one refused while allocatable blocks remain
        # (just fewer than it needs) hit the fragmentation regime —
        # free capacity exists but is insufficient for this request,
        # the admission-failure class fleet-level migration/defrag
        # (the kvplane) exists to erase.
        self.allocs = 0
        self.blocks_allocated = 0
        self.alloc_failures_exhausted = 0
        self.alloc_failures_fragmented = 0
        self.cache_evictions = 0
        # kvplane intra-replica defrag: the engine runs defrag()
        # between fused windows when fragmented failures rose
        self.defrag_runs = 0
        self.defrag_block_moves = 0
        # optional occupancy observer (the engine wires this to the
        # metrics layer's plain-int histogram): called with the pool
        # usage fraction at every allocation attempt, so the histogram
        # shows which occupancy regime allocations actually run in
        self.on_alloc_occupancy = None

    # -- capacity --------------------------------------------------------

    @property
    def available(self) -> int:
        """Blocks allocatable right now (free + evictable-cached)."""
        return len(self._free) + len(self._evictable)

    @property
    def active_blocks(self) -> int:
        """Blocks held by live sequences."""
        return len(self._ref)

    @property
    def usage(self) -> float:
        return self.active_blocks / float(self.num_blocks - 1)

    @property
    def free_blocks(self) -> int:
        """Blocks on the free list (never-written or fully released)."""
        return len(self._free)

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 registered blocks (evictable prefix cache)."""
        return len(self._evictable)

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def frag_report(self) -> dict:
        """Point-in-time fragmentation view (plain-int reads, safe from
        any thread): block-state census + allocation-failure
        classification. The scrape-time sync (EngineMetrics.sync_kvpool)
        and ``GET /debug/perf`` both serve exactly this dict."""
        return {
            "num_blocks": self.num_blocks - 1,   # allocatable, no trash
            "free": self.free_blocks,
            "active": self.active_blocks,
            "cached": self.cached_blocks,
            "usage": round(self.usage, 4),
            "allocs": self.allocs,
            "blocks_allocated": self.blocks_allocated,
            "alloc_failures_exhausted": self.alloc_failures_exhausted,
            "alloc_failures_fragmented": self.alloc_failures_fragmented,
            "cache_evictions": self.cache_evictions,
            "free_contiguity": round(self.free_contiguity(), 4),
            "defrag_runs": self.defrag_runs,
            "defrag_block_moves": self.defrag_block_moves,
        }

    def free_contiguity(self) -> float:
        """Fraction of adjacent free-block-id pairs: 1.0 when the free
        list is one dense run, ->0 as frees scatter across the pool.
        Device DMA batches contiguous block ranges, so scattered frees
        cost extra descriptors per transfer — the quantity defrag()
        restores between fused windows."""
        if len(self._free) < 2:
            return 1.0
        s = sorted(self._free)
        runs = sum(1 for a, b in zip(s, s[1:]) if b == a + 1)
        return runs / (len(s) - 1)

    def defrag(self) -> int:
        """Compact the free list: reorder it so subsequent pops hand
        out ascending, maximally dense block-id runs (pops take from
        the list tail). Pure host-side bookkeeping over indices — KV
        bytes never move, refcounts and the prefix cache are untouched,
        so this is safe between any two fused windows. Returns the
        number of list positions that changed."""
        self.defrag_runs += 1
        target = sorted(self._free, reverse=True)
        moved = sum(1 for a, b in zip(self._free, target) if a != b)
        self._free = target
        self.defrag_block_moves += moved
        return moved

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    # -- allocation ------------------------------------------------------

    def _take_one(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        if self._evictable:
            blk, _ = self._evictable.popitem(last=False)   # LRU out
            key = self._key_of.pop(blk)
            del self._by_key[key]
            self.cache_evictions += 1
            return blk
        return None

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh exclusive blocks (refcount 1), or None — all-or-
        nothing, so a failed admission/extension never leaks blocks."""
        if n <= 0:
            # n == 0 requests (fully prefix-shared prompts) are not
            # allocation attempts; keep them out of the telemetry
            return None if n < 0 else []
        self.allocs += 1
        if self.on_alloc_occupancy is not None:
            self.on_alloc_occupancy(self.usage)
        if self.available < n:
            if self.available == 0:
                self.alloc_failures_exhausted += 1
            else:
                self.alloc_failures_fragmented += 1
            return None
        out = []
        for _ in range(n):
            blk = self._take_one()
            self._ref[blk] = 1
            out.append(blk)
        self.blocks_allocated += n
        return out

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block; refcount-0 registered blocks
        become LRU-evictable (their KV stays valid in the pool), others
        return to the free list."""
        for blk in blocks:
            r = self._ref.get(blk, 0) - 1
            if r > 0:
                self._ref[blk] = r
                continue
            self._ref.pop(blk, None)
            if blk in self._key_of:
                self._evictable[blk] = None    # MRU end
            else:
                self._free.append(blk)

    # -- prefix sharing --------------------------------------------------

    def prefix_keys(self, tokens: Sequence[int],
                    salt: str = "") -> List[bytes]:
        """Chain keys for the matchable prefix of a prompt: full blocks
        covering at most len(tokens)-1 positions (the sequence never
        writes into a shared block and always recomputes at least one
        position). Deterministic — callers may cache per prompt to
        avoid re-hashing on deferred admissions."""
        if self.hasher is None or len(tokens) < 2:
            return []
        usable = (len(tokens) - 1) // self.block_size
        if not usable:
            return []
        return self.hasher.chunk_keys(
            list(tokens[:usable * self.block_size]), salt=salt)

    def match_keys(self, keys: Sequence[bytes],
                   record_stats: bool = True) -> Tuple[List[int], int]:
        """Longest registered block chain along `keys` -> (pinned block
        ids, covered token count). Matched blocks are pinned
        (refcount++) — the caller owns them like alloc'd ones and must
        free() them. record_stats=False skips the hit/miss counters
        (retries of a deferred admission must count once, not once per
        scheduler pass)."""
        blocks: List[int] = []
        for key in keys:
            blk = self._by_key.get(key)
            if blk is None:
                break
            blocks.append(blk)
        if record_stats and self.hasher is not None:
            if blocks:
                self.hits += 1
            else:
                self.misses += 1
        for blk in blocks:
            r = self._ref.get(blk, 0)
            if r == 0:
                self._evictable.pop(blk, None)
            self._ref[blk] = r + 1
        return blocks, len(blocks) * self.block_size

    def match_prefix(self, tokens: Sequence[int],
                     salt: str = "") -> Tuple[List[int], int]:
        """prefix_keys + match_keys in one call (tests, simple users)."""
        if self.hasher is None or len(tokens) < 2:
            return [], 0
        return self.match_keys(self.prefix_keys(tokens, salt=salt))

    def register(self, tokens: Sequence[int], blocks: Sequence[int],
                 salt: str = "") -> int:
        """Register a finished sequence's full blocks for sharing.
        `tokens` must be exactly the WRITTEN positions' tokens
        (prompt + output[:-1]); only blocks fully covered by them are
        registered. Duplicate content (key already registered from
        another sequence) keeps the existing block. Call BEFORE
        free()ing the sequence's blocks. Returns blocks registered."""
        if self.hasher is None:
            return 0
        n = min(len(tokens) // self.block_size, len(blocks))
        if not n:
            return 0
        keys = self.hasher.chunk_keys(
            list(tokens[:n * self.block_size]), salt=salt)
        count = 0
        for key, blk in zip(keys, blocks):
            if key in self._by_key or blk in self._key_of:
                # shared-prefix blocks re-register under their own key
                # (skip), duplicates keep the first copy
                continue
            self._by_key[key] = blk
            self._key_of[blk] = key
            count += 1
        return count

    def register_incremental(self, tokens: Sequence[int],
                             blocks: Sequence[int], state,
                             salt: str = ""):
        """Progressive register() for live sequences: key and register
        only blocks completed SINCE the previous call, threading the
        hasher's (chunks_keyed, digest) chain state — O(new blocks)
        per prefill chunk where re-keying from scratch would make a
        long prompt's hashing quadratic (kvcache/chunks.chain_keys).
        Returns the new state; pass it back on the next call."""
        if self.hasher is None:
            return state
        n = min(len(tokens) // self.block_size, len(blocks))
        start = state[0] if state else 0
        if n <= start:
            return state
        new_keys, state = self.hasher.chain_keys(
            list(tokens[:n * self.block_size]), salt=salt, state=state)
        for key, blk in zip(new_keys, blocks[start:n]):
            if key in self._by_key or blk in self._key_of:
                continue
            self._by_key[key] = blk
            self._key_of[blk] = key
        return state
