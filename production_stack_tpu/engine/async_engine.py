"""AsyncLLMEngine: asyncio facade over the synchronous engine loop.

The engine loop runs on one dedicated thread (JAX dispatch is blocking);
results cross into the event loop via ``loop.call_soon_threadsafe`` onto
per-request asyncio queues. When idle the loop parks on a condition
variable so an idle engine burns no CPU.
"""

import asyncio
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import AsyncIterator, Dict, List, Optional, Tuple

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.engine import LLMEngine, StepOutput
from production_stack_tpu.engine.scheduler import SamplingOptions
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

_SENTINEL: Tuple = ()


class AsyncLLMEngine:
    def __init__(self, cfg: EngineConfig, params=None, mesh=None):
        self.engine = LLMEngine(cfg, params=params, mesh=mesh)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._wake = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # dedicated pool for calls that wait on the ENGINE LOCK
        # (add_request/abort): during a multi-second lazy compile the
        # lock is held and each waiting call pins a thread — on the
        # loop's SHARED default executor a burst would exhaust the pool
        # and stall unrelated offloaded work (DNS, embeddings). The
        # waits serialize on the lock anyway, so a few threads suffice.
        self._lock_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="engine-lock")

    # ------------------------------------------------------------------

    def start(self, loop: Optional[asyncio.AbstractEventLoop] = None,
              warmup: bool = True) -> None:
        self._loop = loop or asyncio.get_event_loop()
        if self._lock_pool._shutdown:    # restarted after stop()
            self._lock_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="engine-lock")
        if warmup:
            self.engine.runner.warmup()
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="engine-loop")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        with self._wake:
            self._wake.notify_all()
        if self._thread:
            self._thread.join(timeout=10)
        self._lock_pool.shutdown(wait=False)

    def _run(self) -> None:
        while self._running:
            if not self.engine.has_work:
                with self._wake:
                    if not self.engine.has_work and self._running:
                        self._wake.wait(timeout=0.2)
                continue
            try:
                outputs = self.engine.step()
            except Exception:
                logger.exception("engine step failed")
                continue
            if outputs and self._loop is not None:
                self._loop.call_soon_threadsafe(self._dispatch, outputs)

    def _dispatch(self, outputs: List[StepOutput]) -> None:
        for out in outputs:
            q = self._queues.get(out.seq_id)
            if q is not None:
                q.put_nowait(out)
                if out.finished:
                    self._queues.pop(out.seq_id, None)

    # ------------------------------------------------------------------

    async def submit(self, prompt_tokens: List[int],
                     options: SamplingOptions,
                     seq_id: Optional[str] = None,
                     model: Optional[str] = None,
                     deadline: Optional[float] = None
                     ) -> Tuple[str, asyncio.Queue]:
        # add_request takes the ENGINE LOCK (engine.py), which the
        # engine thread holds across whole steps — including lazy XLA
        # compiles of new executable variants (seconds each). Taking
        # that lock here would block the EVENT LOOP: under a burst of
        # first-time feature combinations the server stops accepting
        # connections entirely (observed as connect-refused storms in
        # the r5 mixed-traffic soak). The executor thread absorbs the
        # wait; it also keeps the connector's tier prefetch IO off the
        # loop, as engine.add_request's contract expects.
        #
        # The seq_id is generated HERE so the result queue exists
        # before the engine can emit: once add_request returns on the
        # executor thread, the engine thread may prefill and dispatch
        # within its next iterations — registering the queue after the
        # await would race those first outputs.
        seq_id = seq_id or f"seq-{uuid.uuid4().hex[:12]}"
        if seq_id in self._queues:
            # silently replacing the live stream's queue would orphan
            # it (and the error-path pop below would then tear down the
            # WRONG stream's registration)
            raise ValueError(f"seq_id {seq_id!r} already has a live stream")
        q: asyncio.Queue = asyncio.Queue()
        self._queues[seq_id] = q
        loop = asyncio.get_running_loop()
        # submit directly (not run_in_executor) so the CONCURRENT
        # future stays reachable: on task cancellation asyncio cancels
        # the wrapper even though the executor call keeps running, so
        # only the concurrent future's state says whether add_request
        # actually completed.
        try:
            cfut = self._lock_pool.submit(
                lambda: self.engine.add_request(
                    prompt_tokens, options, seq_id=seq_id, model=model,
                    deadline=deadline))
        except RuntimeError:
            # pool already shut down (request raced stop()): the
            # request never entered the engine, but the registration
            # above must not outlive this admission attempt
            self._queues.pop(seq_id, None)
            raise
        try:
            await asyncio.wrap_future(cfut, loop=loop)
        except asyncio.CancelledError:
            # the executor call cannot be interrupted: add_request may
            # still COMPLETE after this cancellation (client vanished
            # while we waited on the engine lock). Abort the sequence
            # once the call settles, else the orphan decodes to its
            # token budget on a slot nobody is reading.
            self._queues.pop(seq_id, None)

            def _cleanup(f):
                if f.cancelled() or f.exception() is not None:
                    return          # request never entered the engine
                # runs on the pool worker that finished add_request (or
                # the loop thread if it settled before registration)
                try:
                    self._lock_pool.submit(self.engine.abort, seq_id)
                except RuntimeError:
                    # stop() shut the pool down while add_request was
                    # settling: abort inline rather than lose it (the
                    # callback machinery would swallow the RuntimeError
                    # and the admitted orphan would keep its slot)
                    try:
                        self.engine.abort(seq_id)
                    except Exception as e:
                        logger.warning("inline abort of %s failed: %s",
                                       seq_id, e)
            cfut.add_done_callback(_cleanup)
            raise
        except Exception:
            self._queues.pop(seq_id, None)
            raise
        with self._wake:
            self._wake.notify_all()
        return seq_id, q

    def abort(self, seq_id: str) -> None:
        """Abort a live request: the result-queue registration is freed
        SYNCHRONOUSLY (a shed/deadline abort of a still-WAITING sequence
        must not leave its queue lingering until the engine loop next
        notices), while the engine-side abort — which waits on the
        engine lock — is dispatched to an executor thread and not
        awaited. Cleanup paths may run under GeneratorExit where
        awaiting is illegal; abort is idempotent and slot-guarded, so
        ordering vs later admissions is safe."""
        if seq_id not in self._queues:
            return
        self._queues.pop(seq_id, None)
        try:
            f = self._lock_pool.submit(self.engine.abort, seq_id)
        except RuntimeError:
            # stop() already shut the pool down (server shutdown with
            # live streams): abort inline rather than lose it — the
            # engine thread is stopping, so the brief lock wait here
            # cannot stall a running loop.
            try:
                self.engine.abort(seq_id)
            except Exception as e:
                logger.warning("inline abort of %s failed: %s",
                               seq_id, e)
        else:
            f.add_done_callback(
                lambda f: f.exception() and logger.warning(
                    "async abort of %s failed: %s", seq_id,
                    f.exception()))

    async def stream(self, prompt_tokens: List[int],
                     options: SamplingOptions,
                     model: Optional[str] = None,
                     deadline: Optional[float] = None
                     ) -> AsyncIterator[StepOutput]:
        seq_id, q = await self.submit(prompt_tokens, options, model=model,
                                      deadline=deadline)
        try:
            while True:
                out = await q.get()
                yield out
                if out.finished:
                    return
        finally:
            # client disconnected mid-stream (or the consumer saw a
            # terminal output, making this a no-op): free the slot
            self.abort(seq_id)

    @property
    def tokenizer(self):
        return self.engine.tokenizer

    @property
    def model_name(self) -> str:
        return self.engine.model_cfg.name
