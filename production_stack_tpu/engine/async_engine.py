"""AsyncLLMEngine: asyncio facade over the synchronous engine loop.

The engine loop runs on one dedicated thread (JAX dispatch is blocking);
results cross into the event loop via ``loop.call_soon_threadsafe`` onto
per-request asyncio queues. When idle the loop parks on a condition
variable so an idle engine burns no CPU.
"""

import asyncio
import threading
from typing import AsyncIterator, Dict, List, Optional, Tuple

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.engine import LLMEngine, StepOutput
from production_stack_tpu.engine.scheduler import SamplingOptions
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

_SENTINEL: Tuple = ()


class AsyncLLMEngine:
    def __init__(self, cfg: EngineConfig, params=None, mesh=None):
        self.engine = LLMEngine(cfg, params=params, mesh=mesh)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._wake = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------

    def start(self, loop: Optional[asyncio.AbstractEventLoop] = None,
              warmup: bool = True) -> None:
        self._loop = loop or asyncio.get_event_loop()
        if warmup:
            self.engine.runner.warmup()
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="engine-loop")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        with self._wake:
            self._wake.notify_all()
        if self._thread:
            self._thread.join(timeout=10)

    def _run(self) -> None:
        while self._running:
            if not self.engine.has_work:
                with self._wake:
                    if not self.engine.has_work and self._running:
                        self._wake.wait(timeout=0.2)
                continue
            try:
                outputs = self.engine.step()
            except Exception:
                logger.exception("engine step failed")
                continue
            if outputs and self._loop is not None:
                self._loop.call_soon_threadsafe(self._dispatch, outputs)

    def _dispatch(self, outputs: List[StepOutput]) -> None:
        for out in outputs:
            q = self._queues.get(out.seq_id)
            if q is not None:
                q.put_nowait(out)
                if out.finished:
                    self._queues.pop(out.seq_id, None)

    # ------------------------------------------------------------------

    async def submit(self, prompt_tokens: List[int],
                     options: SamplingOptions,
                     seq_id: Optional[str] = None,
                     model: Optional[str] = None) -> Tuple[str, asyncio.Queue]:
        q: asyncio.Queue = asyncio.Queue()
        seq_id = self.engine.add_request(prompt_tokens, options,
                                        seq_id=seq_id, model=model)
        self._queues[seq_id] = q
        with self._wake:
            self._wake.notify_all()
        return seq_id, q

    async def stream(self, prompt_tokens: List[int],
                     options: SamplingOptions,
                     model: Optional[str] = None
                     ) -> AsyncIterator[StepOutput]:
        seq_id, q = await self.submit(prompt_tokens, options, model=model)
        try:
            while True:
                out = await q.get()
                yield out
                if out.finished:
                    return
        finally:
            # client disconnected mid-stream: free the slot
            if seq_id in self._queues:
                self._queues.pop(seq_id, None)
                self.engine.abort(seq_id)

    @property
    def tokenizer(self):
        return self.engine.tokenizer

    @property
    def model_name(self) -> str:
        return self.engine.model_cfg.name
