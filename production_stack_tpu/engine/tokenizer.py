"""Tokenizers: HF-backed for real checkpoints, byte-level for debug models.

The byte tokenizer keeps every CI/e2e path hardware- and download-free
(the reference achieves the same with facebook/opt-125m on CPU runners,
reference: .github/workflows/functionality-helm-chart.yml; we go further
and need no network at all).
"""

from typing import List, Optional, Sequence

BOS_ID = 256
EOS_ID = 257
PAD_ID = 258


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0-255 are bytes, then BOS/EOS/PAD."""

    vocab_size = 512
    bos_token_id = BOS_ID
    eos_token_id = EOS_ID
    pad_token_id = PAD_ID

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return [BOS_ID] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: List[dict]) -> str:
        parts = [f"<|{m.get('role', 'user')}|>\n{_content_text(m)}\n"
                 for m in messages]
        return "".join(parts) + "<|assistant|>\n"


class HFTokenizer:
    """Wraps a transformers tokenizer loaded from a checkpoint path."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(path)
        self.vocab_size = len(self._tok)
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id
        self.pad_token_id = self._tok.pad_token_id or self._tok.eos_token_id

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: List[dict]) -> str:
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True)
        return ByteTokenizer.apply_chat_template(self, messages)  # type: ignore


def _content_text(message: dict) -> str:
    content = message.get("content", "")
    if isinstance(content, list):  # OpenAI content-part arrays
        return "".join(p.get("text", "") for p in content
                       if isinstance(p, dict))
    return str(content)


def load_tokenizer(model_or_path: str, tokenizer_path: Optional[str] = None):
    """HF tokenizer when a checkpoint dir exists; byte tokenizer otherwise."""
    import os
    path = tokenizer_path or model_or_path
    if os.path.isdir(path):
        try:
            return HFTokenizer(path)
        except Exception:
            pass
    return ByteTokenizer()


class DetokenizeStream:
    """Incremental detokenizer producing printable deltas per new token.

    Buffers until the decoded string grows cleanly (handles multi-byte
    UTF-8 and SentencePiece prefix-space merges) — the SSE stream sends
    only stable text.
    """

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._ids: List[int] = []
        self._emitted = 0

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        text = self._tok.decode(self._ids)
        if text.endswith("�"):  # mid-codepoint; wait for more bytes
            return ""
        delta = text[self._emitted:]
        self._emitted = len(text)
        return delta

    def flush(self) -> str:
        """Emit whatever is still buffered (e.g. a trailing partial
        codepoint rendered as the replacement char) at end of stream."""
        text = self._tok.decode(self._ids)
        delta = text[self._emitted:]
        self._emitted = len(text)
        return delta
