"""Tokenizers: HF-backed for real checkpoints, byte-level for debug models.

The byte tokenizer keeps every CI/e2e path hardware- and download-free
(the reference achieves the same with facebook/opt-125m on CPU runners,
reference: .github/workflows/functionality-helm-chart.yml; we go further
and need no network at all).
"""

from typing import List, Optional, Sequence

BOS_ID = 256
EOS_ID = 257
PAD_ID = 258


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0-255 are bytes, then BOS/EOS/PAD."""

    vocab_size = 512
    bos_token_id = BOS_ID
    eos_token_id = EOS_ID
    pad_token_id = PAD_ID

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return [BOS_ID] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")

    def id_to_token(self, token_id: int):
        """(token string, raw bytes) for logprobs reporting — byte ids
        keep their exact byte so clients can reassemble split UTF-8."""
        if token_id < 256:
            raw = bytes([token_id])
            return raw.decode("utf-8", errors="replace"), list(raw)
        name = {BOS_ID: "<bos>", EOS_ID: "<eos>", PAD_ID: "<pad>"}.get(
            token_id, f"<unk:{token_id}>")
        return name, list(name.encode("utf-8"))

    @property
    def special_token_ids(self):
        # everything past the byte range: specials + unmapped ids
        return list(range(256, self.vocab_size))

    def apply_chat_template(self, messages: List[dict]) -> str:
        parts = [f"<|{m.get('role', 'user')}|>\n{_content_text(m)}\n"
                 for m in messages]
        return "".join(parts) + "<|assistant|>\n"


_BYTE_DECODER = None


def _byte_decoder():
    """The standard byte-level-BPE bytes↔unicode table (GPT-2's
    bytes_to_unicode), inverted: printable char -> original byte.
    Covers ALL 256 bytes, so a piece made entirely of these chars is a
    byte-level piece and inverts exactly."""
    global _BYTE_DECODER
    if _BYTE_DECODER is None:
        bs = (list(range(ord("!"), ord("~") + 1))
              + list(range(ord("¡"), ord("¬") + 1))
              + list(range(ord("®"), ord("ÿ") + 1)))
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        _BYTE_DECODER = {chr(c): b for b, c in zip(bs, cs)}
    return _BYTE_DECODER


class HFTokenizer:
    """Wraps a transformers tokenizer loaded from a checkpoint path."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(path)
        self.vocab_size = len(self._tok)
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id
        self.pad_token_id = self._tok.pad_token_id or self._tok.eos_token_id
        self.bos_token = self._tok.bos_token or ""
        self.eos_token = self._tok.eos_token or ""
        self._byte_level = None   # lazily detected (see _is_byte_level)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def id_to_token(self, token_id: int):
        """(token string, raw bytes) for logprobs reporting and the
        guided-decoding token lift. Uses the tokenizer's own token
        representation (convert_ids_to_tokens), NOT decode([id]) —
        decoding a multi-byte-split piece in isolation collapses
        distinct tokens to the replacement char and loses the bytes
        clients need to reassemble UTF-8.

        Raw bytes come from the piece's own encoding scheme: byte-level
        BPE pieces (GPT-2/Llama-3/Qwen style — every char is in the
        256-entry bytes↔unicode table) invert that table exactly, so a
        token for "é" lifts as [0xC3, 0xA9], not the mojibake piece's
        UTF-8; SentencePiece pieces map ▁ to a real space (a lone
        piece's leading space is load-bearing for guided matching —
        convert_tokens_to_string would strip it) and <0xHH>
        byte-fallbacks to their exact byte."""
        piece = self._tok.convert_ids_to_tokens(token_id)
        if piece is None:
            piece = f"<unk:{token_id}>"
        if (len(piece) == 6 and piece.startswith("<0x")
                and piece.endswith(">")):
            try:
                return piece, [int(piece[3:5], 16)]
            except ValueError:
                pass
        if self._is_byte_level():
            bd = _byte_decoder()
            if piece and all(c in bd for c in piece):
                return piece, [bd[c] for c in piece]
        text = piece.replace("▁", " ")      # SPM word boundary
        return piece, list(text.encode("utf-8"))

    def _is_byte_level(self) -> bool:
        """Byte-level BPE (GPT-2/Llama-3/Qwen) vs SentencePiece: decided
        per TOKENIZER, not per piece — SPM vocabularies also contain
        chars that happen to be in the byte table (é), which must lift
        as UTF-8, while in a byte-level vocab the same char IS a byte.
        The Ġ space marker only exists in byte-level vocabs."""
        if self._byte_level is None:
            try:
                vocab = self._tok.get_vocab()
                self._byte_level = any("Ġ" in k for k in vocab)
            except Exception:
                self._byte_level = False
        return self._byte_level

    @property
    def special_token_ids(self):
        return list(getattr(self._tok, "all_special_ids", []) or [])

    def apply_chat_template(self, messages: List[dict]) -> str:
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True)
        return ByteTokenizer.apply_chat_template(self, messages)  # type: ignore


def _content_text(message: dict) -> str:
    content = message.get("content", "")
    if isinstance(content, list):  # OpenAI content-part arrays
        return "".join(p.get("text", "") for p in content
                       if isinstance(p, dict))
    return str(content)


def render_chat_template(template_text: str, messages: List[dict],
                         **extra_vars) -> str:
    """Render a user-supplied Jinja chat template (HF conventions:
    `messages` in scope, `add_generation_prompt` true). StrictUndefined:
    a template referencing a variable we don't provide errors loudly
    instead of silently rendering empty strings."""
    import datetime

    import jinja2
    env = jinja2.Environment(autoescape=False,
                             undefined=jinja2.StrictUndefined)

    # helpers stock HF chat templates expect (many Llama/Mistral templates
    # call raise_exception on bad role sequences; some stamp dates)
    def raise_exception(message):
        raise jinja2.exceptions.TemplateError(message)

    env.globals["raise_exception"] = raise_exception
    env.globals["strftime_now"] = \
        lambda fmt: datetime.datetime.now().strftime(fmt)
    return env.from_string(template_text).render(
        messages=messages, add_generation_prompt=True, **extra_vars)


def load_tokenizer(model_or_path: str, tokenizer_path: Optional[str] = None,
                   chat_template_path: Optional[str] = None):
    """HF tokenizer when a checkpoint dir exists; byte tokenizer otherwise.
    `chat_template_path` (a Jinja file) overrides the built-in template —
    the reference surfaces the same knob as the engine's chat-template
    mount (deployment-vllm-multi.yaml:100-103)."""
    import os
    path = tokenizer_path or model_or_path
    tok = None
    if os.path.isdir(path):
        try:
            tok = HFTokenizer(path)
        except Exception:
            pass
    if tok is None:
        tok = ByteTokenizer()
    if chat_template_path:
        with open(chat_template_path) as f:
            template_text = f.read()
        extra = {
            # common HF template variables
            "bos_token": getattr(tok, "bos_token", "") or "",
            "eos_token": getattr(tok, "eos_token", "") or "",
        }

        def apply_with_override(messages: List[dict]) -> str:
            return render_chat_template(template_text, messages, **extra)

        # fail at startup, not per-request: a broken template (Jinja
        # typo, missing jinja2, undefined variable) must never silently
        # fall back to the default and serve wrong prompts
        probe = [{"role": "system", "content": "probe"},
                 {"role": "user", "content": "probe"}]
        try:
            apply_with_override(probe)
        except Exception as e:
            raise ValueError(
                f"chat template {chat_template_path!r} failed to render: "
                f"{e}") from e
        tok.apply_chat_template = apply_with_override  # type: ignore
    return tok


class DetokenizeStream:
    """Incremental detokenizer producing printable deltas per new token.

    Buffers until the decoded string grows cleanly (handles multi-byte
    UTF-8 and SentencePiece prefix-space merges) — the SSE stream sends
    only stable text.
    """

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._ids: List[int] = []
        # incremental window (the vLLM detokenizer scheme): decode only
        # ids[prefix:] each push — prefix trails read by a few tokens of
        # context so SentencePiece prefix-space merges and multi-byte
        # codepoints resolve identically to a full decode, while per-
        # token cost stays O(window), not O(sequence) (a full re-decode
        # per token is quadratic and dominates host time at long
        # generations).
        self._prefix = 0     # window start
        self._stable = ""    # emitted portion of decode(ids[prefix:])
        self._hold = 0       # consecutive mid-codepoint holds
        self._empty = {}     # id -> renders-nothing-alone (cached)

    # context window (tokens): window start, keep_head offset, buffer
    # tail, and the hold bound all derive from this ONE constant — the
    # slide/compaction invariants require them mutually consistent
    _WINDOW = 8

    def _invisible(self, token_id: int) -> bool:
        v = self._empty.get(token_id)
        if v is None:
            v = self._empty[token_id] = \
                self._tok.decode([token_id]) == ""
        return v

    def push(self, token_id: int) -> str:
        W = self._WINDOW
        self._ids.append(token_id)
        text = self._tok.decode(self._ids[self._prefix:])
        pending = text.endswith("�")
        if pending:
            # trailing codepoint may still be in flight: hold — but
            # BOUNDED. A UTF-8 sequence resolves within 4 bytes, so W
            # consecutive pending decodes mean the tail is invalid
            # bytes, not an in-flight codepoint: emit everything EXCEPT
            # the final (only still-completable) char instead of
            # freezing the window and re-paying an ever-growing decode
            # per push on degenerate byte storms. Because the pending
            # char is never counted emitted (_stable excludes it, and
            # slid windows exclude it below), a later completion emits
            # the resolved char through the ordinary delta — no
            # retroactive divergence, no lost codepoint.
            self._hold += 1
            if self._hold <= W:
                return ""
            emit_to = len(text) - 1
        else:
            emit_to = len(text)
        self._hold = 0
        delta = text[len(self._stable):emit_to] \
            if emit_to > len(self._stable) else ""
        # slide the window: keep the trailing tokens as context so the
        # next decode resolves prefix-space merges exactly like a full
        # decode would. _stable is re-decoded FROM THE NEW START so the
        # next delta is measured against the same origin (a suffix
        # decode can render its first chars differently than the full
        # string; consistency of origin is what matters). String-
        # position-dependent rendering (SentencePiece strips a leading
        # space at position 0) can only leak into a delta when _stable
        # is EMPTY — the next token would sit at the window's string
        # start and lose its boundary space — so when the trailing
        # window renders nothing, KEEP the current origin and instead
        # bound the buffer by dropping middle ids that render nothing
        # on their own (skipped specials: decode output is unchanged
        # without them, and the kept window stays O(2W) through
        # arbitrarily long invisible runs, e.g. an eos loop under
        # ignore_eos).
        start = max(0, len(self._ids) - W)
        stable = self._tok.decode(self._ids[start:])
        if pending and stable.endswith("�"):
            stable = stable[:-1]     # pending char stays un-emitted
        if stable == "" and start > self._prefix:
            self._stable = text[:emit_to]
            keep_head = self._prefix + W
            tail_start = len(self._ids) - W
            if tail_start > keep_head:
                mid = [i for i in self._ids[keep_head:tail_start]
                       if not self._invisible(i)]
                self._ids[keep_head:tail_start] = mid
        else:
            self._prefix = start
            self._stable = stable
        return delta

    def flush(self) -> str:
        """Emit whatever is still buffered (e.g. a trailing partial
        codepoint rendered as the replacement char) at end of stream."""
        text = self._tok.decode(self._ids[self._prefix:])
        delta = text[len(self._stable):]
        self._stable = text
        return delta
