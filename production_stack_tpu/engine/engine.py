"""LLMEngine: the synchronous continuous-batching core.

One ``step()`` = one unit of device work (a prefill chunk or a fused
decode over all running slots) plus host bookkeeping (sampling-param
assembly, stop detection, metrics). The async server drives this loop on
a dedicated thread (see server.py); batch composition changes never
recompile because shapes are static.
"""

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.metrics import EngineMetrics
from production_stack_tpu.engine.runner import ModelRunner
from production_stack_tpu.engine.sampler import SamplingParams
from production_stack_tpu.engine.scheduler import (Scheduler, SamplingOptions,
                                                   SeqStatus, Sequence)
from production_stack_tpu.engine.tokenizer import (DetokenizeStream,
                                                   load_tokenizer)
from production_stack_tpu.models.config import get_config
from production_stack_tpu.models.hf_loader import load_checkpoint
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


@dataclass
class StepOutput:
    seq_id: str
    new_token: Optional[int]
    text_delta: str
    finished: bool
    finish_reason: Optional[str]


# finished sequences kept for post-hoc inspection (bounded; see _remember)
_FINISHED_RETENTION = 1024


class LLMEngine:
    def __init__(self, engine_cfg: EngineConfig, params=None, mesh=None):
        self.cfg = engine_cfg
        self.model_cfg = get_config(engine_cfg.model)
        self.tokenizer = load_tokenizer(engine_cfg.model,
                                        engine_cfg.tokenizer,
                                        engine_cfg.chat_template)
        if params is None and engine_cfg.checkpoint:
            params = load_checkpoint(self.model_cfg, engine_cfg.checkpoint)
        if mesh is None and engine_cfg.tensor_parallel_size > 1:
            from production_stack_tpu.parallel.mesh import (MeshConfig,
                                                            build_mesh)
            import jax
            tp = engine_cfg.tensor_parallel_size
            mesh = build_mesh(MeshConfig(dp=1, sp=1, tp=tp),
                              jax.devices()[:tp])
        self.runner = ModelRunner(self.model_cfg, engine_cfg, params=params,
                                  mesh=mesh)
        self.scheduler = Scheduler(engine_cfg.max_num_seqs,
                                   engine_cfg.max_model_len,
                                   engine_cfg.prefill_chunk)
        self.metrics = EngineMetrics(self.model_cfg.name)
        # KV tiering (HBM→host→disk→remote; kvcache/): the reference wires
        # the same capability through LMCache env + --kv-transfer-config
        # (reference: helm/templates/deployment-vllm-multi.yaml:94-99,154-178)
        self.connector = None
        if engine_cfg.kv_transfer_config:
            from production_stack_tpu.kvcache.connector import (
                KVConnector, KVTransferConfig)
            tcfg = KVTransferConfig.from_dict(engine_cfg.kv_transfer_config)
            if tcfg.enabled:
                self.connector = KVConnector(self.runner, self.model_cfg,
                                             engine_cfg, tcfg)
                self.scheduler.on_admit = self._on_admit
        self.seqs: Dict[str, Sequence] = {}
        self._finished_order: List[str] = []
        self._id_counter = itertools.count()
        # guards scheduler state across the engine-loop and server threads
        self._lock = threading.RLock()
        # per-slot host mirrors feeding the decode batch
        B = engine_cfg.max_num_seqs
        self._slot_token = np.zeros((B,), np.int32)
        self._slot_pos = np.zeros((B,), np.int32)
        self._slot_temp = np.full((B,), 1.0, np.float32)
        self._slot_top_p = np.ones((B,), np.float32)
        self._slot_top_k = np.zeros((B,), np.int32)

    # ------------------------------------------------------------------

    def add_request(self, prompt_tokens: List[int],
                    options: Optional[SamplingOptions] = None,
                    seq_id: Optional[str] = None) -> str:
        seq_id = seq_id or f"seq-{next(self._id_counter)}"
        seq = Sequence(seq_id=seq_id, prompt_tokens=list(prompt_tokens),
                       options=options or SamplingOptions(),
                       detok=DetokenizeStream(self.tokenizer))
        if self.connector is not None:
            # tier lookup + D2H-side fetch runs here, on the caller's
            # thread — never on the engine loop
            seq.kv_prefetch = self.connector.prefetch(seq.prompt_tokens)
        with self._lock:
            self.scheduler.add(seq)
            self.seqs[seq_id] = seq
        return seq_id

    def abort(self, seq_id: str) -> bool:
        with self._lock:
            ok = self.scheduler.abort(seq_id)
            if ok and seq_id in self.seqs:
                self._remember(self.seqs[seq_id])
            self._refresh_gauges()
            return ok

    # ------------------------------------------------------------------

    def step(self) -> List[StepOutput]:
        with self._lock:
            work, decode_seqs = self.scheduler.schedule()
            outputs: List[StepOutput] = []
            if work is not None:
                outputs.extend(self._do_prefill(work))
            elif decode_seqs:
                outputs.extend(self._do_decode(decode_seqs))
            self._refresh_gauges()
            return outputs

    def _do_prefill(self, work) -> List[StepOutput]:
        seq = work.seq
        opt = seq.options
        row = SamplingParams(
            temperature=jnp.asarray([opt.temperature], jnp.float32),
            top_p=jnp.asarray([opt.top_p], jnp.float32),
            top_k=jnp.asarray([opt.top_k], jnp.int32))
        token_dev = self.runner.prefill(work.chunk, work.start, seq.slot, row)
        self.scheduler.on_prefill_done(work)
        self.metrics.prompt_tokens.inc(len(work.chunk))
        if not work.is_last:
            return []
        # prompt fully prefilled: the sampled id is the first output token
        token = int(token_dev)
        seq.first_token_time = time.monotonic()
        self.metrics.ttft.observe(seq.first_token_time - seq.arrival_time)
        return self._accept_token(seq, token)

    def _do_decode(self, decode_seqs) -> List[StepOutput]:
        sampling = SamplingParams(
            temperature=jnp.asarray(self._slot_temp),
            top_p=jnp.asarray(self._slot_top_p),
            top_k=jnp.asarray(self._slot_top_k))
        t0 = time.monotonic()
        ids = np.asarray(self.runner.decode(self._slot_token, self._slot_pos,
                                            sampling))
        dt = time.monotonic() - t0
        outputs: List[StepOutput] = []
        for seq in decode_seqs:
            self.metrics.per_token.observe(dt)
            outputs.extend(self._accept_token(seq, int(ids[seq.slot])))
        return outputs

    def _accept_token(self, seq: Sequence, token: int) -> List[StepOutput]:
        seq.output_tokens.append(token)
        self.metrics.generation_tokens.inc()
        delta = seq.detok.push(token)
        seq.output_text += delta
        reason = self._stop_reason(seq, token, delta)
        if reason is not None and reason != "stop":
            seq.output_text += seq.detok.flush()
        text_delta = seq.output_text[seq.chars_emitted:]
        seq.chars_emitted = len(seq.output_text)
        if reason is not None:
            if self.connector is not None:
                # extract while the slot still holds this sequence's KV —
                # dispatched before scheduler.finish can recycle the slot
                self.connector.on_finish(seq)
            self.scheduler.finish(seq, reason)
            self._remember(seq)
            self.metrics.e2e_latency.observe(
                time.monotonic() - seq.arrival_time)
            return [StepOutput(seq.seq_id, token, text_delta, True, reason)]
        self._sync_slot(seq)
        return [StepOutput(seq.seq_id, token, text_delta, False, None)]

    def _stop_reason(self, seq: Sequence, token: int,
                     delta: str) -> Optional[str]:
        """Stop decision; on a stop-string match, truncates seq.output_text
        so the stop string itself is never delivered (OpenAI semantics)."""
        opt = seq.options
        if token in opt.stop_token_ids:
            return "stop"
        if not opt.ignore_eos and token == self.tokenizer.eos_token_id:
            return "stop"
        if opt.stop and delta:
            # a match can straddle the delta boundary: search a window of
            # (longest stop - 1) chars before the delta
            for s in opt.stop:
                from_idx = max(0, len(seq.output_text) - len(delta) - len(s))
                idx = seq.output_text.find(s, from_idx)
                if idx != -1:
                    seq.output_text = seq.output_text[:idx]
                    return "stop"
        if len(seq.output_tokens) >= opt.max_tokens:
            return "length"
        if seq.num_tokens >= self.cfg.max_model_len:
            return "length"
        return None

    def _remember(self, seq: Sequence) -> None:
        """Retain finished sequences for inspection, bounded in count."""
        self._finished_order.append(seq.seq_id)
        while len(self._finished_order) > _FINISHED_RETENTION:
            old = self._finished_order.pop(0)
            self.seqs.pop(old, None)

    def _sync_slot(self, seq: Sequence) -> None:
        """Mirror the sequence's next decode input into the slot arrays."""
        slot, opt = seq.slot, seq.options
        self._slot_token[slot] = seq.output_tokens[-1]
        self._slot_pos[slot] = seq.next_position
        self._slot_temp[slot] = opt.temperature
        self._slot_top_p[slot] = opt.top_p
        self._slot_top_k[slot] = opt.top_k

    def render_metrics(self) -> bytes:
        with self._lock:
            self._refresh_gauges()
        return self.metrics.render()

    def _on_admit(self, seq: Sequence) -> None:
        """Scheduler hook: inject a prefetched KV prefix into the slot."""
        pf = seq.kv_prefetch
        if pf is None:
            return
        seq.kv_prefetch = None   # release host buffers after injection
        self.connector.inject(pf, seq.slot)
        seq.num_prefilled = pf.cached_tokens

    def _refresh_gauges(self) -> None:
        self.metrics.num_running.set(self.scheduler.num_running)
        self.metrics.num_waiting.set(self.scheduler.num_waiting)
        usage = self.scheduler.kv_usage
        self.metrics.kv_usage.set(usage)
        self.metrics.hbm_kv_usage.set(usage)
        if self.connector is not None:
            self.metrics.prefix_hit_rate.set(self.connector.hit_rate)

    def close(self) -> None:
        """Flush the KV writer and release tier connections."""
        if self.connector is not None:
            self.connector.close()

    # ------------------------------------------------------------------

    def generate(self, prompt: str, options: Optional[SamplingOptions] = None,
                 ) -> str:
        """Blocking single-prompt convenience API (tests, CLI)."""
        toks = self.tokenizer.encode(prompt)
        seq_id = self.add_request(toks, options)
        while True:
            for out in self.step():
                if out.seq_id == seq_id and out.finished:
                    return self.seqs[seq_id].output_text

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work
