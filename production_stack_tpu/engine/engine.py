"""LLMEngine: the synchronous continuous-batching core.

One ``step()`` = one unit of device work (a prefill chunk or a fused
decode over all running slots) plus host bookkeeping (sampling-param
assembly, stop detection, metrics). The async server drives this loop on
a dedicated thread (see server.py); batch composition changes never
recompile because shapes are static.
"""

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.metrics import EngineMetrics
from production_stack_tpu.engine.runner import ModelRunner
from production_stack_tpu.engine.sampler import SamplingParams
from production_stack_tpu.engine.scheduler import (Scheduler, SamplingOptions,
                                                   SeqStatus, Sequence)
from production_stack_tpu.engine.tokenizer import (DetokenizeStream,
                                                   load_tokenizer)
from production_stack_tpu.models.config import get_config
from production_stack_tpu.models.hf_loader import load_checkpoint
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


@dataclass
class StepOutput:
    seq_id: str
    new_token: Optional[int]
    text_delta: str
    finished: bool
    finish_reason: Optional[str]
    # chosen token's log p under the raw model distribution (runner)
    logprob: Optional[float] = None
    # top_logprobs alternatives [(token_id, logprob)] when requested
    top_alts: Optional[list] = None
    # terminal outputs only: the sequence's phase timeline (monotonic
    # stamps + KV prefetch cost) so the server can render engine-side
    # trace spans without reaching into scheduler internals
    # (tracing.py; docs/observability.md "Tracing")
    timing: Optional[dict] = None


# finished sequences kept for post-hoc inspection (bounded; see _remember)
_FINISHED_RETENTION = 1024


class AdmissionRejected(Exception):
    """Bounded admission (cfg.max_waiting_seqs): the waiting queue is
    full, so the request is shed at submit time instead of queuing
    forever. The server maps this to 503 + Retry-After; the router
    treats that answer as shed-not-sick (router/resilience.py)."""

    def __init__(self, queue_depth: int, retry_after_s: float):
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s
        super().__init__(
            f"engine overloaded: {queue_depth} sequences already "
            f"waiting (max_waiting_seqs reached); retry in "
            f"~{retry_after_s:.1f}s")


class DeadlineExceeded(Exception):
    """The request's deadline (x-request-deadline-ms) expired while it
    was still WAITING; the scheduler dropped it before prefill. The
    server maps this to 504 with an x-deadline-expired marker."""

class LLMEngine:
    def __init__(self, engine_cfg: EngineConfig, params=None, mesh=None):
        self.cfg = engine_cfg
        self.model_cfg = get_config(engine_cfg.model)
        # honor --dtype (validated to bfloat16/float32 in EngineConfig;
        # the reference passes --dtype down to vllm serve the same way,
        # reference: helm/templates/deployment-vllm-multi.yaml:80-83)
        want_dtype = jnp.bfloat16 if engine_cfg.dtype == "bfloat16" \
            else jnp.float32
        if self.model_cfg.dtype != want_dtype:
            import dataclasses
            self.model_cfg = dataclasses.replace(self.model_cfg,
                                                 dtype=want_dtype)
        if (engine_cfg.moe_capacity_factor is not None
                and engine_cfg.moe_capacity_factor
                != self.model_cfg.moe_capacity_factor):
            import dataclasses
            self.model_cfg = dataclasses.replace(
                self.model_cfg,
                moe_capacity_factor=engine_cfg.moe_capacity_factor)
        self.tokenizer = load_tokenizer(engine_cfg.model,
                                        engine_cfg.tokenizer,
                                        engine_cfg.chat_template)
        if params is None and engine_cfg.checkpoint:
            params = load_checkpoint(self.model_cfg, engine_cfg.checkpoint)
        # multi-LoRA: every adapter is served as its own model id; the
        # stacked adapter pytree rides in the runner, rows select their
        # adapter per request (reference surface: --enable-lora +
        # proposals/lora-k8s-support.md routing by served model name)
        self.lora_ids: Dict[str, int] = {}
        # runtime adapter pool (load_adapter/evict_adapter): rows are
        # APPEND-ONLY — adapter id == row index + 1 forever, so an
        # evicted name can vanish from the catalog while in-flight
        # sequences keep a valid row. The config is pinned at first
        # use: every adapter in one engine shares rank/targets (the
        # stacked-pytree contract).
        self._lora_cfg = None
        self._lora_rows: List = []
        self.adapter_loads = 0
        self.adapter_evictions = 0
        lora_stacked, lora_scaling = None, 1.0
        if engine_cfg.lora_adapters:
            from production_stack_tpu.models import lora as lora_mod
            lcfg = self._ensure_lora_cfg()
            for name, src in sorted(engine_cfg.lora_adapters.items()):
                self._lora_rows.append(self._build_adapter(name, src))
                self.lora_ids[name] = len(self._lora_rows)
            lora_stacked = lora_mod.stack_adapters(self.model_cfg, lcfg,
                                                   self._lora_rows)
            lora_scaling = lcfg.scaling
        self.served_models = [engine_cfg.model] + list(self.lora_ids)
        if mesh is None and (engine_cfg.tensor_parallel_size > 1
                             or engine_cfg.expert_parallel_size > 1):
            from production_stack_tpu.parallel.mesh import (MeshConfig,
                                                            build_mesh)
            import jax
            tp = engine_cfg.tensor_parallel_size
            ep = engine_cfg.expert_parallel_size
            if ep > 1:
                E = self.model_cfg.num_experts
                if not E:
                    raise ValueError(
                        f"expert_parallel_size={ep} but model "
                        f"{self.model_cfg.name!r} is dense (no experts)")
                if E % ep:
                    raise ValueError(
                        f"expert_parallel_size={ep} does not divide "
                        f"num_experts={E}")
            mesh = build_mesh(MeshConfig(dp=1, sp=1, tp=tp, ep=ep),
                              jax.devices()[:tp * ep])
        self.runner = ModelRunner(self.model_cfg, engine_cfg, params=params,
                                  mesh=mesh, lora_stacked=lora_stacked,
                                  lora_scaling=lora_scaling)
        self.scheduler = Scheduler(engine_cfg.max_num_seqs,
                                   engine_cfg.max_model_len,
                                   engine_cfg.prefill_chunk)
        self.metrics = EngineMetrics(self.model_cfg.name)
        self.metrics.adapters_loaded.set(len(self.lora_ids))
        # paged-KV block accounting (engine/block_manager.py): admission
        # allocates each prompt's blocks, decode windows extend tables
        # on demand, and prefix caching is refcounted block SHARING —
        # zero-copy prefix hits (the reference's --enable-prefix-caching,
        # helm/templates/deployment-vllm-multi.yaml:73-75)
        from production_stack_tpu.engine.block_manager import BlockManager
        from production_stack_tpu.kvcache.chunks import model_fingerprint
        self.block_mgr = BlockManager(
            self.runner.cache.num_blocks, engine_cfg.kv_block_size,
            enable_prefix_caching=engine_cfg.enable_prefix_caching,
            namespace=model_fingerprint(self.model_cfg,
                                        engine_cfg.kv_dtype))
        self._tables = np.zeros((engine_cfg.max_num_seqs,
                                 engine_cfg.max_blocks_per_seq), np.int32)
        self.scheduler.can_admit = self._try_admit
        self.scheduler.on_admit = self._on_admit
        # pool-occupancy-at-allocation histogram: the block manager
        # stays metrics-free, the metrics layer owns the plain-int
        # buckets (one bisect per allocation attempt, not per token)
        self.block_mgr.on_alloc_occupancy = \
            self.metrics.kvpool_occ_hist.observe
        # kvplane defrag trigger state: fragmented-failure count at the
        # last end-of-step check (engine lock only)
        self._defrag_seen_failures = 0
        # engine efficiency accounting (engine/efficiency.py;
        # docs/engine.md "Efficiency telemetry"): classifies every
        # fused window's token-steps, models HBM traffic for the
        # effective-bandwidth/MBU gauges, and stamps XLA compiles.
        # Byte model inputs are host-side metadata only (no device
        # sync): the full parameter footprint and the per-position KV
        # read cost (K+V across layers/heads, plus the int8 cache's
        # f32 scales).
        from production_stack_tpu.engine.efficiency import (
            EngineEffAccounting)
        mc = self.model_cfg
        kv_itemsize = {"bfloat16": 2, "float32": 4,
                       "int8": 1}[engine_cfg.kv_dtype]
        kv_pos_bytes = (2 * mc.num_layers * mc.num_kv_heads
                        * mc.head_dim_ * kv_itemsize)
        if engine_cfg.kv_dtype == "int8":
            # per-(token, head) f32 scales stream alongside the blocks
            kv_pos_bytes += 2 * mc.num_layers * mc.num_kv_heads * 4
        from jax import tree_util as _tree_util
        weight_bytes = sum(
            x.size * x.dtype.itemsize
            for x in _tree_util.tree_leaves(self.runner.params))
        self.eff = EngineEffAccounting(
            weight_bytes=weight_bytes,
            kv_position_bytes=kv_pos_bytes,
            hbm_peak_bytes_per_s=engine_cfg.hbm_peak_gbps * 1e9,
            ring_entries=engine_cfg.perf_ring_entries,
            compile_hist=self.metrics.compile_hist)
        self.runner.compile_observer = self.eff
        # advertised once: the router's per-endpoint concurrency cap
        # reads this gauge (0 = unbounded admission, nothing to cap on)
        self.metrics.capacity.set(
            engine_cfg.max_num_seqs + engine_cfg.max_waiting_seqs
            if engine_cfg.max_waiting_seqs is not None else 0)
        # KV tiering (HBM→host→disk→remote; kvcache/): the reference wires
        # the same capability through LMCache env + --kv-transfer-config
        # (reference: helm/templates/deployment-vllm-multi.yaml:94-99,154-178)
        self.connector = None
        if engine_cfg.kv_transfer_config:
            from production_stack_tpu.kvcache.connector import (
                KVConnector, KVTransferConfig)
            tcfg = KVTransferConfig.from_dict(engine_cfg.kv_transfer_config)
            if tcfg.enabled:
                self.connector = KVConnector(self.runner, self.model_cfg,
                                             engine_cfg, tcfg)
                # kv_prefetch / kv_publish durations land in the same
                # phase family as queue_wait/prefill/decode
                self.connector.phase_recorder = \
                    self.metrics.engine_phases
        # rolling KV: models whose EVERY layer is windowed (Mistral
        # v0.1-style) never attend positions behind the window again, so
        # their blocks are freed as generation advances — live-context
        # HBM bounded by W instead of total length. Off for alternating
        # (Gemma-2: global layers need the full prefix) and under KV
        # tiering (tier extraction reads from position 0).
        self._roll_window = (
            self.model_cfg.sliding_window
            if (self.model_cfg.sliding_window
                and not self.model_cfg.alternating_sliding
                and self.connector is None)
            else None)
        self.seqs: Dict[str, Sequence] = {}
        self._finished_order: List[str] = []
        self._id_counter = itertools.count()
        # EWMA of finished-request wall time (arrival -> finish),
        # seeding the load report's queue-delay estimate before any
        # request has completed
        self._service_ewma = 0.5
        # guards scheduler state across the engine-loop and server threads
        self._lock = threading.RLock()
        # per-slot host mirrors feeding the decode batch. Free/prefilling
        # slots sit at position S: their garbage window writes DUS-clamp
        # onto S-1, which is safe because every forward writes a row's
        # real K/V BEFORE attention reads the cache — any query that
        # legitimately reaches position S-1 overwrites the garbage in the
        # same executable that first attends it (see models/kv.py).
        B = engine_cfg.max_num_seqs
        self._slot_token = np.zeros((B,), np.int32)
        self._slot_pos = np.full((B,), engine_cfg.max_model_len, np.int32)
        self._slot_temp = np.full((B,), 1.0, np.float32)
        self._slot_top_p = np.ones((B,), np.float32)
        self._slot_top_k = np.zeros((B,), np.int32)
        self._slot_adapter = np.zeros((B,), np.int32)
        self._slot_seed = np.zeros((B,), np.int32)
        # OpenAI/vLLM logit-shaping mirrors (engine/sampler.py); all
        # default-inert so unshaped batches compile the ordinary
        # executables
        from production_stack_tpu.engine.sampler import (LOGIT_BIAS_K,
                                                         MIN_TOKENS_STOP_K)
        self._slot_presence = np.zeros((B,), np.float32)
        self._slot_frequency = np.zeros((B,), np.float32)
        self._slot_repetition = np.ones((B,), np.float32)
        self._slot_min_p = np.zeros((B,), np.float32)
        self._slot_min_tokens = np.zeros((B,), np.int32)
        self._slot_prompt_len = np.zeros((B,), np.int32)
        self._slot_bias_ids = np.full((B, LOGIT_BIAS_K), -1, np.int32)
        self._slot_bias_vals = np.zeros((B, LOGIT_BIAS_K), np.float32)
        # stop_token_ids masked below min_tokens (sampler.adjust_logits)
        self._slot_stop_ids = np.full((B, MIN_TOKENS_STOP_K), -1, np.int32)
        self.runner._eos_id = int(self.tokenizer.eos_token_id or 0)
        # guided decoding: per-slot DFA-state host mirror (grammar row
        # indices are rebuilt per dispatch from the sequences)
        self._slot_gstate = np.zeros((B,), np.int32)
        self._guided_key = None      # tuple of active patterns
        self._guided_table = None    # device [G+1, S, V] int32
        self._guided_gids = {}       # pattern -> row index
        # device-resident sampling params, re-uploaded only when a slot's
        # options change (admission/finish), never per decode window
        self._dev_sampling = None
        self._sampling_dirty = True
        # decode inputs are device-carried across windows (runner); the
        # host re-uploads its mirrors only when this is set (admission,
        # finish, abort — any slot-composition change)
        self._decode_dirty = True
        # speculative-ngram history re-upload flag: tracked separately
        # because the history matrix is only (re)built for windows that
        # actually speculate — a stale device history can only degrade
        # DRAFT quality, never correctness (verification ignores it)
        self._hist_dirty = True
        # decode windows kept in flight between step() calls (FIFO of
        # (ids_device, lps, counts, window, [seqs at dispatch], t0)).
        # Up to cfg.pipeline_depth windows ride the device queue at once:
        # window N+1 is dispatched BEFORE window N's results are synced,
        # so the device starts N+1 the instant N retires instead of
        # idling one host round-trip (which dominates when the chip sits
        # behind a high-RTT tunnel). Valid because decode inputs are
        # device-carried; the host only has to stay out of the way
        # (no mirror uploads) until every queued window is processed.
        self._inflight: List[tuple] = []
        # continuous batching across windows (docs/engine.md
        # "Continuous batching across windows"): the device carry's
        # current batch bucket (dispatches at a different bucket must
        # re-upload the host mirrors), and an EWMA of the per-row-step
        # probability of a non-length stop — the EOS-rate horizon the
        # adaptive window sizing reads so finished tails cannot span a
        # long window even when max_tokens gives no warning
        self._carry_batch = engine_cfg.max_num_seqs
        self._eos_rate = 0.0
        # real embedding encoder (models/encoder.py), built EAGERLY:
        # a lazy first-request load would run checkpoint reading on the
        # server's event loop (stalling every in-flight stream) and
        # race across executor threads; and a bad preset/checkpoint
        # must fail at startup, not at first request
        self._enc_params = None
        self._embed_tok = None
        if engine_cfg.embedding_model:
            self._ensure_encoder()

    # ------------------------------------------------------------------

    def _adapter_salt(self, adapter_id: int) -> str:
        """KV-tier key salt: adapter NAME (stable across processes and
        config orderings, unlike the id) — adapter-colored KV chunks must
        never collide with the base model's or each other's."""
        if adapter_id == 0:
            return ""
        for name, aid in self.lora_ids.items():
            if aid == adapter_id:
                return f"lora:{name}"
        return f"lora-id:{adapter_id}"

    def resolve_model(self, model: Optional[str]) -> int:
        """Served model name -> adapter id (0 = base). Raises on unknown."""
        if model is None or model == self.cfg.model:
            return 0
        if model in self.lora_ids:
            return self.lora_ids[model]
        raise ValueError(f"unknown model {model!r}; serving "
                         f"{self.served_models}")

    # ------------------------------------------------- runtime adapters

    def _ensure_lora_cfg(self):
        if self._lora_cfg is None:
            from production_stack_tpu.models import lora as lora_mod
            self._lora_cfg = lora_mod.LoRAConfig(
                rank=self.cfg.lora_rank, alpha=self.cfg.lora_alpha,
                targets=tuple(self.cfg.lora_targets))
        return self._lora_cfg

    def _build_adapter(self, name: str, src: str):
        lcfg = self._ensure_lora_cfg()
        from production_stack_tpu.models import lora as lora_mod
        if src.startswith("random:"):
            import jax
            return lora_mod.random_adapter(
                self.model_cfg, lcfg,
                jax.random.PRNGKey(int(src.split(":", 1)[1])))
        return lora_mod.load_adapter_npz(self.model_cfg, lcfg, src)

    def load_adapter(self, name: str, src: str) -> bool:
        """Load a LoRA adapter at runtime and start serving it as model
        ``name``. Returns False when the name is already serving
        (idempotent); raises on any failure — the server answers a
        load failure with a structured 503 + Retry-After (a SHED, per
        the r9 shed!=sick contract: a failed weight fetch means "not
        now", never a breaker signal against the engine)."""
        with self._lock:
            if name == self.cfg.model or name in self.lora_ids:
                return False
            new_row = self._build_adapter(name, src)
            from production_stack_tpu.models import lora as lora_mod
            lcfg = self._ensure_lora_cfg()
            rows = self._lora_rows + [new_row]
            stacked = lora_mod.stack_adapters(self.model_cfg, lcfg, rows)
            # restack + device swap BEFORE publishing the id: a request
            # racing in on the new name must never select a row the
            # device pytree does not hold yet
            self.runner.set_lora(stacked, lcfg.scaling)
            self._lora_rows = rows
            self.lora_ids[name] = len(rows)
            self.served_models.append(name)
            self.adapter_loads += 1
            self.metrics.adapter_loads.inc()
            self.metrics.adapters_loaded.set(len(self.lora_ids))
            logger.info("adapter %s loaded from %s (id=%d, %d rows "
                        "stacked)", name, src, len(rows), len(rows))
            return True

    def evict_adapter(self, name: str) -> None:
        """Stop serving adapter ``name``. Raises KeyError when unknown
        (the server answers 404). The stacked row is tombstoned, not
        freed: in-flight sequences carry the adapter id in their device
        sampling rows, and id stability is what keeps them valid —
        only the NAME leaves the catalog, so new requests 404 at
        resolve_model while old ones finish."""
        with self._lock:
            if name not in self.lora_ids:
                raise KeyError(f"adapter {name!r} is not loaded; "
                               f"serving {self.served_models}")
            del self.lora_ids[name]
            self.served_models.remove(name)
            self.adapter_evictions += 1
            self.metrics.adapter_evictions.inc()
            self.metrics.adapters_loaded.set(len(self.lora_ids))
            logger.info("adapter %s evicted (row tombstoned)", name)

    def add_request(self, prompt_tokens: List[int],
                    options: Optional[SamplingOptions] = None,
                    seq_id: Optional[str] = None,
                    model: Optional[str] = None,
                    deadline: Optional[float] = None) -> str:
        seq_id = seq_id or f"seq-{next(self._id_counter)}"
        options = options or SamplingOptions()
        if options.logit_bias:
            # validate at the ENGINE boundary (callers' thread): a bad
            # map must 400 here, not poison step() with an
            # IndexError/OverflowError the engine loop would retry
            # forever
            from production_stack_tpu.engine.sampler import LOGIT_BIAS_K
            if len(options.logit_bias) > LOGIT_BIAS_K:
                raise ValueError(
                    f"logit_bias supports at most {LOGIT_BIAS_K} "
                    f"entries (got {len(options.logit_bias)})")
            V = self.model_cfg.vocab_size
            bad = [t for t in options.logit_bias
                   if not 0 <= int(t) < V]
            if bad:
                raise ValueError(
                    f"logit_bias token id {bad[0]} out of range for "
                    f"vocab size {V}")
        # penalty ranges (vLLM/OpenAI contracts): out-of-range values
        # would silently produce garbage logits, not errors
        if not options.repetition_penalty > 0:
            raise ValueError(
                f"repetition_penalty must be > 0 "
                f"(got {options.repetition_penalty})")
        for fname in ("presence_penalty", "frequency_penalty"):
            val = getattr(options, fname)
            if not -2.0 <= val <= 2.0:
                raise ValueError(
                    f"{fname} must be in [-2, 2] (got {val})")
        if not 0.0 <= options.min_p <= 1.0:
            raise ValueError(f"min_p must be in [0, 1] "
                             f"(got {options.min_p})")
        if options.min_tokens < 0:
            raise ValueError(f"min_tokens must be >= 0 "
                             f"(got {options.min_tokens})")
        if options.min_tokens and options.stop_token_ids:
            # the floor must ban these ids on-device; the mask array is
            # a fixed small width (sampler.MIN_TOKENS_STOP_K)
            from production_stack_tpu.engine.sampler import (
                MIN_TOKENS_STOP_K)
            if len(options.stop_token_ids) > MIN_TOKENS_STOP_K:
                raise ValueError(
                    f"min_tokens supports at most {MIN_TOKENS_STOP_K} "
                    f"stop_token_ids (got {len(options.stop_token_ids)})")
        seq = Sequence(seq_id=seq_id, prompt_tokens=list(prompt_tokens),
                       options=options,
                       adapter_id=self.resolve_model(model),
                       deadline=deadline,
                       detok=DetokenizeStream(self.tokenizer))
        if seq.options.guided_regex:
            from production_stack_tpu.engine import guided
            # compiled per (pattern, tokenizer) with an LRU cache; a bad
            # pattern raises here, on the caller's thread, as ValueError
            seq.grammar = guided.compile_grammar(seq.options.guided_regex,
                                                 self.tokenizer)
        if self.connector is not None:
            # tier lookup + D2H-side fetch runs here, on the caller's
            # thread — never on the engine loop
            seq.kv_prefetch = self.connector.prefetch(
                seq.prompt_tokens, salt=self._adapter_salt(seq.adapter_id))
            if seq.kv_prefetch is not None:
                seq.kv_prefetch_wait_s = seq.kv_prefetch.wait_s
                seq.kv_cached_tokens = seq.kv_prefetch.cached_tokens
        with self._lock:
            # bounded admission: shed at submit rather than queue
            # forever. Admission happens only at step time, so a fresh
            # submit ALWAYS lands in waiting first — the bound is
            # therefore on waiting beyond what the free slots will
            # absorb on the next pass (max_waiting_seqs=0 = "shed
            # anything that cannot be admitted immediately", not "shed
            # everything"). Only never-admitted sequences count —
            # preempted ones re-queue at the front and must not be
            # double-counted against new arrivals (they already hold a
            # client stream).
            if self.cfg.max_waiting_seqs is not None:
                depth = sum(1 for s in self.scheduler.waiting
                            if not s.output_tokens)
                # free slots absorb that much of the queue on the next
                # pass — minus the preempted sequences queued ahead of
                # everyone (recompute-first), which reclaim slots
                # before any fresh arrival
                preempted = len(self.scheduler.waiting) - depth
                allowance = self.cfg.max_waiting_seqs + max(
                    0, len(self.scheduler.free_slots) - preempted)
                if depth >= allowance:
                    self.metrics.admission_rejected.inc()
                    raise AdmissionRejected(
                        depth, self.estimated_queue_delay_s())
            self.scheduler.add(seq)
            self.seqs[seq_id] = seq
        return seq_id

    def abort(self, seq_id: str) -> bool:
        with self._lock:
            seq = self.seqs.get(seq_id)
            slot = seq.slot if seq is not None else -1
            ok = self.scheduler.abort(seq_id)
            if ok:
                self._park_slot(slot)
                if seq is not None:
                    self._free_seq_blocks(seq)
                    self._remember(seq)
            self._refresh_gauges()
            return ok

    # ------------------------------------------------------------------

    def step(self) -> List[StepOutput]:
        """One engine iteration: at most one prefill chunk AND one decode
        window — interleaved 1:1, so running sequences keep their token
        cadence while a long prompt prefills chunk by chunk (no
        head-of-line blocking; the reference exposes the same property as
        --enable-chunked-prefill, reference:
        helm/templates/deployment-vllm-multi.yaml:69-72)."""
        with self._lock:
            outputs: List[StepOutput] = []
            # overload protection: drop expired-deadline / over-delayed
            # sequences from the waiting queue BEFORE admission, so no
            # prefill compute is burned on a request whose client has
            # already given up (ISSUE 4; docs/engine.md)
            delay_cap = self.cfg.max_queue_delay_ms
            expired = self.scheduler.expire_waiting(
                max_queue_delay_s=delay_cap / 1e3
                if delay_cap is not None else None)
            for seq in expired:
                self._free_seq_blocks(seq)
                self._remember(seq)
                if seq.finish_reason == "deadline":
                    self.metrics.deadline_expired.inc()
                else:
                    self.metrics.queue_delay_shed.inc()
                logger.info("dropped %s while waiting (%s): queued "
                            "%.0fms", seq.seq_id, seq.finish_reason,
                            1e3 * (time.monotonic() - seq.arrival_time))
                drop_now = time.monotonic()
                # a WAITING-dropped request's whole remaining life IS
                # queue wait — close its open interval so shed storms
                # show up in the phase histograms, not just counters
                seq.queue_wait_s += drop_now - seq.enqueued_time
                self.metrics.engine_phases.observe(
                    "queue_wait", seq.queue_wait_s)
                outputs.append(StepOutput(
                    seq.seq_id, None, "", True, seq.finish_reason,
                    timing=self._seq_timing(seq, drop_now)))
            works, decode_seqs = self.scheduler.schedule()
            if works:
                # drain the in-flight window first: it was dispatched
                # from pre-prefill state and stays valid; the prefill's
                # writes are ordered after it on device
                outputs.extend(self._drain_decode())
                outputs.extend(self._do_prefill(works))
                # re-snapshot: sequences whose prefill just completed are
                # RUNNING now and must join this step's decode window —
                # the device generates tokens for every live row, and a
                # row the host skipped would desync the device carry
                decode_seqs = list(self.scheduler.running.values())
            if decode_seqs or self._inflight:
                if not self._inflight:
                    self._dispatch_decode(decode_seqs)
                # optimistic pipelining: top the device queue up to
                # cfg.pipeline_depth windows BEFORE blocking on the front
                # window's sync — with window N+1 already queued behind
                # N, the device starts N+1 the instant N retires instead
                # of idling one host round-trip (the dominant per-window
                # cost when the chip sits behind a high-RTT tunnel), and
                # it keeps decoding while the host walks tokens (detok,
                # stop checks, callbacks). Valid because decode inputs
                # are device-carried: each window continues from its
                # predecessor's final tokens/positions regardless of
                # what the host decides; rows whose sequence turns out
                # to have finished are discarded at the next drain
                # (their writes only touch blocks still owned by the
                # finished sequence — never registered-prefix blocks,
                # which are always full). Only when the device carry is
                # self-contained: a dirty decode/sampling state means
                # the next dispatch must upload host mirrors, and
                # mid-processing mirrors lag the device (uploading them
                # would rewind live rows and duplicate tokens).
                self._top_up_pipeline()
                t_win = time.monotonic()
                synced = self._sync_inflight()
                # per-window host-visible decode latency: the blocking
                # device sync for one fused window — the batching-level
                # signal (how long a window takes end to end) the
                # roofline work reads next to the per-request phases
                self.metrics.engine_phases.observe(
                    "decode_window", time.monotonic() - t_win)
                outputs.extend(self._process_window(synced))
                if not self._inflight:
                    decode_seqs = list(self.scheduler.running.values())
                    if decode_seqs:
                        self._dispatch_decode(decode_seqs)
            self._maybe_defrag()
            self._refresh_gauges()
            return outputs

    def _maybe_defrag(self) -> None:
        """kvplane intra-replica defrag, between fused windows: if this
        step's admissions hit the fragmented-failure regime, compact
        the free list so the next allocations hand out dense block-id
        runs. Called under the engine lock at the end of step() — the
        one point where no allocation is mid-flight."""
        if not self.cfg.kvplane_defrag:
            return
        frag = self.block_mgr.alloc_failures_fragmented
        if frag > self._defrag_seen_failures:
            self._defrag_seen_failures = frag
            self.block_mgr.defrag()

    def migrate_out(self, max_seqs: int = 2,
                    target_blocks: int = 0) -> Dict[str, object]:
        """kvplane live migration, source side: publish the victim
        sequences' computed chunks to the shared tiers, preempt them
        (freeing their blocks for the admissions that were failing),
        flush the write-through, and hand back the chunk keys so the
        planner can warm the destination replica and re-home routing.

        Victims are the LEAST recently active sequences first (oldest
        ``last_active`` stamp, arrival time as the tie-break): their KV
        is the coldest on this replica, they are the least likely to be
        mid-burst, and the stall a migration adds lands on the request
        that has already waited longest — instead of yanking the
        hottest sequence just because it holds the most blocks.
        Preempted victims are re-prefetched from the tiers before their
        next admission, so migration costs them a tier read, not a
        recompute. A planner crash after this call leaves only
        published chunks + preempted sequences — both states the stack
        already recovers from (recompute + checksummed tier reads), so
        migration is torn-safe by construction."""
        if self.connector is None or not self.connector.cfg.is_producer:
            return {"migrated": [], "freed_blocks": 0, "keys": [],
                    "error": "kv tiering with a producer role is "
                             "required for migration"}
        keys: List[bytes] = []
        victims = []
        freed = 0
        with self._lock:
            candidates = list(self.scheduler.running.values()) \
                + list(self.scheduler._prefilling.values())
            candidates.sort(
                key=lambda s: (s.last_active, s.arrival_time))
            for seq in candidates:
                if len(victims) >= max(1, max_seqs):
                    break
                if target_blocks and freed >= target_blocks:
                    break
                held = len([b for b in seq.block_ids if b])
                if held == 0:
                    continue
                keys.extend(self.connector.on_migrate(
                    seq, salt=self._adapter_salt(seq.adapter_id)))
                self._preempt(seq)
                freed += held
                victims.append(seq)
            self.metrics.kvplane_migrations.inc(len(victims))
            self.metrics.kvplane_migrated_blocks.inc(freed)
        # outside the lock: make the published chunks tier-visible
        # before the planner acts on the keys, then re-prefetch each
        # victim so its re-admission injects instead of recomputing
        # (benign race: a victim admitted before its prefetch lands
        # simply recomputes, the pre-migration behavior)
        self.connector.flush(timeout=10.0)
        for seq in victims:
            pf = self.connector.prefetch(
                seq.prompt_tokens,
                salt=self._adapter_salt(seq.adapter_id))
            if pf is not None and seq.kv_prefetch is None:
                seq.kv_prefetch = pf
        return {"migrated": [s.seq_id for s in victims],
                "freed_blocks": freed,
                "keys": [k.hex() for k in keys]}

    def warm_chunks(self, hex_keys: List[str]) -> Dict[str, int]:
        """kvplane migration, destination side: pull the given chunk
        keys through the tier walk so hits promote into this replica's
        fastest tier (connector.warm_keys). Runs on the caller's
        thread — never the engine loop."""
        if self.connector is None:
            return {"warmed": 0, "missed": 0}
        try:
            keys = [bytes.fromhex(k) for k in hex_keys]
        except ValueError:
            return {"warmed": 0, "missed": len(hex_keys)}
        # connector.warmed_chunks totals delta-sync into
        # tpu:kvplane_warmed_chunks_total at scrape time
        warmed, missed = self.connector.warm_keys(keys)
        return {"warmed": warmed, "missed": missed}

    def _top_up_pipeline(self) -> None:
        """Queue optimistic decode windows behind the in-flight one(s)
        up to cfg.pipeline_depth, provided the device carry is
        self-contained (no pending mirror uploads) and the extra window
        is unlikely to be pure discarded work."""
        while (self._inflight
               and len(self._inflight) < self.cfg.pipeline_depth
               and not self._decode_dirty and not self._sampling_dirty
               and not (self.cfg.speculative_ngram_tokens
                        and self._hist_dirty)
               # mid-window admission preference: with a request
               # waiting AND a slot to admit it into, an extra queued
               # window only delays the admission pass it is waiting
               # for
               and not (self.cfg.window_adapt
                        and self._admission_imminent())
               and self._worth_dispatch_ahead()):
            ahead = sum(w[4] for w in self._inflight)
            if not self._dispatch_decode(
                    list(self.scheduler.running.values()), ahead=ahead):
                break

    def _worth_dispatch_ahead(self) -> bool:
        """Skip the optimistic window when every live sequence could
        reach its token budget within the windows already in flight —
        then the whole dispatch would likely be discarded work (and
        would delay the next admission wave by one window)."""
        inflight_steps = sum(w[4] for w in self._inflight)
        live = [s for s in self.scheduler.running.values()
                if s.status is SeqStatus.RUNNING]
        if not live:
            return False
        return any(
            s.options.max_tokens is None
            or s.options.max_tokens - len(s.output_tokens) > inflight_steps
            for s in live)

    # adaptive window sizing: the largest window bucket whose EXPECTED
    # dead fraction (finished-row tails, from remaining max_tokens
    # budgets + the EOS-rate horizon) stays under this budget. A hard
    # bound, not a target: real storms sit well below it because most
    # windows have no finishing row at all.
    _WINDOW_DEAD_BUDGET = 0.125

    def _choose_window(self, ahead: int) -> int:
        """Window length for the next decode dispatch (adaptive sizing,
        docs/engine.md "Continuous batching across windows").

        With ``window_adapt`` off this is the configured
        ``decode_window``. Otherwise pick the LARGEST bucket from
        ``decode_window_buckets`` whose expected dead fraction stays
        under ``_WINDOW_DEAD_BUDGET``:

        - **budget tails**: a row whose remaining ``max_tokens``
          budget ends inside the window contributes its tail
          ``W - remaining`` as dead steps. With one live row this
          degenerates to "the smallest bucket covering the remaining
          budget"; with a big churny batch it keeps windows LONG as
          long as the occasional tail is an acceptable fraction of
          ``live x W`` — ending the window at every first finish
          would multiply per-window dispatch overhead past what the
          saved tails buy back (measured on the r17 A/B);
        - **EOS-rate horizon**: rows that have recently been stopping
          on EOS/stop (not budget) are expected to stop at rate
          ``_eos_rate`` per row-step, contributing ``rate x W^2 / 2``
          expected tail steps per row — ``max_tokens`` gives no
          warning for natural stops, so long windows get charged for
          them the same way;
        - **mid-window admission**: a request waiting WITH a free
          slot to land in takes the next SHORTER bucket below the
          capped choice — finishing the window sooner runs the
          admission + prefill pass sooner, trading per-window fusion
          for time-to-join. One bucket, not the minimum (under churny
          closed loops someone is waiting at almost every dispatch),
          and only when admission can actually happen: with the batch
          full, the waiter needs a finish first — which the dead
          budget above already steers the window toward.

        ``ahead`` steps already in flight count against the budgets
        (an optimistic window continues from where the queued ones
        will end)."""
        cfg = self.cfg
        if not cfg.window_adapt:
            return cfg.decode_window
        buckets = cfg.decode_window_buckets
        live = [s for s in self.scheduler.running.values()
                if s.status is SeqStatus.RUNNING]
        if not live:
            return buckets[0]
        # no spec_w scaling: speculation pins the full fixed geometry
        # at config time (window_adapt is forced off), so this only
        # ever runs with one token per row-step
        budgets = [max(0, s.options.max_tokens - len(s.output_tokens)
                       - ahead)
                   for s in live if s.options.max_tokens is not None]
        cap = buckets[0]
        for w in buckets:
            tail = sum(max(0, w - b) for b in budgets)
            tail += self._eos_rate * len(live) * w * w / 2.0
            if tail <= self._WINDOW_DEAD_BUDGET * len(live) * w:
                cap = w
        if self._admission_imminent():
            i = buckets.index(cap)
            cap = buckets[max(0, i - 1)]
        return cap

    def _admission_imminent(self) -> bool:
        """A request is waiting and a slot is free to admit it into:
        the next scheduler pass will admit — every queued window step
        between now and then is time-to-join the waiter pays. A pass
        that just deferred the head waiter on the KV admission gate
        (`kv_deferred`) negates that premise: under pool pressure the
        next pass will NOT admit, and shortening windows / pausing the
        pipeline would cost fusion and device occupancy for nothing."""
        return bool(self.scheduler.waiting
                    and self.scheduler.free_slots
                    and not self.scheduler.kv_deferred)

    @staticmethod
    def _grid_hot(seqs) -> bool:
        """True when this batch composition lands on an executable
        variant the warmup grid actually compiled — greedy or
        plain-sampled, with no seeded/guided/penalized/top-k rows.
        Only those variants may dispatch at adapted (batch, window)
        geometry: every other variant warms at the FULL shape alone,
        and adapting it would pay a cold multi-second compile per
        geometry reached, mid-serving (the pre-r17 fixed dispatch
        paid exactly one lazy compile per variant — keep that). Both
        hot variants are closed under row subsetting, so a preemption
        between this check and the dispatch cannot turn a hot window
        cold."""
        return (all(s.options.seed is None and s.grammar is None
                    and not s.options.shaped
                    and not s.options.top_logprobs for s in seqs)
                and (all(s.options.temperature <= 0.0 for s in seqs)
                     or all(s.options.top_p >= 1.0
                            and not s.options.top_k
                            and not s.options.min_p for s in seqs)))

    def _compact_slots(self) -> None:
        """Remap RUNNING sequences into the lowest slots (skipping
        slots held by still-prefilling sequences) so the decode batch
        bucket tracks the LIVE batch instead of historical slot
        positions. Only legal between windows (nothing in flight):
        the remap rewrites the host mirrors, and the next dispatch
        rebuilds every device carry from them (the move marks decode/
        sampling/history dirty; penalty counts and guided ids are
        rebuilt from the sequences at dispatch). KV never moves — a
        slot only indexes a block-table row, so the remap is two table
        rows per moved sequence, not a cache copy."""
        running = sorted(self.scheduler.running.values(),
                         key=lambda s: s.slot)
        if not running:
            return
        busy = {s.slot for s in self.scheduler._prefilling.values()}
        target = 0
        for seq in running:
            while target in busy:
                target += 1
            if seq.slot != target:
                # target < seq.slot and every lower-slotted live row
                # already sits at an earlier target, so target is free
                self._move_slot(seq, target)
            target += 1

    def _move_slot(self, seq: Sequence, new: int) -> None:
        """Move a RUNNING sequence's slot: scheduler maps, every host
        sampling/decode/guided mirror row, and the block-table row —
        coherently, so the next dispatch's uploads see the sequence at
        its new index."""
        old = seq.slot
        sched = self.scheduler
        del sched.running[old]
        sched.running[new] = seq
        sched.free_slots.remove(new)
        seq.slot = new
        for arr in (self._slot_token, self._slot_pos, self._slot_temp,
                    self._slot_top_p, self._slot_top_k,
                    self._slot_adapter, self._slot_seed,
                    self._slot_presence, self._slot_frequency,
                    self._slot_repetition, self._slot_min_p,
                    self._slot_min_tokens, self._slot_prompt_len,
                    self._slot_bias_ids, self._slot_bias_vals,
                    self._slot_stop_ids, self._slot_gstate):
            arr[new] = arr[old]
        self._set_table_row(new, seq.block_ids)
        # park AFTER copying (resets old's mirrors, marks carries
        # dirty); the moved row's sampling differs from the parked
        # defaults park left at `new`, so force the sampling re-upload
        self._park_slot(old)
        self._set_table_row(old, [])
        sched._free_slot(old)
        self._sampling_dirty = True

    def _do_prefill(self, works) -> List[StepOutput]:
        """Batch-prefill every scheduled chunk: one device dispatch per
        chunk-length bucket (usually one total), all slots at once."""
        outputs: List[StepOutput] = []
        for w in works:
            self._sync_sampling(w.seq)
        self._ensure_dev_sampling()
        by_bucket: Dict[int, list] = {}
        for w in works:
            by_bucket.setdefault(self.cfg.bucket_for(len(w.chunk)),
                                 []).append(w)
        B, S = self.cfg.max_num_seqs, self.cfg.max_model_len
        for bucket, group in sorted(by_bucket.items()):
            tokens = np.zeros((B, bucket), np.int32)
            starts = np.full((B,), S, np.int32)   # parked rows: clamp on S-1
            lengths = np.ones((B,), np.int32)
            kv_need = bucket
            for w in group:
                slot = w.seq.slot
                tokens[slot, :len(w.chunk)] = w.chunk
                starts[slot] = w.start
                lengths[slot] = len(w.chunk)
                kv_need = max(kv_need, w.start + bucket)
            kv_len = self.cfg.kv_bucket_for(min(kv_need, S))
            gtable = gids = gstates = None
            if any(w.seq.grammar is not None for w in group):
                gtable, gid_map = self._ensure_guided_table()
                gids = np.zeros((B,), np.int32)
                gstates = np.zeros((B,), np.int32)
                for w in group:
                    if w.seq.grammar is not None:
                        gids[w.seq.slot] = gid_map[w.seq.options.guided_regex]
                        gstates[w.seq.slot] = w.seq.fsm_state
            penalized = any(w.seq.options.shaped for w in group
                            if w.is_last)
            topk = max((w.seq.options.top_logprobs for w in group
                        if w.is_last), default=0)
            if topk:
                topk = 1 << (topk - 1).bit_length()
            if penalized:
                # the group's last-chunk rows sample their first token
                # with shaped logits; mirrors are current (all in-flight
                # windows were drained before prefill). The next decode
                # dispatch rebuilds AGAIN on the same step — not
                # redundant: that rebuild includes the first tokens
                # this very prefill samples, which prefill executables
                # don't record device-side
                self.runner.set_penalty_state(*self._penalty_arrays())
            ids_dev, lps_dev, tops_dev = self.runner.prefill(
                tokens, starts, lengths, self._dev_sampling, kv_len,
                guide_table=gtable, guide_ids=gids,
                guide_states=gstates, penalized=penalized, topk=topk)
            # bucket-padding accounting: the dispatch computed B*bucket
            # positions; only the scheduled chunks' tokens were real
            self.eff.note_prefill(
                bucket=bucket, batch=B,
                real_tokens=sum(len(w.chunk) for w in group))
            ids = lps = tops = None
            for w in group:
                self.scheduler.on_prefill_done(w)
                self.metrics.prompt_tokens.inc(len(w.chunk))
                if (self.cfg.enable_prefix_caching
                        and not w.seq.rolled_blocks):
                    # LIVE progressive registration: a full block's
                    # K/V is final the moment its last position is
                    # written (write-then-attend; full blocks are
                    # never rewritten), so a concurrent same-prefix
                    # request can attach it WITHOUT waiting for this
                    # sequence to finish. The hasher chain state rides
                    # the sequence so each chunk keys only its NEW
                    # blocks (O(L^2) otherwise on long prompts).
                    seq = w.seq
                    seq.reg_state = self.block_mgr.register_incremental(
                        seq.prefill_tokens[:seq.num_prefilled],
                        seq.block_ids, seq.reg_state,
                        salt=self._adapter_salt(seq.adapter_id))
                if self.connector is not None:
                    # progressive publish: disagg decode engines can pull
                    # the prefix while later chunks still prefill
                    self.connector.on_prefill_progress(
                        w.seq, salt=self._adapter_salt(w.seq.adapter_id))
                if not w.is_last:
                    continue
                seq = w.seq
                if seq.output_tokens:
                    # preemption-recompute resume: emitted output was
                    # teacher-forced back in; the prefill's sampled id
                    # is discarded (the last emitted token is the next
                    # decode input — _sync_slot restores it)
                    self._sync_slot(seq)
                    continue
                if ids is None:
                    ids = np.asarray(ids_dev)  # one sync per bucket group
                    lps = np.asarray(lps_dev)
                    tops = (None if tops_dev is None else
                            (np.asarray(tops_dev[0]),
                             np.asarray(tops_dev[1])))
                # prompt fully prefilled: the sampled id is the first
                # output token
                k = seq.options.top_logprobs
                alts = None
                if tops is not None and k:
                    alts = [(int(t), float(l)) for t, l in
                            zip(tops[0][seq.slot, :k],
                                tops[1][seq.slot, :k])
                            if l > -1e29]
                seq.first_token_time = time.monotonic()
                self.metrics.ttft.observe(
                    seq.first_token_time - seq.arrival_time)
                outputs.extend(self._accept_token(
                    seq, int(ids[seq.slot]), float(lps[seq.slot]),
                    alts))
        # prefill changed slot contents/positions: refresh decode carry
        self._decode_dirty = True
        self._hist_dirty = True
        return outputs

    def _ensure_dev_sampling(self) -> None:
        if self._sampling_dirty:
            self._dev_sampling = SamplingParams(
                temperature=jnp.asarray(self._slot_temp),
                top_p=jnp.asarray(self._slot_top_p),
                top_k=jnp.asarray(self._slot_top_k),
                adapter=jnp.asarray(self._slot_adapter),
                seed=jnp.asarray(self._slot_seed),
                presence=jnp.asarray(self._slot_presence),
                frequency=jnp.asarray(self._slot_frequency),
                repetition=jnp.asarray(self._slot_repetition),
                min_p=jnp.asarray(self._slot_min_p),
                min_tokens=jnp.asarray(self._slot_min_tokens),
                prompt_len=jnp.asarray(self._slot_prompt_len),
                bias_ids=jnp.asarray(self._slot_bias_ids),
                bias_vals=jnp.asarray(self._slot_bias_vals),
                stop_ids=jnp.asarray(self._slot_stop_ids))
            self._sampling_dirty = False

    def _penalty_arrays(self):
        """[B, V] generated-token counts + prompt membership for every
        live slot, rebuilt from the sequences (composition changes
        only; within windows the device carries counts itself)."""
        B, V = self.cfg.max_num_seqs, self.model_cfg.vocab_size
        counts = np.zeros((B, V), np.int32)
        seen = np.zeros((B, V), bool)
        live = list(self.scheduler.running.values()) + list(
            self.scheduler._prefilling.values())
        for s in live:
            if s.slot < 0:
                continue
            if s.output_tokens:
                out = np.asarray(s.output_tokens, np.int64)
                np.add.at(counts[s.slot], np.clip(out, 0, V - 1), 1)
            if s.prompt_tokens:
                pt = np.clip(np.asarray(s.prompt_tokens, np.int64),
                             0, V - 1)
                seen[s.slot][pt] = True
        return counts, seen

    def _ensure_guided_table(self):
        """(Re)build the stacked guided-decoding table for the distinct
        grammars among admitted sequences. Returns (device table
        [G+1, S, V] or None, {pattern: row index}). Row 0 is the
        unguided placeholder; vocab columns beyond a grammar's tokenizer
        range stay forbidden."""
        active = list(self.scheduler.running.values()) + list(
            self.scheduler._prefilling.values())
        pats = sorted({s.options.guided_regex for s in active
                       if s.grammar is not None})
        if not pats:
            return None, {}
        key = tuple(pats)
        if key != self._guided_key:
            from production_stack_tpu.engine import guided as guided_mod
            grammars = [guided_mod.compile_grammar(p, self.tokenizer)
                        for p in pats]
            # pad S and G up to power-of-two buckets: the decode/prefill
            # executables are keyed on the table shape, so raw sizes
            # would recompile on every pattern-set change
            S = max(g.n_states for g in grammars)
            S = 1 << (S - 1).bit_length() if S > 1 else 1
            G = len(pats) + 1
            G = 1 << (G - 1).bit_length()
            V = self.model_cfg.vocab_size
            table = np.full((G, S, V), -1, np.int32)
            for gi, g in enumerate(grammars, start=1):
                s, v = g.token_next.shape
                table[gi, :s, :min(v, V)] = g.token_next[:, :V]
            self._guided_table = jnp.asarray(table)
            self._guided_gids = {p: i + 1 for i, p in enumerate(pats)}
            self._guided_key = key
            self._decode_dirty = True   # gids/states must re-upload
        return self._guided_table, self._guided_gids

    def _dispatch_decode(self, decode_seqs, ahead: int = 0) -> bool:
        """Launch one decode window (async dispatch; no host sync).

        With ``window_adapt`` on, the dispatch tracks the LIVE batch
        along three levers (docs/engine.md "Continuous batching across
        windows"): live rows are first compacted into the low slots
        (only between windows — the remap rebuilds every device carry
        from the host mirrors, which are only current when nothing is
        in flight), the batch bucket is the smallest one covering
        them (parked rows above it are not computed at all), and the
        window length comes from the live rows' remaining budgets +
        the EOS-rate horizon — one bucket shorter when admission is
        imminent, so waiters join sooner (_choose_window). Windows
        needing a variant outside the warmed grid (seeded / guided /
        penalized / top-k / full-sort sampling) pin the full fixed
        geometry instead (_grid_hot).

        ahead > 0 = optimistic dispatch while the previous window's
        tokens are still unprocessed on the host: device positions are
        `ahead` steps past the host mirrors, so block coverage and the
        kv bucket are computed from position + ahead. An optimistic
        dispatch must leave host state untouched by the device's view:
        it returns False WITHOUT dispatching if it would have to
        preempt (parking rewrites the decode carry) or upload host
        mirrors (they lag the device by `ahead` steps until the synced
        window is processed) — the caller then falls back to the
        ordinary process-first path. It also keeps the carry's batch
        bucket (a bucket change is a mirror upload by definition)."""
        live0 = [s for s in self.scheduler.running.values()
                 if s.status is SeqStatus.RUNNING]
        adapt = self.cfg.window_adapt and self._grid_hot(live0)
        if adapt and live0:
            # the warmup grid exists at the SMALLEST kv bucket only:
            # adapted geometry at a larger bucket would compile cold
            # per (batch, window) combination reached mid-serving —
            # pin the full fixed geometry there instead (one lazy
            # compile per variant, the pre-r17 cost). Long-context
            # fleets that want adaptation should size
            # --kv-len-buckets so the first bucket spans their
            # serving contexts. Probed at the largest possible
            # window so the actual kv pick (made after W below) can
            # never exceed the probe.
            probe = (max(s.next_position for s in live0)
                     + self.cfg.decode_window + ahead + 1)
            adapt = (self.cfg.kv_bucket_for(
                min(probe, self.cfg.max_model_len))
                == self.cfg.kv_len_buckets[0])
        if ahead == 0 and adapt and not self._inflight:
            self._compact_slots()
        W = self._choose_window(ahead) if adapt else self.cfg.decode_window
        if self._roll_window:
            # free behind-window blocks BEFORE growing coverage: the
            # reclaimed blocks feed this very window's growth
            self._roll_windows(decode_seqs)
        # block coverage first: every live slot's table must span the
        # whole window (worst case: speculation emits spec+1 per step).
        # Pool pressure preempts youngest-first; a sequence that cannot
        # be covered even then is preempted itself (recompute later).
        spec_w = self.cfg.speculative_ngram_tokens + 1
        horizon = (W + ahead) * spec_w + 1
        for s in list(decode_seqs):
            if s.status is not SeqStatus.RUNNING:
                continue   # already preempted as a victim this pass
            covered = self._ensure_blocks(s, s.next_position + horizon,
                                          allow_preempt=ahead == 0)
            if not covered:
                if ahead:
                    return False   # pool pressure: no optimistic window
                self._preempt(s)
        decode_seqs = list(self.scheduler.running.values())
        if not decode_seqs:
            return False
        # batch bucket: smallest executable covering every live slot
        # (compaction just packed them low). An optimistic dispatch
        # continues the device carry, whose batch is fixed.
        if ahead:
            batch = self._carry_batch
            if not adapt and batch != self.cfg.max_num_seqs:
                # a pinned-geometry window (non-hot variant, or the kv
                # probe crossed above the warmed grid's bucket) would
                # continue a BUCKETED carry here — that (carry batch,
                # full window, higher kv) executable was never warmed,
                # and an optimistic dispatch may not reshape the
                # carry. Fall back to the process-first path: its
                # ahead == 0 dispatch re-uploads at the full batch.
                return False
        else:
            # a non-hot variant window (adapt False) pins the full
            # batch; crossing between that and a bucketed hot window
            # is a carry reshape like any other bucket change
            batch = (self.cfg.batch_bucket_for(
                max(s.slot for s in decode_seqs) + 1)
                if adapt else self.cfg.max_num_seqs)
            if batch != self._carry_batch:
                self._decode_dirty = True
                self._hist_dirty = True
        max_pos = max(s.next_position for s in decode_seqs)
        greedy = all(s.options.temperature <= 0.0 for s in decode_seqs)
        self._ensure_dev_sampling()
        gtable = gids = None
        if any(s.grammar is not None for s in decode_seqs):
            gtable, gid_map = self._ensure_guided_table()
            gids = np.zeros((len(self._slot_gstate),), np.int32)
            for s in decode_seqs:
                if s.grammar is not None:
                    gids[s.slot] = gid_map[s.options.guided_regex]
        # penalized windows carry [B, V] token counts and shape logits
        # before sampling; unshaped batches keep the ordinary executables
        penalized = any(s.options.shaped for s in decode_seqs)
        # OpenAI top_logprobs alternatives: one executable per
        # power-of-two K bucket, only when some live row asks
        topk = max((s.options.top_logprobs for s in decode_seqs),
                   default=0)
        if topk:
            topk = 1 << (topk - 1).bit_length()
        # n-gram speculation is PER-ROW: a row speculates iff it is
        # greedy (argmax verify is exact), unguided (drafts would
        # bypass the DFA mask), unshaped (draft verification ignores
        # the adjusted logits), and asked for no alternatives
        # (macro-steps emit several tokens). Ineligible rows single-
        # step inside the same window — one presence_penalty user
        # costs only their own row its speculation, not the batch's.
        spec_rows = [s for s in decode_seqs
                     if s.options.temperature <= 0.0
                     and s.grammar is None and not s.options.shaped
                     and not s.options.top_logprobs]
        spec = (self.cfg.speculative_ngram_tokens if spec_rows else 0)
        spec_ok = None
        if spec:
            spec_ok = np.zeros((self.cfg.max_num_seqs,), bool)
            for s in spec_rows:
                spec_ok[s.slot] = True
        kv_len = self.cfg.kv_bucket_for(
            min(max_pos + (W + ahead) * (spec + 1) + 1,
                self.cfg.max_model_len))
        if ahead and (self._decode_dirty or self._sampling_dirty):
            # the guided-table rebuild (or any path above) dirtied the
            # carry: uploading mid-processing mirrors would rewind the
            # device — bail, the normal path re-dispatches after
            # processing
            return False
        hist = None
        if spec and (self._hist_dirty or self._decode_dirty):
            # only built for windows that will actually read it; spec=0
            # windows skip the [B, S] host build + upload entirely
            hist = np.zeros((batch,
                             self.cfg.max_model_len), np.int32)
            for s in decode_seqs:
                row = s.prompt_tokens + s.output_tokens
                hist[s.slot, :len(row)] = row
            self._hist_dirty = False
        if penalized and self._decode_dirty:
            # counts/prompt-membership upload rides the same trigger as
            # the decode carry: any composition change. Within windows
            # the device updates counts itself (runner._decode_impl)
            counts_arr, seen_arr = self._penalty_arrays()
            self.runner.set_penalty_state(counts_arr[:batch],
                                          seen_arr[:batch])
        if self._decode_dirty or hist is not None:
            # mirrors are uploaded at the dispatch's batch bucket: the
            # runner's carry shape IS the executable's batch axis
            self.runner.set_decode_state(self._slot_token[:batch],
                                         self._slot_pos[:batch],
                                         self._slot_gstate[:batch], hist)
            self._decode_dirty = False
        self._carry_batch = batch
        seeded = any(s.options.seed is not None for s in decode_seqs)
        # the API-default sampling shape (top_p=1, top_k=0, min_p=0)
        # needs no [B, V] sort — a separate executable skips it
        # (sampler.py); min_p truncation lives on the sorted path
        plain = all(s.options.top_p >= 1.0 and not s.options.top_k
                    and not s.options.min_p
                    for s in decode_seqs)
        ids_dev, lps_dev, counts_dev, tops_dev = self.runner.decode(
            self._dev_sampling, steps=W, kv_len=kv_len, greedy=greedy,
            seeded=seeded, guide_table=gtable, guide_ids=gids, spec=spec,
            spec_ok=spec_ok, plain=plain, penalized=penalized, topk=topk)
        self._inflight.append((ids_dev, lps_dev, counts_dev, tops_dev,
                               W, list(decode_seqs), time.monotonic(),
                               spec_ok, kv_len, batch))
        return True

    def _drain_decode(self) -> List[StepOutput]:
        """Sync + process every in-flight window. A sequence that
        finished or aborted after dispatch simply has its rows discarded
        (its slot is parked and the decode carry marked dirty)."""
        outputs: List[StepOutput] = []
        while self._inflight:
            outputs.extend(self._process_window(self._sync_inflight()))
        return outputs

    def _sync_inflight(self):
        """Device->host sync of the OLDEST in-flight window's arrays (no
        token processing): (ids, lps, counts, tops, W, seqs, t0,
        spec_ok, kv_len, batch) or None. t0
        is clamped to the previous sync's completion so pipelined
        windows report per-window wall, not time-since-dispatch."""
        if not self._inflight:
            return None
        (ids_dev, lps_dev, counts_dev, tops_dev, W, seqs,
         t0, spec_ok, kv_len, batch) = self._inflight.pop(0)
        t0 = max(t0, getattr(self, "_last_sync_t", 0.0))
        ids = np.asarray(ids_dev)  # the window's single sync
        lps = np.asarray(lps_dev)
        counts = None if counts_dev is None else np.asarray(counts_dev)
        tops = (None if tops_dev is None else
                (np.asarray(tops_dev[0]), np.asarray(tops_dev[1])))
        self._last_sync_t = time.monotonic()
        return (ids, lps, counts, tops, W, seqs, t0, spec_ok, kv_len,
                batch)

    def _process_window(self, synced) -> List[StepOutput]:
        if synced is None:
            return []
        ids, lps, counts, tops, W, seqs, t0, spec_ok, kv_len, B = synced
        dt = time.monotonic() - t0
        outputs: List[StepOutput] = []
        alive = [s for s in seqs if s.status is not SeqStatus.FINISHED]
        walkers = len(alive)   # rows that will actually walk steps
        # window efficiency accounting: every row of the DISPATCHED
        # batch bucket B computes W steps of P positions each (P =
        # spec+1 under speculation). real counts tokens the client
        # keeps (one per _accept_token); non-live rows inside the
        # bucket are pure padding; everything else a live row computed
        # but did not emit — finished-row tails, rows finished/aborted
        # between dispatch and drain, rejected draft positions — is
        # dead.
        P = ids.shape[2] if counts is not None and ids.ndim == 3 else 1
        accepted = 0
        eos_stops = 0
        steps_walked = 0
        for j in range(W):
            steps_walked = j + 1
            still = []
            for seq in alive:
                if counts is None:
                    row = [(int(ids[seq.slot, j]),
                            float(lps[seq.slot, j]))]
                else:
                    # speculative macro-step: 1..spec+1 verified tokens
                    c = int(counts[seq.slot, j])
                    row = [(int(ids[seq.slot, j, t]),
                            float(lps[seq.slot, j, t]))
                           for t in range(c)]
                    if spec_ok is not None and spec_ok[seq.slot]:
                        self.metrics.spec_macro_steps.inc()
                        self.metrics.spec_accepted_tokens.inc(c - 1)
                # top_logprobs alternatives for rows that asked (trim
                # the window's K bucket to the request's k); a row with
                # alternatives never speculates (per-row spec_ok gate),
                # so its macro-steps always emit exactly one token and
                # the per-step alts attach unambiguously
                k = seq.options.top_logprobs
                alts = None
                if tops is not None and k:
                    ti, tl = tops
                    # guided rows mask forbidden tokens to -inf; those
                    # slots are garbage ids and would serialize as
                    # invalid JSON (-Infinity) — drop them (OpenAI
                    # allows fewer than k alternatives)
                    alts = [(int(t), float(l)) for t, l in
                            zip(ti[seq.slot, j, :k], tl[seq.slot, j, :k])
                            if l > -1e29]
                finished = False
                for token, lp in row:
                    accepted += 1
                    outs = self._accept_token(seq, token, lp, alts)
                    outputs.extend(outs)
                    if outs[-1].finished:
                        finished = True
                        if outs[-1].finish_reason == "stop":
                            eos_stops += 1
                        break
                if not finished:
                    still.append(seq)
            alive = still
            if not alive:
                break
        # per-token latency: the window wall over the steps actually
        # WALKED (every alive row retiring at step j means steps past
        # j never produced host-visible tokens — dividing by the full
        # W would understate ITL under adaptive/early-retired
        # windows); under speculation a macro-step emits several
        # verified tokens, so divide by the tokens actually emitted.
        # Observed after the walk (the divisor needs steps_walked);
        # histogram totals are order-independent.
        if accepted:
            per_tok_dt = dt / (steps_walked if counts is None
                               else accepted)
            for _ in range(accepted):
                self.metrics.per_token.observe(per_tok_dt)
        # EOS-rate EWMA feeding the adaptive window horizon
        # (_choose_window): observed per-row-step probability of a
        # non-length stop this window, over the rows that actually
        # WALKED steps — rows finished/aborted between dispatch and
        # drain never walked, and a window with no walkers says
        # nothing and leaves the rate alone (counting either would
        # bias the rate low and under-charge long windows for
        # finished tails).
        if walkers and steps_walked:
            obs = eos_stops / (walkers * steps_walked)
            self._eos_rate = 0.8 * self._eos_rate + 0.2 * obs
        pad = (B - len(seqs)) * W * P
        dead = B * W * P - pad - accepted
        self.eff.note_window(steps=W, positions=P, batch=B,
                             live_rows=len(seqs), kv_len=kv_len,
                             real=accepted, pad=pad, dead=dead,
                             window_s=dt)
        return outputs

    @staticmethod
    def _seq_timing(seq: Sequence, end: float) -> dict:
        """Terminal StepOutput timing payload: the monotonic phase
        stamps the SERVER turns into engine-side trace spans (it holds
        the HTTP context — traceparent — that this layer must not)."""
        return {
            "arrival": seq.arrival_time,
            "admit": seq.admit_time,
            "first_token": seq.first_token_time,
            "queue_wait_s": seq.queue_wait_s,
            "end": end,
            "prompt_tokens": len(seq.prompt_tokens),
            "output_tokens": len(seq.output_tokens),
            "kv_prefetch_wait_s": seq.kv_prefetch_wait_s,
            "kv_cached_tokens": seq.kv_cached_tokens,
        }

    def _accept_token(self, seq: Sequence, token: int,
                      logprob: Optional[float] = None,
                      top_alts=None) -> List[StepOutput]:
        seq.output_tokens.append(token)
        seq.last_active = time.monotonic()
        seq.output_logprobs.append(logprob)
        if seq.options.top_logprobs:
            seq.output_top.append(top_alts)
        if seq.grammar is not None:
            # host mirror of the device-carried DFA state (re-uploaded on
            # slot composition changes); DEAD can't be sampled, max() is
            # pure defense
            seq.fsm_state = max(
                seq.grammar.next_state(seq.fsm_state, token), 0)
        self.metrics.generation_tokens.inc()
        delta = seq.detok.push(token)
        opt = seq.options
        if (token in opt.stop_token_ids
                or (not opt.ignore_eos
                    and token == self.tokenizer.eos_token_id)):
            # a token that stops the sequence is excluded from the
            # returned text (vLLM semantics) — this keeps the text
            # aligned with logprobs (server._lp_skip). Any earlier
            # bytes the detokenizer was still buffering drop with it.
            delta = ""
        seq.output_text += delta
        reason = self._stop_reason(seq, token, delta)
        if reason is not None and reason != "stop":
            seq.output_text += seq.detok.flush()
        text_delta = seq.output_text[seq.chars_emitted:]
        seq.chars_emitted = len(seq.output_text)
        if reason is not None:
            if self.connector is not None:
                # extract while the slot still holds this sequence's KV —
                # dispatched before scheduler.finish can recycle the slot
                self.connector.on_finish(
                    seq, salt=self._adapter_salt(seq.adapter_id))
            # prefix caching: the full blocks stay in the pool under
            # their chain keys (zero-copy sharing); register BEFORE
            # free so refcount-0 registered blocks land in the
            # evictable LRU instead of the free list. Rolled sequences
            # skip registration: chain keys need the contiguous prefix,
            # whose early blocks are gone.
            if not seq.rolled_blocks:
                self.block_mgr.register(
                    (seq.prompt_tokens + seq.output_tokens)[:-1],
                    seq.block_ids,
                    salt=self._adapter_salt(seq.adapter_id))
            self._free_seq_blocks(seq)
            slot = seq.slot
            self.scheduler.finish(seq, reason)
            self._park_slot(slot)
            self._remember(seq)
            now = time.monotonic()
            dur = now - seq.arrival_time
            self.metrics.e2e_latency.observe(dur)
            # service-time EWMA feeding the queue-delay estimate the
            # load report / Retry-After are built on (includes queueing
            # — deliberately: it is what the next queued client will
            # actually wait through)
            self._service_ewma = 0.8 * self._service_ewma + 0.2 * dur
            # phase attribution: where this request's engine wall time
            # went (tracing.py; tpu:engine_phase_seconds). Plain-int
            # bucket increments — no prometheus objects on the loop.
            # queue_wait is the CUMULATIVE wait across admissions
            # (scheduler stamps it), so a preempted-and-requeued
            # sequence never counts an interval twice; a first token
            # emitted BEFORE the last admission (preemption after
            # first token) zeroes prefill and folds the re-prefill
            # into decode — the phases stay disjoint and sum to at
            # most the request's wall time.
            phases = self.metrics.engine_phases
            admit = seq.admit_time if seq.admit_time is not None \
                else seq.arrival_time
            first = seq.first_token_time if seq.first_token_time \
                is not None else now
            phases.observe("queue_wait", seq.queue_wait_s)
            phases.observe("prefill", max(0.0, first - admit))
            phases.observe("decode", max(0.0, now - max(first, admit)))
            return [StepOutput(seq.seq_id, token, text_delta, True, reason,
                               logprob, top_alts,
                               timing=self._seq_timing(seq, now))]
        self._sync_slot(seq)
        return [StepOutput(seq.seq_id, token, text_delta, False, None,
                           logprob, top_alts)]

    def _stop_reason(self, seq: Sequence, token: int,
                     delta: str) -> Optional[str]:
        """Stop decision; on a stop-string match, truncates seq.output_text
        so the stop string itself is never delivered (OpenAI semantics)."""
        opt = seq.options
        if token in opt.stop_token_ids:
            return "stop"
        if not opt.ignore_eos and token == self.tokenizer.eos_token_id:
            return "stop"
        if opt.stop and delta:
            # a match can straddle the delta boundary: search a window of
            # (longest stop - 1) chars before the delta
            for s in opt.stop:
                from_idx = max(0, len(seq.output_text) - len(delta) - len(s))
                idx = seq.output_text.find(s, from_idx)
                if idx != -1:
                    seq.output_text = seq.output_text[:idx]
                    return "stop"
        if len(seq.output_tokens) >= opt.max_tokens:
            return "length"
        if seq.num_tokens >= self.cfg.max_model_len:
            return "length"
        return None

    def _remember(self, seq: Sequence) -> None:
        """Retain finished sequences for inspection, bounded in count."""
        self._finished_order.append(seq.seq_id)
        while len(self._finished_order) > _FINISHED_RETENTION:
            old = self._finished_order.pop(0)
            self.seqs.pop(old, None)

    def _sync_slot(self, seq: Sequence) -> None:
        """Mirror the sequence's next decode input into the slot arrays."""
        slot = seq.slot
        self._slot_token[slot] = seq.output_tokens[-1]
        self._slot_pos[slot] = seq.next_position
        self._slot_gstate[slot] = seq.fsm_state
        self._sync_sampling(seq)

    def _sync_sampling(self, seq: Sequence) -> None:
        slot, opt = seq.slot, seq.options
        # normalize the user seed (any int, 0 and negatives included)
        # into a nonzero int32: 0 stays the "unseeded" sentinel only for
        # requests that sent no seed at all
        seed = 0 if opt.seed is None else (opt.seed % 0x7FFFFFFE) + 1
        plen = len(seq.prompt_tokens)
        bias_ids = np.full((self._slot_bias_ids.shape[1],), -1, np.int32)
        bias_vals = np.zeros_like(self._slot_bias_vals[slot])
        if opt.logit_bias:
            for i, (tid, val) in enumerate(sorted(opt.logit_bias.items())):
                bias_ids[i] = tid
                bias_vals[i] = val
        stop_ids = np.full((self._slot_stop_ids.shape[1],), -1, np.int32)
        if opt.min_tokens and opt.stop_token_ids:
            # only meaningful below the min_tokens floor; width validated
            # at add_request
            stop_ids[:len(opt.stop_token_ids)] = opt.stop_token_ids
        if (self._slot_temp[slot] != opt.temperature
                or self._slot_top_p[slot] != opt.top_p
                or self._slot_top_k[slot] != opt.top_k
                or self._slot_adapter[slot] != seq.adapter_id
                or self._slot_seed[slot] != seed
                or self._slot_presence[slot] != opt.presence_penalty
                or self._slot_frequency[slot] != opt.frequency_penalty
                or self._slot_repetition[slot] != opt.repetition_penalty
                or self._slot_min_p[slot] != opt.min_p
                or self._slot_min_tokens[slot] != opt.min_tokens
                or self._slot_prompt_len[slot] != plen
                or not np.array_equal(self._slot_bias_ids[slot], bias_ids)
                or not np.array_equal(self._slot_bias_vals[slot],
                                      bias_vals)
                or not np.array_equal(self._slot_stop_ids[slot],
                                      stop_ids)):
            self._slot_temp[slot] = opt.temperature
            self._slot_top_p[slot] = opt.top_p
            self._slot_top_k[slot] = opt.top_k
            self._slot_adapter[slot] = seq.adapter_id
            self._slot_seed[slot] = seed
            self._slot_presence[slot] = opt.presence_penalty
            self._slot_frequency[slot] = opt.frequency_penalty
            self._slot_repetition[slot] = opt.repetition_penalty
            self._slot_min_p[slot] = opt.min_p
            self._slot_min_tokens[slot] = opt.min_tokens
            self._slot_prompt_len[slot] = plen
            self._slot_bias_ids[slot] = bias_ids
            self._slot_bias_vals[slot] = bias_vals
            self._slot_stop_ids[slot] = stop_ids
            self._sampling_dirty = True

    def _park_slot(self, slot: int) -> None:
        """Return a freed slot's mirrors to the idle state (position S —
        its window writes clamp onto S-1, harmless because real K/V is
        always written before attention reads; see models/kv.py)."""
        if slot >= 0:
            self._slot_token[slot] = 0
            self._slot_pos[slot] = self.cfg.max_model_len
            self._slot_gstate[slot] = 0
            if (self._slot_presence[slot] or self._slot_frequency[slot]
                    or self._slot_repetition[slot] != 1.0
                    or self._slot_min_tokens[slot]
                    or self._slot_min_p[slot]
                    or self._slot_bias_ids[slot, 0] >= 0
                    or self._slot_stop_ids[slot, 0] >= 0):
                self._slot_presence[slot] = 0.0
                self._slot_frequency[slot] = 0.0
                self._slot_repetition[slot] = 1.0
                self._slot_min_p[slot] = 0.0
                self._slot_min_tokens[slot] = 0
                self._slot_bias_ids[slot, :] = -1
                self._slot_bias_vals[slot, :] = 0.0
                self._slot_stop_ids[slot, :] = -1
                self._sampling_dirty = True
            self._decode_dirty = True
            self._hist_dirty = True

    @property
    def embedding_source(self) -> str:
        """What powers /v1/embeddings: 'encoder:<name>' when a real
        bidirectional encoder is configured, else the documented
        'causal-mean-pool' approximation (mean-pooled hidden states of
        the causal chat model — API-shape parity, unvalidated
        embedding quality)."""
        if self.cfg.embedding_model:
            return f"encoder:{self._encoder_cfg().name}"
        return "causal-mean-pool"

    def _encoder_cfg(self):
        self._ensure_encoder()
        return self._enc_cfg

    @property
    def embedding_tokenizer(self):
        """Tokenizer for the embeddings path: the encoder checkpoint's
        own (BERT vocabs differ from chat vocabs — loaded and
        validated at startup by _ensure_encoder), else the serving
        tokenizer."""
        return self._embed_tok or self.tokenizer

    @property
    def max_embed_len(self) -> int:
        """Length cap for pooling inputs: the encoder's position table
        when one is configured, else the serving cache length."""
        if self.cfg.embedding_model:
            return self._encoder_cfg().max_position_embeddings
        return self.cfg.max_model_len

    def _ensure_encoder(self) -> None:
        """Lazily build the embedding encoder (models/encoder.py):
        a preset name (random weights — tests/demos) or a HF BertModel
        checkpoint dir."""
        if getattr(self, "_enc_params", None) is not None:
            return
        import os
        from production_stack_tpu.models import encoder as enc
        spec = self.cfg.embedding_model
        if os.path.isdir(spec):
            import json as _json
            with open(os.path.join(spec, "config.json")) as f:
                cfg = enc.config_from_hf_json(_json.load(f),
                                              name=os.path.basename(spec))
            params = enc.load_checkpoint(cfg, spec)
            # string inputs MUST tokenize with the checkpoint's own
            # vocab: the serving tokenizer's ids would gather-clamp
            # into the encoder's smaller embedding table and return
            # confidently wrong vectors. Missing tokenizer = startup
            # error, never a silent fallback.
            from production_stack_tpu.engine.tokenizer import load_tokenizer
            tok = load_tokenizer(spec, None)
            tok_vocab = getattr(tok, "vocab_size", None)
            if tok_vocab is None or tok_vocab > cfg.vocab_size:
                raise ValueError(
                    f"embedding checkpoint {spec} has no usable "
                    f"tokenizer (got vocab "
                    f"{tok_vocab} vs encoder vocab {cfg.vocab_size}); "
                    f"ship the model's tokenizer files in the "
                    f"checkpoint dir")
            self._embed_tok = tok
        else:
            cfg = enc.get_encoder_config(spec)
            params = enc.init_params(cfg, jax.random.PRNGKey(
                self.cfg.seed ^ 0xE9C0DE))
            logger.info("random-initialized embedding encoder %s "
                        "(preset; pass a checkpoint dir for real "
                        "embeddings)", cfg.name)
        self._enc_cfg, self._enc_params = cfg, params
        self._enc_fns = {}

    def _embed_batch(self, tokens: np.ndarray,
                     lengths: np.ndarray) -> np.ndarray:
        """One padded batch -> pooled [B, H] fp32, via the configured
        encoder or the causal-mean-pool fallback."""
        if not self.cfg.embedding_model:
            return np.asarray(self.runner.embed(tokens, lengths))
        self._ensure_encoder()
        from production_stack_tpu.models import encoder as enc
        key = tokens.shape
        fn = self._enc_fns.get(key)
        if fn is None:
            fn = self._enc_fns[key] = jax.jit(
                lambda p, t, ln: enc.encode(p, self._enc_cfg, t, ln))
        return np.asarray(fn(self._enc_params,
                             jnp.asarray(tokens, jnp.int32),
                             jnp.asarray(lengths, jnp.int32)))

    def embed_tokens(self, token_lists: List[List[int]]) -> np.ndarray:
        """Pooled prompt embeddings [n, H] fp32 (the /v1/embeddings
        path; rerank and score pool on top of it). Length-bucketed and
        batch-padded to bound executable count; runs off the engine loop
        (read-only on params, nothing donated)."""
        B = self.cfg.max_num_seqs
        if self.cfg.embedding_model:
            # raw token-list inputs bypass the tokenizer: out-of-vocab
            # ids would gather-clamp silently into the embedding table
            V = self._encoder_cfg().vocab_size
            for toks in token_lists:
                bad = [t for t in toks if not 0 <= t < V]
                if bad:
                    raise ValueError(
                        f"token id {bad[0]} out of range for the "
                        f"embedding encoder vocab ({V})")
        buckets = sorted(set(self.cfg.prefill_buckets)
                         | set(self.cfg.kv_len_buckets))
        out: List[np.ndarray] = []
        for i in range(0, len(token_lists), B):
            group = token_lists[i:i + B]
            need = max(len(t) for t in group)
            tb = next((b for b in buckets if b >= need), need)
            if self.cfg.embedding_model:
                # serving buckets can exceed the encoder's position
                # table; callers are length-capped by max_embed_len
                tb = min(tb, self.max_embed_len)
            tokens = np.zeros((B, tb), np.int32)
            lengths = np.ones((B,), np.int32)
            for j, toks in enumerate(group):
                tokens[j, :len(toks)] = toks
                lengths[j] = len(toks)
            pooled = self._embed_batch(tokens, lengths)
            out.append(pooled[:len(group)])
        return np.concatenate(out, axis=0)

    def render_metrics(self) -> bytes:
        with self._lock:
            self._refresh_gauges()
            if self.connector is not None:
                # totals -> counter deltas + tier occupancy gauges, at
                # scrape frequency (never on the step loop)
                self.metrics.sync_kv(self.connector.stats_report())
            # efficiency + fragmentation totals -> counter deltas and
            # rate gauges, same scrape-time idiom
            self.metrics.sync_eff(self.eff.report(), self.eff.rates())
            self.metrics.sync_kvpool(self.block_mgr.frag_report())
        return self.metrics.render()

    # ------------------------------------------------- overload surface

    def admission_full(self) -> bool:
        """Lock-free fast-path hint: True when a new submit would very
        likely be rejected by bounded admission right now. The
        authoritative count (which excludes preempted sequences) stays
        in add_request under the lock; this lets a shed storm be
        refused BEFORE tokenization and the executor hop burn
        event-loop CPU on requests that are going to 503 anyway. May
        over-shed by up to the preempted-sequence count under combined
        KV pressure + queue overflow — when both valves are blowing,
        early shed is the right bias."""
        cap = self.cfg.max_waiting_seqs
        if cap is None:
            return False
        return len(self.scheduler.waiting) >= \
            cap + len(self.scheduler.free_slots)

    def estimated_queue_delay_s(self) -> float:
        """Rough wait a newly queued request faces: queue depth ahead of
        it over the batch width, paced by the recent per-request wall
        time. Deliberately lock-free (len()/attribute reads are atomic
        in CPython): the /load endpoint and Retry-After must answer
        while the engine lock is held across a multi-second compile."""
        waiting = len(self.scheduler.waiting)
        return (waiting / max(1, self.cfg.max_num_seqs)) \
            * self._service_ewma

    def load_report(self) -> Dict[str, object]:
        """Cheap point-in-time load signal (served on /load and as
        x-engine-* response headers; the router scrapes the same
        numbers from /metrics). Lock-free by design — see
        estimated_queue_delay_s."""
        sched = self.scheduler
        cap = None
        if self.cfg.max_waiting_seqs is not None:
            cap = self.cfg.max_num_seqs + self.cfg.max_waiting_seqs
        report = {
            "queue_depth": len(sched.waiting),
            "running": len(sched.running) + len(sched._prefilling),
            "max_num_seqs": self.cfg.max_num_seqs,
            "max_waiting_seqs": self.cfg.max_waiting_seqs,
            # total in-flight the engine will accept before shedding
            # (None = unbounded admission); the router derives its
            # per-endpoint concurrency cap from this
            "capacity": cap,
            "free_kv_blocks": self.block_mgr.available,
            "kv_usage": round(self.block_mgr.usage, 4),
            "est_queue_delay_ms": round(
                1e3 * self.estimated_queue_delay_s(), 1),
            # live model catalog (base first, then loaded adapters):
            # the router's /v1/models aggregation and pool resolution
            # read it, so a runtime adapter load is fleet-visible one
            # scrape later without a config push
            "models": list(self.served_models),
            # engine-efficiency accounting (engine/efficiency.py):
            # token-step totals, recent effective-bandwidth/MBU rates,
            # and compile counters — including compile_in_flight, which
            # this lock-free path reports WHILE the engine lock is held
            # across the compile itself. Parsed by signals.EngineLoad.
            "perf": self.eff.perf_block(),
            # kvplane census: block-state counts + allocation-failure
            # classification (block_manager.frag_report — plain-int
            # reads). The migration planner's trigger signal: fragmented
            # failures rising here while another replica reports free
            # headroom is exactly the stranded capacity it reclaims.
            "kv_pool": self.block_mgr.frag_report(),
        }
        if self.connector is not None:
            # tier hit/miss/bytes counters (all in-memory totals — no
            # I/O): the cache-aware router scores endpoints on these,
            # and the kvshare rig reads them for its pass/fail contract
            report["kv_cache"] = self.connector.stats_report()
        return report

    # ---------------------------------------------------- paged-KV host

    def _try_admit(self, seq: Sequence) -> bool:
        """Scheduler admission gate: claim KV blocks for the whole
        prompt (+1 position for the first sampled token). Registered
        prefix blocks are attached by reference (zero copies); the rest
        are allocated fresh. Returns False — deferring admission —
        when the pool cannot cover the remainder."""
        toks = seq.prefill_tokens
        salt = self._adapter_salt(seq.adapter_id)
        # hash the prompt once per (salt, length): deferred admissions
        # retry every scheduler pass and must not re-hash or re-count
        state = seq.prefix_state
        first_try = state is None or state[0] != (salt, len(toks))
        if first_try:
            keys = self.block_mgr.prefix_keys(toks, salt=salt)
            seq.prefix_state = ((salt, len(toks)), keys)
        else:
            keys = state[1]
        shared, covered = self.block_mgr.match_keys(
            keys, record_stats=first_try)
        need = self.block_mgr.blocks_for(len(toks) + 1) - len(shared)
        fresh = self.block_mgr.alloc(max(need, 0))
        if fresh is None:
            self.block_mgr.free(shared)   # unpin; retry next iteration
            return False
        seq.block_ids = shared + fresh
        seq.num_prefilled = covered       # capped at len-1, full blocks
        return True

    def _on_admit(self, seq: Sequence) -> None:
        """Scheduler hook (slot now assigned): point the slot's table
        row at the sequence's blocks, then let the KV tiers inject any
        deeper cached prefix (host/disk/remote, kvcache/connector.py)."""
        self._set_table_row(seq.slot, seq.block_ids)
        pf = seq.kv_prefetch
        seq.kv_prefetch = None   # release host buffers either way
        if pf is None:
            return
        conn_covered = pf.cached_tokens
        if conn_covered > seq.num_prefilled:
            # the injected range may overlap prefix-shared blocks; the
            # bytes are identical by key construction, so concurrent
            # sharers read the same values
            self.connector.inject(pf, seq.slot)
            seq.num_prefilled = conn_covered
        else:
            # block sharing already covers at least as much: the tier
            # holds these chunks, skip the device->host re-extract at
            # finish
            self.connector.mark_seen(pf.keys)

    def _set_table_row(self, slot: int, block_ids) -> None:
        self._tables[slot, :] = 0
        if block_ids:
            # rolled entries are None placeholders -> trash block 0
            # (never read: every attention path skips blocks behind the
            # window, the only reason entries roll)
            self._tables[slot, :len(block_ids)] = [
                b or 0 for b in block_ids]
        self.runner.set_block_tables(self._tables)

    def _free_seq_blocks(self, seq: Sequence) -> None:
        """Release a sequence's live blocks (rolled entries are None
        placeholders, already freed)."""
        self.block_mgr.free([b for b in seq.block_ids if b])
        seq.block_ids = []

    def _roll_windows(self, decode_seqs) -> None:
        """Free blocks every future query of a windowed sequence can no
        longer attend (positions <= next_position - W). Safe against
        in-flight windows: their starts are >= the host's view, so
        their own window lower bound is at least as high, and they
        never read (or write) behind it."""
        W = self._roll_window
        Bs = self.cfg.kv_block_size
        for s in decode_seqs:
            if s.status is not SeqStatus.RUNNING:
                continue
            keep_from = max(s.next_position - W + 1, 0) // Bs
            if keep_from <= s.rolled_blocks:
                continue
            keep_from = min(keep_from, len(s.block_ids))
            dead = [b for b in s.block_ids[s.rolled_blocks:keep_from]
                    if b]
            if dead:
                self.block_mgr.free(dead)
            for i in range(s.rolled_blocks, keep_from):
                s.block_ids[i] = None
            s.rolled_blocks = keep_from
            self._set_table_row(s.slot, s.block_ids)

    def _ensure_blocks(self, seq: Sequence, upto_tokens: int,
                       allow_preempt: bool = True) -> bool:
        """Grow a live sequence's block list to cover positions
        < min(upto_tokens, max_model_len), preempting younger sequences
        under pool pressure. False = could not cover even after
        preemption (caller preempts `seq` itself). allow_preempt=False
        (optimistic dispatch) fails fast instead of evicting anyone."""
        need = self.block_mgr.blocks_for(
            min(upto_tokens, self.cfg.max_model_len))
        while len(seq.block_ids) < need:
            fresh = self.block_mgr.alloc(need - len(seq.block_ids))
            if fresh is not None:
                seq.block_ids.extend(fresh)
                self._set_table_row(seq.slot, seq.block_ids)
                return True
            if not allow_preempt:
                return False
            if not self._preempt_youngest(requester=seq):
                return False
        return True

    def _preempt_youngest(self, requester: Sequence) -> bool:
        """Free pool pressure by preempting the most recently arrived
        live sequence (recompute flavor). If the REQUESTER is itself
        the youngest, returns False so the caller preempts it rather
        than letting a new arrival serially evict older sequences
        (youngest-first must hold globally, not just among victims)."""
        candidates = list(self.scheduler.running.values()) \
            + list(self.scheduler._prefilling.values())
        if requester not in candidates:
            candidates.append(requester)
        victim = max(candidates, key=lambda s: s.arrival_time)
        if victim is requester or len(candidates) == 1:
            return False
        self._preempt(victim)
        return True

    def _preempt(self, seq: Sequence) -> None:
        logger.warning(
            "preempting %s (KV pool pressure): %d blocks freed, "
            "%d tokens will recompute", seq.seq_id, len(seq.block_ids),
            seq.num_tokens)
        slot = seq.slot
        self._free_seq_blocks(seq)
        seq.rolled_blocks = 0   # recompute re-prefills from position 0
        seq.reg_state = None    # re-register the recomputed blocks
        self.scheduler.preempt(seq)
        self._park_slot(slot)
        self._set_table_row(slot, [])
        self.metrics.preemptions.inc()

    def _refresh_gauges(self) -> None:
        self.metrics.num_running.set(self.scheduler.num_running)
        self.metrics.num_waiting.set(self.scheduler.num_waiting)
        self.metrics.est_queue_delay.set(
            1e3 * self.estimated_queue_delay_s())
        usage = self.block_mgr.usage
        self.metrics.kv_usage.set(usage)
        self.metrics.hbm_kv_usage.set(usage)
        # two distinct gauges: the block pool's (per-request, in-HBM)
        # and the tiers' (token-weighted) hit rates have different
        # semantics — shadowing one with the other would skew dashboards
        if self.cfg.enable_prefix_caching:
            self.metrics.hbm_prefix_hit_rate.set(self.block_mgr.hit_rate)
        if self.connector is not None:
            self.metrics.prefix_hit_rate.set(self.connector.hit_rate)
        elif self.cfg.enable_prefix_caching:
            self.metrics.prefix_hit_rate.set(self.block_mgr.hit_rate)

    def close(self) -> None:
        """Flush the KV writer and release tier connections."""
        if self.connector is not None:
            self.connector.close()

    # ------------------------------------------------------------------

    def generate(self, prompt: str, options: Optional[SamplingOptions] = None,
                 ) -> str:
        """Blocking single-prompt convenience API (tests, CLI)."""
        toks = self.tokenizer.encode(prompt)
        seq_id = self.add_request(toks, options)
        while True:
            for out in self.step():
                if out.seq_id == seq_id and out.finished:
                    return self.seqs[seq_id].output_text

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work
