"""Continuous-batching scheduler: waiting queue -> slots -> decode batch.

Policy (round-robin between admission and decode):
- A waiting sequence is admitted when a slot is free; its prompt is
  prefilled in chunks of ``prefill_chunk`` tokens (chunked prefill — the
  reference exposes this as the `--enable-chunked-prefill` engine flag,
  reference: helm/templates/deployment-vllm-multi.yaml:69-72).
- When no prefill work is pending, all running slots advance one token in
  a single fused decode step.
- Finished sequences free their slot immediately; the next waiting
  sequence takes it on the following iteration.

The scheduler is pure host-side bookkeeping — device work happens in
ModelRunner. Static batch shape (max_num_seqs) means admission never
recompiles anything.
"""

import collections
import enum
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple


class SeqStatus(enum.Enum):
    WAITING = "waiting"
    PREFILLING = "prefilling"
    RUNNING = "running"
    FINISHED = "finished"


@dataclass
class SamplingOptions:
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    max_tokens: int = 128
    stop: List[str] = field(default_factory=list)
    stop_token_ids: List[int] = field(default_factory=list)
    ignore_eos: bool = False
    logprobs: bool = False
    # OpenAI top_logprobs: return the K highest-probability
    # alternatives per generated token (0 = chosen-token only)
    top_logprobs: int = 0
    # > 0: reproducible sampling — gumbel noise derived from
    # (seed, token position) only (engine/sampler.py)
    seed: Optional[int] = None
    # constrain generation to this regex (engine/guided.py); the server
    # maps guided_choice onto it
    guided_regex: Optional[str] = None
    # OpenAI/vLLM logit shaping (engine/sampler.adjust_logits); all
    # inert at their defaults — the penalized executable only compiles
    # when a live row departs from them
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    min_p: float = 0.0
    min_tokens: int = 0
    logit_bias: Optional[Dict[int, float]] = None
    # vLLM scheduling priority: LOWER values admit earlier; equal
    # priorities keep FIFO arrival order (scheduler.add)
    priority: int = 0

    @property
    def shaped(self) -> bool:
        """True when this request needs the penalized executable."""
        return bool(self.presence_penalty or self.frequency_penalty
                    or self.repetition_penalty != 1.0 or self.min_tokens
                    or self.logit_bias)


@dataclass
class Sequence:
    seq_id: str
    prompt_tokens: List[int]
    options: SamplingOptions
    status: SeqStatus = SeqStatus.WAITING
    slot: int = -1
    adapter_id: int = 0      # LoRA adapter (0 = base model, models/lora.py)
    # paged-KV blocks this sequence owns, table order (engine/
    # block_manager.py); prefix-shared blocks lead, exclusive ones
    # follow. Rolled (sliding-window-freed) entries are None
    # placeholders so virtual indexing stays stable.
    block_ids: List[int] = field(default_factory=list)
    # blocks freed behind the sliding window (engine._roll_windows);
    # prefix registration is skipped once any block rolled
    rolled_blocks: int = 0
    # live progressive-registration hasher chain state
    # (block_manager.register_incremental); reset on preemption
    reg_state: object = None
    output_tokens: List[int] = field(default_factory=list)
    # per output token: chosen-token logprob (pre-temperature, post-
    # shaping distribution — raw model distribution for unshaped rows)
    output_logprobs: List[Optional[float]] = field(default_factory=list)
    # per output token, when options.top_logprobs: [(id, logprob)] top
    # alternatives (None for tokens emitted by paths without them)
    output_top: List[Optional[list]] = field(default_factory=list)
    num_prefilled: int = 0
    arrival_time: float = field(default_factory=time.monotonic)
    # last forward-progress stamp (prefill chunk landed / token
    # emitted): kvplane victim selection retires the LEAST recently
    # active sequence first — its KV is coldest and its owner has
    # waited longest already, so re-prefilling it elsewhere wastes the
    # least warm state. Set from arrival in __post_init__.
    last_active: float = 0.0
    # phase attribution (tracing.py): queue time accumulates across
    # admissions so a preempted-and-requeued sequence never
    # double-counts wall time — enqueued_time stamps each entry into
    # the waiting queue (creation + every preemption), schedule() folds
    # the closed interval into queue_wait_s at slot assignment, and
    # admit_time keeps the LAST admission stamp.
    enqueued_time: float = 0.0          # set from arrival in __post_init__
    queue_wait_s: float = 0.0
    admit_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_reason: Optional[str] = None
    # KV-tier prefetch cost paid for this request at add time
    # (kvcache/connector.py): wall seconds of the tier walk and the
    # prompt tokens it served — the kv_prefetch trace span
    kv_prefetch_wait_s: float = 0.0
    kv_cached_tokens: int = 0
    # absolute monotonic deadline (from the client's
    # x-request-deadline-ms header, engine/server.py): a sequence whose
    # deadline expires while still WAITING is dropped by
    # expire_waiting() before burning prefill compute on a request the
    # client has abandoned. None = no deadline.
    deadline: Optional[float] = None
    # host-side KV for a cached prompt prefix, fetched off the engine loop
    # at add time (kvcache/connector.py Prefetch); injected at admission
    kv_prefetch: object = None
    # incremental chunk-key chain state for progressive KV publish
    # (kvcache/connector.py _publish)
    kv_publish_state: object = None
    # cached prefix-cache chain keys: (salt, prefill_len, keys) — an
    # admission deferred by pool pressure retries every scheduler pass
    # and must not re-hash the prompt (or re-count hit/miss) each time
    prefix_state: object = None
    # guided decoding (engine/guided.py): compiled grammar + current
    # DFA state (host mirror of the device-carried state)
    grammar: object = None
    fsm_state: int = 0
    # incremental detokenization state (owned by LLMEngine)
    output_text: str = ""       # stable decoded text, stop-truncated
    chars_emitted: int = 0      # prefix of output_text already delivered
    detok: object = None

    def __post_init__(self):
        self.enqueued_time = self.arrival_time
        self.last_active = self.arrival_time

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_tokens) + len(self.output_tokens)

    @property
    def next_position(self) -> int:
        return self.num_tokens - 1

    @property
    def prefill_tokens(self) -> List[int]:
        """Tokens to prefill when (re)building this sequence's KV: the
        prompt, plus — after a preemption-recompute — the already-
        emitted output teacher-forced back in (all but the last emitted
        token, which becomes the decode input again)."""
        if self.output_tokens:
            return self.prompt_tokens + self.output_tokens[:-1]
        return self.prompt_tokens


@dataclass
class PrefillWork:
    seq: Sequence
    chunk: List[int]
    start: int
    is_last: bool


class Scheduler:
    def __init__(self, max_num_seqs: int, max_model_len: int,
                 prefill_chunk: int):
        self.max_num_seqs = max_num_seqs
        self.max_model_len = max_model_len
        self.prefill_chunk = prefill_chunk
        self.waiting: Deque[Sequence] = collections.deque()
        self.running: Dict[int, Sequence] = {}        # slot -> seq
        # kept sorted DESCENDING so pop() hands out the LOWEST free
        # slot: admissions fill the low slots first, which keeps the
        # live batch dense and the engine's batch-bucketed decode
        # dispatch (engine._compact_slots) mostly a no-op
        self.free_slots: List[int] = list(range(max_num_seqs - 1, -1, -1))
        # last schedule() pass deferred the head waiter on the KV
        # admission gate (can_admit): a waiter + free slot does not
        # imply the next pass admits (read by engine._admission_imminent)
        self.kv_deferred = False
        self._prefilling: Dict[int, Sequence] = {}    # slot -> seq
        # invoked right after a slot is assigned, before the first prefill
        # chunk is cut — may rewind seq.num_prefilled past a cached prefix
        self.on_admit: Optional[object] = None
        # admission gate: called with the head-of-queue sequence BEFORE a
        # slot is taken; returning False defers admission (the engine's
        # KV block allocator uses this — engine.py _try_admit)
        self.can_admit: Optional[object] = None

    # ------------------------------------------------------------------

    def add(self, seq: Sequence) -> None:
        if len(seq.prompt_tokens) >= self.max_model_len:
            raise ValueError(
                f"prompt length {len(seq.prompt_tokens)} exceeds "
                f"max_model_len {self.max_model_len}")
        # priority insertion (vLLM semantics: lower value admits
        # earlier; FIFO within a priority level). The common all-
        # default case is a pure O(1) append. The scan iterates (no
        # mid-deque indexing — deque[i] is O(n)) and never crosses a
        # PREEMPTED sequence (one with emitted output): recompute-first
        # holds even against higher-priority arrivals, or a steady
        # stream of them would starve a partially-streamed request
        # while its recompute debt grows.
        pr = seq.options.priority
        i = len(self.waiting)
        for other in reversed(self.waiting):
            if other.options.priority > pr and not other.output_tokens:
                i -= 1
            else:
                break
        if i == len(self.waiting):
            self.waiting.append(seq)
        else:
            self.waiting.insert(i, seq)

    def abort(self, seq_id: str) -> bool:
        for seq in list(self.waiting):
            if seq.seq_id == seq_id:
                self.waiting.remove(seq)
                seq.status = SeqStatus.FINISHED
                seq.finish_reason = "abort"
                seq.kv_prefetch = None   # release host KV buffers
                return True
        for slot, seq in list(self.running.items()):
            if seq.seq_id == seq_id:
                self._release(slot, seq, "abort")
                return True
        for slot, seq in list(self._prefilling.items()):
            if seq.seq_id == seq_id:
                del self._prefilling[slot]
                self._release(slot, seq, "abort")
                return True
        return False

    # ------------------------------------------------------------------

    def expire_waiting(self, now: Optional[float] = None,
                       max_queue_delay_s: Optional[float] = None
                       ) -> List[Sequence]:
        """Overload-protection sweep over the un-admitted queue, run by
        the engine at the top of every step:

        - a sequence whose ``deadline`` has passed is dropped with
          finish_reason ``"deadline"`` (the client's budget elapsed
          while it queued — prefilling it now serves nobody);
        - with ``max_queue_delay_s`` set, a sequence queued longer than
          the cap is shed with finish_reason ``"queue_delay"``.

        Preempted sequences (ones with emitted output) are exempt from
        the queue-delay shed — they were admitted once and their client
        is mid-stream — but not from their own deadline. Returns the
        dropped sequences so the engine can emit terminal StepOutputs.
        """
        if not self.waiting:
            return []
        if now is None:
            now = time.monotonic()
        dropped: List[Sequence] = []
        kept: List[Sequence] = []
        for seq in self.waiting:
            if seq.deadline is not None and now >= seq.deadline:
                reason = "deadline"
            elif (max_queue_delay_s is not None
                  and not seq.output_tokens
                  and now - seq.arrival_time >= max_queue_delay_s):
                reason = "queue_delay"
            else:
                kept.append(seq)
                continue
            seq.status = SeqStatus.FINISHED
            seq.finish_reason = reason
            seq.kv_prefetch = None   # release host KV buffers
            dropped.append(seq)
        if dropped:
            # one rebuild, not one O(n) deque.remove per drop — a storm
            # can expire thousands of queued sequences in a single pass
            self.waiting.clear()
            self.waiting.extend(kept)
        return dropped

    def schedule(self) -> Tuple[List[PrefillWork], List[Sequence]]:
        """Pick this iteration's device work.

        Returns (prefill_works, decode_seqs) — BOTH may be non-empty: the
        engine batch-prefills every admissible sequence's next chunk in
        one dispatch and then runs a decode window in the same step, so a
        newcomer's (chunked) prefill never stalls running sequences'
        token cadence (the reference gets this from vLLM's chunked
        prefill, reference:
        helm/templates/deployment-vllm-multi.yaml:69-72).
        """
        works = [self._chunk_of(seq) for seq in self._prefilling.values()]
        self.kv_deferred = False
        while self.waiting and self.free_slots:
            seq = self.waiting[0]
            if self.can_admit is not None and not self.can_admit(seq):
                # KV pool pressure: keep FIFO order, retry later. The
                # flag tells the engine's mid-window-admission lever
                # that a waiter + free slot does NOT mean the next
                # pass admits — shortening windows buys nothing here
                self.kv_deferred = True
                break
            self.waiting.popleft()
            seq.slot = self.free_slots.pop()
            seq.status = SeqStatus.PREFILLING
            seq.admit_time = time.monotonic()
            seq.queue_wait_s += seq.admit_time - seq.enqueued_time
            self._prefilling[seq.slot] = seq
            if self.on_admit is not None:
                self.on_admit(seq)
            works.append(self._chunk_of(seq))
        return works, list(self.running.values())

    def _chunk_of(self, seq: Sequence) -> PrefillWork:
        toks = seq.prefill_tokens
        start = seq.num_prefilled
        end = min(start + self.prefill_chunk, len(toks))
        return PrefillWork(seq=seq, chunk=toks[start:end],
                           start=start, is_last=end == len(toks))

    def on_prefill_done(self, work: PrefillWork) -> None:
        seq = work.seq
        seq.num_prefilled += len(work.chunk)
        seq.last_active = time.monotonic()
        if work.is_last:
            seq.status = SeqStatus.RUNNING
            self._prefilling.pop(seq.slot, None)
            self.running[seq.slot] = seq

    def preempt(self, seq: Sequence) -> None:
        """KV-pressure preemption (recompute flavor): drop the sequence
        back to the FRONT of the waiting queue; its next admission
        re-prefills prefill_tokens (prompt + emitted output, teacher-
        forced) into freshly allocated blocks. The engine frees the
        blocks and parks the slot (engine.py _preempt)."""
        slot = seq.slot
        self.running.pop(slot, None)
        self._prefilling.pop(slot, None)
        if slot >= 0:
            self._free_slot(slot)
        seq.slot = -1
        seq.status = SeqStatus.WAITING
        seq.num_prefilled = 0
        seq.enqueued_time = time.monotonic()   # new queue-wait interval
        self.waiting.appendleft(seq)

    def finish(self, seq: Sequence, reason: str) -> None:
        self._release(seq.slot, seq, reason)

    def _free_slot(self, slot: int) -> None:
        """Return a slot to the free list, keeping it sorted descending
        (pop() hands out the lowest index)."""
        self.free_slots.append(slot)
        self.free_slots.sort(reverse=True)

    def _release(self, slot: int, seq: Sequence, reason: str) -> None:
        seq.status = SeqStatus.FINISHED
        seq.finish_reason = reason
        seq.kv_prefetch = None   # finished seqs are retained; drop host KV
        if slot >= 0:
            self.running.pop(slot, None)
            self._free_slot(slot)
            seq.slot = -1

    # ------------------------------------------------------------------

    @property
    def num_waiting(self) -> int:
        return len(self.waiting) + len(self._prefilling)

    @property
    def num_running(self) -> int:
        return len(self.running)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running or self._prefilling)

