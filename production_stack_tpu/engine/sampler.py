"""On-device batched sampling (greedy / temperature / top-k / top-p).

Fused into the decode executable so only sampled token ids (a few bytes
per sequence) cross the host↔device boundary each step — never the
[B, vocab] logits. All branching is data-dependent masking, not Python
control flow, so one executable serves any mix of per-request sampling
params. The single descending sort per step feeds both top-k and top-p.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

_EPS = 1e-6
_NEG_INF = -1e30

# logit_bias slot width: covers OpenAI's documented 300-entry cap (the
# server rejects >300 with a 400 for API parity; the engine boundary
# rejects >LOGIT_BIAS_K). The arrays stay [B, K] int32/fp32 — a few
# hundred KB — and the scatter-add in adjust_logits is noise next to
# the [B, V] shaping math it feeds.
LOGIT_BIAS_K = 320

# stop_token_ids masked while out_len < min_tokens (vLLM semantics:
# min_tokens bans EOS and every stop token, not EOS alone)
MIN_TOKENS_STOP_K = 16


class SamplingParams(NamedTuple):
    """Per-sequence device-side request state, shape [B] each.

    ``adapter`` selects each row's LoRA adapter (0 = base model,
    models/lora.py); it rides with the sampling params because both
    change only at slot (re)assignment, so one dirty-flag upload covers
    them. sample() itself ignores it.
    """

    temperature: jnp.ndarray  # fp32; 0 => greedy
    top_p: jnp.ndarray        # fp32 in (0, 1]
    top_k: jnp.ndarray        # int32; 0 => disabled
    adapter: jnp.ndarray      # int32 adapter id; 0 => base model
    seed: jnp.ndarray         # int32; 0 => unseeded (engine key stream)
    # OpenAI/vLLM logit-shaping params (adjust_logits; all inert at
    # their defaults, and the PENALIZED decode executable only compiles
    # when some live row departs from them — engine._dispatch_decode)
    presence: jnp.ndarray     # fp32; 0 => off (OpenAI presence_penalty)
    frequency: jnp.ndarray    # fp32; 0 => off (OpenAI frequency_penalty)
    repetition: jnp.ndarray   # fp32; 1 => off (HF/vLLM repetition_penalty)
    min_p: jnp.ndarray        # fp32; 0 => off (vLLM min_p truncation)
    min_tokens: jnp.ndarray   # int32; EOS + stop ids forbidden below this
    prompt_len: jnp.ndarray   # int32; output count = position+1 - this
    bias_ids: jnp.ndarray     # int32 [B, K]; -1 => unused slot
    bias_vals: jnp.ndarray    # fp32 [B, K] (OpenAI logit_bias)
    stop_ids: jnp.ndarray     # int32 [B, KS]; -1 => unused (min_tokens)

    @staticmethod
    def filled(batch: int, temperature=1.0, top_p=1.0, top_k=0, adapter=0,
               seed=0, presence=0.0, frequency=0.0, repetition=1.0,
               min_p=0.0, min_tokens=0, prompt_len=0, bias_k=LOGIT_BIAS_K,
               stop_k=MIN_TOKENS_STOP_K):
        return SamplingParams(
            temperature=jnp.full((batch,), temperature, jnp.float32),
            top_p=jnp.full((batch,), top_p, jnp.float32),
            top_k=jnp.full((batch,), top_k, jnp.int32),
            adapter=jnp.full((batch,), adapter, jnp.int32),
            seed=jnp.full((batch,), seed, jnp.int32),
            presence=jnp.full((batch,), presence, jnp.float32),
            frequency=jnp.full((batch,), frequency, jnp.float32),
            repetition=jnp.full((batch,), repetition, jnp.float32),
            min_p=jnp.full((batch,), min_p, jnp.float32),
            min_tokens=jnp.full((batch,), min_tokens, jnp.int32),
            prompt_len=jnp.full((batch,), prompt_len, jnp.int32),
            bias_ids=jnp.full((batch, bias_k), -1, jnp.int32),
            bias_vals=jnp.zeros((batch, bias_k), jnp.float32),
            stop_ids=jnp.full((batch, stop_k), -1, jnp.int32),
        )


def adjust_logits(logits: jnp.ndarray, params: SamplingParams,
                  out_counts: jnp.ndarray, prompt_seen: jnp.ndarray,
                  out_len: jnp.ndarray, eos_id: int) -> jnp.ndarray:
    """OpenAI/vLLM logit shaping, fused ahead of sampling.

    logits fp32 [B, V]; out_counts int32 [B, V] = per-row counts of
    GENERATED tokens (device-carried, engine/runner.py); prompt_seen
    bool [B, V] marks tokens present in the prompt; out_len [B] =
    tokens generated so far (the one being sampled is out index
    out_len). Semantics match vLLM:

    - logit_bias: additive, from the request's (id, value) pairs;
    - repetition_penalty: divide positive / multiply negative logits of
      every token seen in prompt OR output (HF convention);
    - presence_penalty: subtract once for any generated token;
    - frequency_penalty: subtract per occurrence generated;
    - min_tokens: EOS AND the request's stop_token_ids (params.stop_ids)
      forbidden while out_len < min_tokens (vLLM semantics — a stop id
      terminating before the floor would end the sequence early).
    """
    B, V = logits.shape
    valid = params.bias_ids >= 0
    idx = jnp.maximum(params.bias_ids, 0)
    logits = logits.at[jnp.arange(B)[:, None], idx].add(
        jnp.where(valid, params.bias_vals, 0.0))
    seen_out = out_counts > 0
    rep = params.repetition[:, None]
    seen_any = seen_out | prompt_seen
    penal = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen_any, penal, logits)
    logits = logits - params.presence[:, None] * seen_out
    logits = logits - params.frequency[:, None] * out_counts
    below_floor = (out_len < params.min_tokens)[:, None]
    banned = (jnp.arange(V) == eos_id)[None, :] | jnp.zeros(
        (B, V), bool).at[jnp.arange(B)[:, None],
                         jnp.maximum(params.stop_ids, 0)].max(
        params.stop_ids >= 0)
    return jnp.where(below_floor & banned, _NEG_INF, logits)


def sample(logits: jnp.ndarray, params: SamplingParams,
           key: jax.Array,
           positions: jnp.ndarray = None,
           plain: bool = False) -> jnp.ndarray:
    """logits fp32 [B,V] -> token ids int32 [B].

    positions [B]: absolute position of the token being sampled. Rows
    with params.seed > 0 draw their gumbel noise from a key derived
    ONLY from (seed, position) — the same seeded request reproduces the
    same tokens whatever else shares the batch or how the engine's key
    stream has advanced. seed == 0 rows use the engine key stream (the
    engine normalizes user seeds, 0/negative included, to nonzero —
    engine.py _sync_sampling). Pass positions=None to skip the seeded
    branch entirely (the decode hot loop does when no live row is
    seeded, engine.py _dispatch_decode).

    plain=True (STATIC; the engine sets it when every live row has
    top_p >= 1 and top_k == 0 — the API default) skips the [B, V]
    descending sort + cumsum entirely: pure temperature/gumbel
    sampling needs no threshold. For untruncated rows the two paths
    are mathematically identical (the threshold keeps the whole
    distribution), so mixing plain and full windows across a
    sequence's lifetime cannot change its distribution.
    """
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(params.temperature, _EPS)[:, None]
    scaled = logits / temp

    if plain:
        masked = scaled
    else:
        # One sort serves top-k and top-p. [B,V] descending.
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]

        # top-k threshold: value of the k-th largest (disabled => all)
        k = jnp.where(params.top_k > 0, params.top_k, V).astype(jnp.int32)
        kth = jnp.take_along_axis(
            sorted_logits, jnp.clip(k[:, None] - 1, 0, V - 1), axis=-1)

        # top-p: smallest prefix of the sorted distribution w/ mass >= p
        probs_sorted = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs_sorted, axis=-1)
        # keep ranks whose cumulative mass *before* them is < p
        keep_sorted = (cum - probs_sorted) < params.top_p[:, None]
        # threshold = smallest kept logit value
        p_thresh = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
            keepdims=True)

        threshold = jnp.maximum(kth, p_thresh)
        masked = jnp.where(scaled >= threshold, scaled, _NEG_INF)

        # min_p (vLLM): drop tokens whose prob < min_p * max prob.
        # Softmax is monotone, so prob >= min_p * pmax is exactly
        # scaled >= max_logit + log(min_p) — reuse the sort's top
        # instead of materializing a second [B, V] softmax; log(0) is
        # -inf, which keeps every token for min_p == 0 rows. The
        # engine keeps a batch on the plain path only when every live
        # row has min_p == 0
        minp_thresh = sorted_logits[:, :1] + jnp.log(
            jnp.clip(params.min_p[:, None], 0.0, 1.0))
        masked = jnp.where(scaled >= minp_thresh, masked, _NEG_INF)

    gumbel = jax.random.gumbel(key, (B, V), jnp.float32)
    if positions is not None:
        def row_noise(seed, pos):
            k = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
            return jax.random.gumbel(k, (V,), jnp.float32)
        seeded = jax.vmap(row_noise)(params.seed, positions)
        gumbel = jnp.where((params.seed > 0)[:, None], seeded, gumbel)
    sampled = jnp.argmax(masked + gumbel, axis=-1)

    return jnp.where(params.temperature <= _EPS, greedy, sampled).astype(
        jnp.int32)
