"""Engine Prometheus metrics.

Gauge names keep the `vllm:` prefix the reference router scrapes
(reference: src/vllm_router/stats/engine_stats.py:46-55 parses
vllm:num_requests_running / vllm:num_requests_waiting /
vllm:gpu_cache_usage_perc / vllm:gpu_prefix_cache_hit_rate) so either
stack's router can balance on either engine. TPU-specific duplicates are
exported under `tpu:` (HBM KV usage) for the Grafana dashboard.
"""

from prometheus_client import (CollectorRegistry, Counter, Gauge, Histogram,
                               generate_latest)

from production_stack_tpu.engine.efficiency import (COMPILE_BUCKETS,
                                                    OCCUPANCY_BUCKETS)
from production_stack_tpu.tracing import (PhaseHistogramCollector,
                                          PhaseHistograms)

# Engine metrics get their own registry so multiple in-process engines
# (tests) don't collide in the global default registry.


class EngineMetrics:
    def __init__(self, model: str):
        self.registry = CollectorRegistry()
        labels = {"model_name": model}

        def gauge(name, doc):
            g = Gauge(name, doc, list(labels), registry=self.registry)
            return g.labels(**labels)

        def counter(name, doc):
            c = Counter(name, doc, list(labels), registry=self.registry)
            return c.labels(**labels)

        def histo(name, doc, buckets):
            h = Histogram(name, doc, list(labels), buckets=buckets,
                          registry=self.registry)
            return h.labels(**labels)

        self.num_running = gauge("vllm:num_requests_running",
                                 "Sequences in the decode batch")
        self.num_waiting = gauge("vllm:num_requests_waiting",
                                 "Sequences queued or prefilling")
        self.kv_usage = gauge("vllm:gpu_cache_usage_perc",
                              "KV cache slot-token utilization (0-1)")
        self.hbm_kv_usage = gauge("tpu:hbm_kv_usage_perc",
                                  "KV cache HBM utilization (0-1)")
        self.prefix_hit_rate = gauge("vllm:gpu_prefix_cache_hit_rate",
                                     "Prefix cache hit rate (0-1)")
        self.hbm_prefix_hit_rate = gauge(
            "tpu:hbm_prefix_cache_hit_rate",
            "In-HBM prefix pool hit rate (0-1, per request)")
        self.preemptions = counter(
            "vllm:num_preemptions_total",
            "Sequences preempted (KV pool pressure) for recompute")
        self.prompt_tokens = counter("vllm:prompt_tokens_total",
                                     "Prefilled prompt tokens")
        self.generation_tokens = counter("vllm:generation_tokens_total",
                                         "Generated tokens")
        self.ttft = histo(
            "vllm:time_to_first_token_seconds", "Time to first token",
            (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
        self.e2e_latency = histo(
            "vllm:e2e_request_latency_seconds", "End-to-end request latency",
            (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0))
        self.per_token = histo(
            "vllm:time_per_output_token_seconds", "Inter-token latency",
            (0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5))
        # n-gram speculation effectiveness: accepted draft tokens are
        # the tokens emitted BEYOND one per macro-step; macro_steps
        # counts only rows eligible to speculate (per-row spec_ok), so
        # accepted/steps is the true per-row acceptance rate
        self.spec_accepted_tokens = counter(
            "tpu:spec_accepted_draft_tokens_total",
            "Draft tokens accepted by speculative verification")
        self.spec_macro_steps = counter(
            "tpu:spec_macro_steps_total",
            "Speculative macro-steps executed by eligible rows")
        # overload protection (docs/engine.md): shed/drop accounting
        # plus the two load signals the router scrapes — advertised
        # capacity (max_num_seqs + max_waiting_seqs; 0 = unbounded
        # admission, no cap derivable) and the estimated queue delay
        self.admission_rejected = counter(
            "tpu:admission_rejected_total",
            "Requests shed at submit (max_waiting_seqs reached, 503)")
        self.deadline_expired = counter(
            "tpu:deadline_expired_total",
            "Requests dropped while WAITING (x-request-deadline-ms "
            "elapsed before admission, 504)")
        self.queue_delay_shed = counter(
            "tpu:queue_delay_shed_total",
            "Requests shed while WAITING (max_queue_delay_ms exceeded, "
            "503)")
        # runtime LoRA adapter pool (engine.load_adapter/evict_adapter;
        # /admin/lora/load|evict): lifecycle counters + live catalog
        # size, per pool on the router's dashboard row
        self.adapter_loads = counter(
            "tpu:engine_adapter_loads_total",
            "LoRA adapters loaded at runtime (/admin/lora/load)")
        self.adapter_evictions = counter(
            "tpu:engine_adapter_evictions_total",
            "LoRA adapters evicted at runtime (/admin/lora/evict)")
        self.adapters_loaded = gauge(
            "tpu:engine_adapters_loaded",
            "LoRA adapters currently serving (served model catalog "
            "minus the base model)")
        self.capacity = gauge(
            "tpu:engine_capacity_seqs",
            "Total sequences accepted before shedding (max_num_seqs + "
            "max_waiting_seqs; 0 = unbounded admission)")
        self.est_queue_delay = gauge(
            "tpu:est_queue_delay_ms",
            "Estimated wait for a newly queued request (ms)")
        # KV tiering (kvcache/connector.py): hit/miss/bytes counters
        # plus per-tier occupancy gauges. The connector keeps running
        # totals; sync_kv() converts them to counter increments at
        # scrape time (render path), so the hot loop never touches
        # prometheus objects.
        self.kv_query_tokens = counter(
            "tpu:kvcache_query_tokens_total",
            "Prompt tokens looked up against the KV tiers")
        self.kv_hit_tokens = counter(
            "tpu:kvcache_hit_tokens_total",
            "Prompt tokens served from the KV tiers (prefill skipped)")
        self.kv_foreign_hit_tokens = counter(
            "tpu:kvcache_foreign_hit_tokens_total",
            "Tier-hit tokens from chunks this process never published "
            "(produced by another replica — cross-replica sharing)")
        self.kv_chunk_hits = counter(
            "tpu:kvcache_chunk_hits_total", "Tier chunk lookups that hit")
        self.kv_chunk_misses = counter(
            "tpu:kvcache_chunk_misses_total",
            "Tier chunk lookups that ended the prefix walk")
        self.kv_bytes_loaded = counter(
            "tpu:kvcache_bytes_loaded_total",
            "Bytes materialized from the tiers by prefetch")
        self.kv_bytes_saved = counter(
            "tpu:kvcache_bytes_saved_total",
            "Bytes written through the tiers by the publish path")
        self.kv_rejected_chunks = counter(
            "tpu:kvcache_rejected_chunks_total",
            "Tier values rejected (size/checksum validation) and evicted")
        self.kv_dropped_saves = counter(
            "tpu:kvcache_dropped_saves_total",
            "Publish batches dropped by writer-queue backpressure")
        # disaggregated-prefill role surface (docs/disagg.md): which
        # side of the P/D split this engine is on, plus the producer's
        # publish counters the split's observability reads
        self.kv_published_chunks = counter(
            "tpu:kvcache_published_chunks_total",
            "Chunks written through the tiers by the producer path")
        self.kv_progress_published_chunks = counter(
            "tpu:kvcache_progress_published_chunks_total",
            "Published chunks that became tier-visible mid-prefill "
            "(the eager-publish path disaggregated decode overlaps "
            "with)")
        self._kv_role = Gauge(
            "tpu:engine_kv_role",
            "KV transfer role (1 on the engine's role label: "
            "kv_producer, kv_consumer, or kv_both)",
            list(labels) + ["role"], registry=self.registry)
        self.kv_remote_breaker_open = gauge(
            "tpu:kvcache_remote_breaker_open",
            "1 while the remote cache-server tier is breaker-skipped")
        self._kv_tier_bytes = Gauge(
            "tpu:kvcache_tier_bytes", "KV tier occupancy in bytes",
            list(labels) + ["tier"], registry=self.registry)
        self._kv_tier_items = Gauge(
            "tpu:kvcache_tier_items", "KV tier chunk count",
            list(labels) + ["tier"], registry=self.registry)
        # per-tier chunk-hit attribution (connector stats_report
        # "tier_hits": which tier actually served prefetch hits — cpu
        # promotion vs disk vs the remote DCN round trip)
        self._kv_tier_hits = Counter(
            "tpu:kvcache_tier_chunk_hits",
            "Prefetch chunk hits by the tier that served them",
            list(labels) + ["tier"], registry=self.registry)
        # phase-latency attribution (tracing.py): where a request's
        # engine-side wall time goes — queue_wait / prefill / decode
        # per request, kv_prefetch / kv_publish per tier operation,
        # decode_window per fused device window. Fed by plain-int
        # bucket increments on the engine loop; rendered at scrape by
        # the custom collector (the sync_kv idiom for histograms).
        self.engine_phases = PhaseHistograms(("phase",))
        self.registry.register(PhaseHistogramCollector(
            "tpu:engine_phase_seconds",
            "Engine-side request phase durations (docs/observability.md "
            "'Tracing' phase glossary)", self.engine_phases))
        # engine efficiency accounting (engine/efficiency.py;
        # docs/engine.md "Efficiency telemetry"): every family here is
        # fed plain-int on the step loop and delta-synced at scrape
        # time via sync_eff/sync_kvpool — zero prometheus objects near
        # the loop, the same idiom as sync_kv above.
        self._token_steps = Counter(
            "tpu:engine_token_steps",
            "Device token-step computations by usefulness: real "
            "(emitted tokens), pad (parked rows), dead (finished-row "
            "tails, discarded rows, rejected draft positions, prefill "
            "bucket padding)",
            list(labels) + ["kind", "phase"], registry=self.registry)
        self.effective_bytes_per_s = gauge(
            "tpu:engine_effective_bytes_per_s",
            "Modeled useful HBM traffic per wall-clock second over the "
            "recent window (weights + live-row KV reads, scaled by the "
            "live fraction)")
        self.mbu_perc = gauge(
            "tpu:engine_mbu_perc",
            "Model-bandwidth utilization: effective bytes/s over the "
            "configured --hbm-peak-gbps (0-100)")
        self.decode_live_fraction = gauge(
            "tpu:decode_window_live_fraction",
            "Recent fraction of decode token-steps that emitted a "
            "kept token (real / (real+pad+dead))")
        self._compiles = Counter(
            "tpu:engine_compiles",
            "XLA executable compilations by (kind, window, kv bucket, "
            "batch bucket)",
            list(labels) + ["kind", "window", "kv_bucket", "batch"],
            registry=self.registry)
        self.compile_in_flight = gauge(
            "tpu:engine_compile_in_flight",
            "XLA compilations currently blocking the engine loop "
            "(also on /load perf.compile_in_flight, which answers "
            "mid-compile)")
        # compile-duration histogram, fed at compile completion by the
        # accounting layer (seconds-scale buckets)
        self.compile_hist = PhaseHistograms(
            ("kind", "window", "kv_bucket"), buckets=COMPILE_BUCKETS)
        self.registry.register(PhaseHistogramCollector(
            "tpu:engine_compile_seconds",
            "XLA compile durations by (kind, window, kv bucket)",
            self.compile_hist))
        # KV block-pool fragmentation (engine/block_manager.py)
        self._kvpool_blocks = Gauge(
            "tpu:kvpool_blocks",
            "Paged-KV pool blocks by state (free list / held by live "
            "sequences / refcount-0 prefix-cached)",
            list(labels) + ["state"], registry=self.registry)
        self._kvpool_alloc_failures = Counter(
            "tpu:kvpool_alloc_failures",
            "Block allocations refused, by reason: exhausted (zero "
            "allocatable blocks) vs fragmented (free blocks remain "
            "but fewer than the request needs)",
            list(labels) + ["reason"], registry=self.registry)
        self.kvpool_cache_evictions = counter(
            "tpu:kvpool_cache_evictions_total",
            "Prefix-cached blocks reclaimed (LRU) to satisfy "
            "allocations")
        self.kvpool_occ_hist = PhaseHistograms(
            (), buckets=OCCUPANCY_BUCKETS)
        self.registry.register(PhaseHistogramCollector(
            "tpu:kvpool_alloc_occupancy",
            "Pool occupancy fraction observed at each allocation "
            "attempt", self.kvpool_occ_hist))
        # kvplane: fleet KV memory management (migration / defrag /
        # codecs / pipelined prefetch — docs/kv-tiering.md "Migration,
        # defrag, and codecs"). Counters inc'd directly on the admin
        # paths (migrate_out/warm run off the engine loop) or
        # delta-synced from connector totals at scrape time.
        self.kvplane_migrations = counter(
            "tpu:kvplane_migrations_total",
            "Sequences migrated out (published to the tiers and "
            "preempted) by /admin/kvplane/migrate_out")
        self.kvplane_migrated_blocks = counter(
            "tpu:kvplane_migrated_blocks_total",
            "KV pool blocks freed by migrate_out victims")
        self.kvplane_warmed_chunks = counter(
            "tpu:kvplane_warmed_chunks_total",
            "Chunks pulled warm by /admin/kvplane/warm (destination "
            "side of a migration: tier hits promoted into the fastest "
            "local tier)")
        self.kvplane_migrated_chunks = counter(
            "tpu:kvplane_migrated_chunks_total",
            "Chunks published by the migration source path "
            "(connector.on_migrate)")
        self.kvplane_defrag_runs = counter(
            "tpu:kvplane_defrag_runs_total",
            "Free-list compactions run between fused windows")
        self.kvplane_defrag_block_moves = counter(
            "tpu:kvplane_defrag_block_moves_total",
            "Free-list positions reordered by defrag")
        self.kvplane_free_contiguity = gauge(
            "tpu:kvplane_free_contiguity",
            "Fraction of adjacent free-block-id pairs (1.0 = one dense "
            "run; the quantity defrag restores)")
        self.kvplane_chunk_deadline_hits = counter(
            "tpu:kvplane_prefetch_chunk_deadline_hits_total",
            "Prefetch walks cut because one chunk blew its fair-share "
            "slice of the budget (per-remaining-chunk accounting)")
        self.kvplane_pipelined_fetches = counter(
            "tpu:kvplane_pipelined_fetches_total",
            "Chunk reads issued while an earlier chunk was still "
            "being consumed (pipelined prefetch overlap)")
        self._kvplane_codec_bytes_in = Counter(
            "tpu:kvplane_codec_bytes_in",
            "Logical chunk-body bytes entering a tier codec's encoder",
            list(labels) + ["tier", "codec"], registry=self.registry)
        self._kvplane_codec_bytes_out = Counter(
            "tpu:kvplane_codec_bytes_out",
            "Encoded bytes written to the tier (bytes_in/bytes_out = "
            "the tier's capacity multiplier)",
            list(labels) + ["tier", "codec"], registry=self.registry)
        self._kvplane_codec_rejects = Counter(
            "tpu:kvplane_codec_rejects",
            "Encoded payloads rejected by the post-encode checksum "
            "(torn/corrupt values read as misses and evicted)",
            list(labels) + ["tier", "codec"], registry=self.registry)
        self._labels = labels
        self._kv_last: dict = {}
        self._eff_last: dict = {}
        self._kvpool_last: dict = {}

    _KV_COUNTER_KEYS = (
        ("query_tokens", "kv_query_tokens"),
        ("hit_tokens", "kv_hit_tokens"),
        ("foreign_hit_tokens", "kv_foreign_hit_tokens"),
        ("chunk_hits", "kv_chunk_hits"),
        ("chunk_misses", "kv_chunk_misses"),
        ("bytes_loaded", "kv_bytes_loaded"),
        ("bytes_saved", "kv_bytes_saved"),
        ("rejected_chunks", "kv_rejected_chunks"),
        ("dropped_saves", "kv_dropped_saves"),
        ("published_chunks", "kv_published_chunks"),
        ("progress_published_chunks", "kv_progress_published_chunks"),
        ("prefetch_chunk_deadline_hits", "kvplane_chunk_deadline_hits"),
        ("pipelined_fetches", "kvplane_pipelined_fetches"),
        ("migrated_chunks", "kvplane_migrated_chunks"),
        ("warmed_chunks", "kvplane_warmed_chunks"),
    )

    def sync_kv(self, report: dict) -> None:
        """Fold a connector ``stats_report()`` into the exposition:
        counters advance by the delta since the last sync, tier gauges
        are set absolutely."""
        for src, attr in self._KV_COUNTER_KEYS:
            total = report.get(src, 0)
            delta = total - self._kv_last.get(src, 0)
            if delta > 0:
                getattr(self, attr).inc(delta)
            self._kv_last[src] = total
        for tier, total in (report.get("tier_hits") or {}).items():
            key = f"tier_hits:{tier}"
            delta = total - self._kv_last.get(key, 0)
            if delta > 0:
                self._kv_tier_hits.labels(tier=tier,
                                          **self._labels).inc(delta)
            self._kv_last[key] = total
        self.kv_remote_breaker_open.set(
            1.0 if report.get("remote_breaker_open") else 0.0)
        role = report.get("role")
        if role:
            self._kv_role.labels(role=role, **self._labels).set(1.0)
        for tier, st in (report.get("tiers") or {}).items():
            self._kv_tier_bytes.labels(tier=tier, **self._labels).set(
                st.get("bytes", 0))
            self._kv_tier_items.labels(tier=tier, **self._labels).set(
                st.get("count", 0))
        for row in report.get("codecs") or []:
            tier, codec = row.get("tier", "?"), row.get("codec", "?")
            for src, metric in (
                    ("bytes_in", self._kvplane_codec_bytes_in),
                    ("bytes_out", self._kvplane_codec_bytes_out),
                    ("rejects", self._kvplane_codec_rejects)):
                self._delta_inc(
                    metric.labels(tier=tier, codec=codec,
                                  **self._labels),
                    self._kv_last, f"codec:{tier}:{codec}:{src}",
                    row.get(src, 0))

    def _delta_inc(self, metric, last: dict, key: str, total) -> None:
        delta = total - last.get(key, 0)
        if delta > 0:
            metric.inc(delta)
        last[key] = total

    def sync_eff(self, report: dict, rates: dict) -> None:
        """Fold an ``EngineEffAccounting.report()/rates()`` pair into
        the exposition: token-step/compile counters advance by deltas,
        rate gauges are set absolutely."""
        dec = report.get("decode") or {}
        for kind in ("real", "pad", "dead"):
            self._delta_inc(
                self._token_steps.labels(kind=kind, phase="decode",
                                         **self._labels),
                self._eff_last, f"decode:{kind}", dec.get(kind, 0))
        pre = report.get("prefill") or {}
        for kind in ("real", "pad"):
            self._delta_inc(
                self._token_steps.labels(kind=kind, phase="prefill",
                                         **self._labels),
                self._eff_last, f"prefill:{kind}", pre.get(kind, 0))
        for key, entry in (report.get("compiles") or {}).items():
            kind, window, kv, batch = (key.split("|") + ["0"])[:4]
            self._delta_inc(
                self._compiles.labels(kind=kind, window=window,
                                      kv_bucket=kv, batch=batch,
                                      **self._labels),
                self._eff_last, f"compile:{key}", entry["count"])
        self.compile_in_flight.set(report.get("compile_in_flight", 0))
        self.effective_bytes_per_s.set(
            rates.get("effective_bytes_per_s", 0.0))
        self.mbu_perc.set(rates.get("mbu_perc", 0.0))
        self.decode_live_fraction.set(rates.get("live_fraction", 0.0))

    def sync_kvpool(self, report: dict) -> None:
        """Fold a ``BlockManager.frag_report()`` into the exposition."""
        for state in ("free", "active", "cached"):
            self._kvpool_blocks.labels(state=state, **self._labels).set(
                report.get(state, 0))
        for reason in ("exhausted", "fragmented"):
            self._delta_inc(
                self._kvpool_alloc_failures.labels(reason=reason,
                                                   **self._labels),
                self._kvpool_last, reason,
                report.get(f"alloc_failures_{reason}", 0))
        self._delta_inc(self.kvpool_cache_evictions, self._kvpool_last,
                        "cache_evictions",
                        report.get("cache_evictions", 0))
        self._delta_inc(self.kvplane_defrag_runs, self._kvpool_last,
                        "defrag_runs", report.get("defrag_runs", 0))
        self._delta_inc(self.kvplane_defrag_block_moves,
                        self._kvpool_last, "defrag_block_moves",
                        report.get("defrag_block_moves", 0))
        self.kvplane_free_contiguity.set(
            report.get("free_contiguity", 1.0))

    def render(self) -> bytes:
        return generate_latest(self.registry)
