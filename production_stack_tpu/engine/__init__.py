"""TPU-native serving engine.

The reference stack launches an external ``vllm serve`` container per
replica (reference: helm/templates/deployment-vllm-multi.yaml:57-64) and
never implements the engine itself. Here the engine is in-repo and
TPU-first: a continuous-batching loop over two cached XLA executables
(chunked prefill + batched decode), a statically-shaped slot KV cache,
fused on-device sampling, and an aiohttp OpenAI-compatible server.
"""

from production_stack_tpu.engine.config import EngineConfig

__all__ = ["EngineConfig"]
