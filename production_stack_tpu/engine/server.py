"""OpenAI-compatible HTTP server for the TPU engine (aiohttp).

Surface parity with what the reference's router expects from each engine
pod (reference: src/vllm_router/service_discovery.py:131-155 queries
/v1/models; stats/engine_stats.py scrapes /metrics; helm probes hit
/health): /v1/completions, /v1/chat/completions (streaming SSE and
non-streaming), /v1/models, /health, /metrics, /version, /tokenize,
/detokenize.

Built on aiohttp (no FastAPI dependency): handlers parse with pydantic
models from protocol.py and stream via chunked responses.
"""

import argparse
import asyncio
import json
import math
import time
from contextlib import aclosing
from typing import List, Optional

from aiohttp import web
from pydantic import ValidationError

from production_stack_tpu import protocol as proto
from production_stack_tpu.engine.async_engine import AsyncLLMEngine
from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.engine import (AdmissionRejected,
                                                DeadlineExceeded)
from production_stack_tpu.engine.scheduler import SamplingOptions
from production_stack_tpu.tracing import (TraceRecorder,
                                          debug_traces_handler)
from production_stack_tpu.utils import (honor_platform_env, init_logger,
                                          set_ulimit)
from production_stack_tpu.version import __version__

logger = init_logger(__name__)

ENGINE_KEY = web.AppKey("engine", AsyncLLMEngine)
TRACER_KEY = web.AppKey("tracer", TraceRecorder)

# paths whose requests get an engine-side trace (tracing.py): the
# generation endpoints the router's span chain continues into
TRACED_PATHS = frozenset({"/v1/chat/completions", "/v1/completions"})

# relative per-request budget in milliseconds; the router injects its
# own --request-timeout here when the client sent none (docs/router.md
# "Overload protection")
DEADLINE_HEADER = "x-request-deadline-ms"
# marks an engine 504 as "the CLIENT's deadline elapsed" — the router
# relays it without a breaker signal or failover (retrying a request
# whose budget is spent helps nobody)
DEADLINE_MARKER = "x-deadline-expired"


def _stash_timing(request: web.Request, out) -> None:
    """Capture a terminal StepOutput's phase timeline for the trace
    middleware (one attribute write on the stream path; the LAST
    finishing choice wins for n>1 requests)."""
    if out.finished and out.timing is not None:
        request["seq_timing"] = out.timing


def _seal_engine_trace(tracer: TraceRecorder, trace, request: web.Request,
                       status: str) -> None:
    """Build the engine-side span set from what the handlers stashed:

    - ``preprocess``: HTTP entry -> engine arrival (parse, chat
      template, tokenize, guided compile, KV-tier prefetch) — tokenize
      and kv_prefetch ride inside it as EVENT spans so the phase sum
      never double-counts;
    - ``queue_wait`` / ``prefill`` / ``decode``: from the terminal
      StepOutput's timing stamps (engine._seq_timing);
    - ``postprocess``: last engine output -> response done.

    Requests that never produced a sequence (400s, sheds, deadline
    504s) get a single ``preprocess`` phase covering their whole life.

    XLA compiles that overlapped this request's life are attached as
    ``xla_compile`` EVENT spans (engine/efficiency.py keeps the bounded
    compile-event ring): a compile stalls every in-flight request, so a
    request whose tail latency was a compile must say so in
    ``/debug/traces`` instead of showing unattributed decode time.
    """
    now = time.monotonic()
    engine = request.app.get(ENGINE_KEY)
    if engine is not None:
        for (start, dur, kind, window, kv, batch) in \
                engine.engine.eff.compile_events_between(trace.t0, now):
            trace.add_event("xla_compile", start, dur,
                            attrs={"kind": kind, "window": window,
                                   "kv_bucket": kv, "batch": batch})
    timing = request.get("seq_timing")
    tok_s = request.get("trace_tokenize_s")
    if timing is not None:
        arrival = timing["arrival"]
        admit = timing["admit"]
        end = timing["end"]
        trace.add_phase("preprocess", trace.t0, arrival)
        if admit is None:
            # never admitted (WAITING-dropped: deadline / queue-delay
            # shed): the whole engine-side life is queue wait — it must
            # NOT render as prefill, or a shed storm's traces point the
            # operator at the wrong phase
            trace.add_phase("queue_wait", arrival, end)
        else:
            # queue_wait_s is cumulative across admissions (preemption
            # re-queues); render it anchored at arrival so the span
            # layout stays readable while the durations stay honest
            qw = timing.get("queue_wait_s") or max(0.0, admit - arrival)
            trace.add_span("queue_wait", arrival, qw, "phase")
            first = timing["first_token"] if timing["first_token"] \
                is not None else end
            trace.add_phase("prefill", admit, max(admit, first))
            trace.add_phase("decode", max(admit, first), end)
        trace.add_phase("postprocess", end, now)
        if timing.get("kv_prefetch_wait_s"):
            trace.add_event(
                "kv_prefetch", None, timing["kv_prefetch_wait_s"],
                attrs={"cached_tokens": timing.get("kv_cached_tokens",
                                                   0)})
        trace.attrs["prompt_tokens"] = timing.get("prompt_tokens")
        trace.attrs["output_tokens"] = timing.get("output_tokens")
    else:
        trace.add_phase("preprocess", trace.t0, now)
    if tok_s:
        trace.add_event("tokenize", None, tok_s)
    tracer.finish(trace, status)


def _trace_middleware(tracer: TraceRecorder):
    @web.middleware
    async def record_trace(request: web.Request, handler):
        if request.path not in TRACED_PATHS:
            return await handler(request)
        trace = tracer.begin(request.headers.get("traceparent"),
                             name=request.path)
        request["trace"] = trace
        try:
            resp = await handler(request)
        except BaseException:
            _seal_engine_trace(tracer, trace, request, "exception")
            raise
        if not resp.prepared:
            resp.headers["x-trace-id"] = trace.trace_id
        status = request.get("trace_status") or (
            "ok" if resp.status < 400 else f"http_{resp.status}")
        _seal_engine_trace(tracer, trace, request, status)
        return resp
    return record_trace


def _error(status: int, message: str,
           err_type: str = "invalid_request_error") -> web.Response:
    body = proto.ErrorResponse(
        error=proto.ErrorInfo(message=message, type=err_type,
                              code=status))
    return web.json_response(body.model_dump(), status=status)


class _QueueDelayShed(Exception):
    """The scheduler shed this request for exceeding max_queue_delay_ms
    while WAITING (finish_reason "queue_delay")."""


def _deadline_from(request: web.Request):
    """Parse x-request-deadline-ms into an absolute monotonic deadline.
    Returns (deadline_or_None, error_response_or_None)."""
    raw = request.headers.get(DEADLINE_HEADER)
    if raw is None:
        return None, None
    try:
        ms = float(raw)
    except ValueError:
        return None, _error(400, f"{DEADLINE_HEADER} must be a number "
                                 f"of milliseconds (got {raw!r})")
    if not math.isfinite(ms):
        return None, _error(400, f"{DEADLINE_HEADER} must be finite")
    if ms <= 0:
        # already expired on arrival: answer 504 before any engine work
        return None, _deadline_error()
    return time.monotonic() + ms / 1e3, None


def _deadline_error() -> web.Response:
    resp = _error(504, "request deadline expired while waiting for "
                       "admission (x-request-deadline-ms elapsed before "
                       "the engine could start it)",
                  err_type="timeout_error")
    resp.headers[DEADLINE_MARKER] = "1"
    return resp


def _shed_error(engine: AsyncLLMEngine,
                message: Optional[str] = None) -> web.Response:
    """Structured 503 + Retry-After: the overload shed the router's
    resilience layer recognizes as shed-not-sick."""
    retry_s = max(1.0, engine.engine.estimated_queue_delay_s())
    resp = _error(503, message or "engine overloaded: request shed; "
                                  "retry after the indicated delay",
                  err_type="overloaded_error")
    resp.headers["Retry-After"] = str(int(math.ceil(retry_s)))
    return resp


def _load_headers(engine: AsyncLLMEngine) -> dict:
    """The per-response load report (cheap, lock-free): every reply
    carries the engine's pressure signals so callers (and the router)
    see load without an extra round trip."""
    report = engine.engine.load_report()
    return {
        "x-engine-queue-depth": str(report["queue_depth"]),
        "x-engine-running": str(report["running"]),
        "x-engine-free-kv-blocks": str(report["free_kv_blocks"]),
        "x-engine-est-queue-delay-ms": str(report["est_queue_delay_ms"]),
    }


def _check_overload_finish(out) -> None:
    """Translate a WAITING-dropped sequence's terminal StepOutput
    (engine.step's expire pass: no token, no text) into the structured
    error the client contract promises."""
    if not out.finished or out.new_token is not None or out.text_delta:
        return
    if out.finish_reason == "deadline":
        raise DeadlineExceeded()
    if out.finish_reason == "queue_delay":
        raise _QueueDelayShed()


async def _guarded_payloads(merged, lead_payloads, chunk_for):
    """Shared streaming shape for the chat/completions SSE paths: pull
    the FIRST engine output off ``merged`` before emitting the
    ``lead_payloads`` (role/echo chunks), so an admission shed or a
    WAITING-deadline drop surfaces pre-yield and _sse_stream can still
    answer a structured 503/504 instead of a truncated stream; then
    relay ``chunk_for(i, out)`` payloads. A drop arriving AFTER the
    response started (another choice's shed, or a preempted sequence's
    deadline) is NOT an error: the transport is healthy, so that choice
    simply terminates with its finish_reason chunk ("deadline" /
    "queue_delay") while its siblings stream on to [DONE]."""
    try:
        head = await merged.__anext__()
    except StopAsyncIteration:
        head = None
    if head is not None:
        _check_overload_finish(head[1])
    for payload in lead_payloads:
        yield payload
    if head is not None:
        payload = chunk_for(*head)
        if payload is not None:
            yield payload
        async for i, out in merged:
            payload = chunk_for(i, out)
            if payload is not None:
                yield payload


def _logit_bias(req) -> Optional[dict]:
    """OpenAI logit_bias {token-id-string: bias} -> {int: float},
    bounded by the device-side slot width (sampler.LOGIT_BIAS_K)."""
    raw = getattr(req, "logit_bias", None)
    if not raw:
        return None
    # OpenAI documents a 300-entry cap; the device slot width
    # (sampler.LOGIT_BIAS_K) covers it, so the API-parity bound is the
    # binding one here
    if len(raw) > 300:
        raise ValueError(
            f"logit_bias supports at most 300 entries (got {len(raw)})")
    try:
        return {int(k): float(v) for k, v in raw.items()}
    except (TypeError, ValueError):
        raise ValueError("logit_bias keys must be token ids and values "
                         "numbers")


def _top_logprobs(req) -> int:
    """How many per-token alternatives the request wants: chat's
    top_logprobs, or legacy completions' integer logprobs=N (OpenAI
    caps both at 20, rejects negatives, and requires chat's
    logprobs=true alongside top_logprobs)."""
    tl = getattr(req, "top_logprobs", None)
    if tl is not None and not 0 <= tl <= 20:
        raise ValueError(
            f"top_logprobs must be in [0, 20] (got {tl})")
    if tl and not getattr(req, "logprobs", None):
        raise ValueError(
            "top_logprobs requires logprobs to be set to true")
    tl = tl or 0
    if not tl:
        lp = getattr(req, "logprobs", None)
        if isinstance(lp, int) and not isinstance(lp, bool) and lp > 0:
            tl = lp
    if tl > 20:
        raise ValueError(f"top_logprobs supports at most 20 (got {tl})")
    return int(tl)


def _sampling_options(req, max_tokens: Optional[int]) -> SamplingOptions:
    stop = req.stop if isinstance(req.stop, list) else (
        [req.stop] if req.stop else [])
    return SamplingOptions(
        temperature=req.temperature,
        top_p=req.top_p,
        top_k=req.top_k,
        max_tokens=max_tokens if max_tokens is not None else 128,
        stop=stop,
        stop_token_ids=req.stop_token_ids or [],
        ignore_eos=req.ignore_eos,
        seed=req.seed,
        guided_regex=_guided_pattern(req),
        presence_penalty=req.presence_penalty,
        frequency_penalty=req.frequency_penalty,
        repetition_penalty=req.repetition_penalty,
        min_p=req.min_p,
        min_tokens=req.min_tokens,
        priority=req.priority,
        logit_bias=_logit_bias(req),
        top_logprobs=_top_logprobs(req),
    )


async def _precompile_guided(engine, options) -> None:
    """Compile the request's grammar (LRU-cached) BEFORE streaming, in
    a thread: a bad pattern becomes a 400 here instead of a 500
    mid-stream, and a first-time compile (a full-vocab token lift,
    seconds on large vocabularies) never blocks the event loop."""
    if not options.guided_regex:
        return
    from production_stack_tpu.engine import guided
    await asyncio.get_running_loop().run_in_executor(
        None, guided.compile_grammar, options.guided_regex,
        engine.tokenizer)


def _guided_pattern(req) -> Optional[str]:
    """vLLM-style guided decoding knobs -> one regex (or None)."""
    if getattr(req, "guided_regex", None):
        return req.guided_regex
    if getattr(req, "guided_choice", None):
        from production_stack_tpu.engine import guided
        return guided.choice_regex(req.guided_choice)
    if getattr(req, "guided_json", None) is not None:
        from production_stack_tpu.engine import guided
        # schema errors surface as RegexError -> 400 at validation
        return guided.json_schema_regex(req.guided_json)
    rf = getattr(req, "response_format", None)
    if rf:
        kind = rf.get("type")
        if kind == "json_schema":
            from production_stack_tpu.engine import guided
            spec = rf.get("json_schema") or {}
            schema = spec.get("schema", spec)   # OpenAI nests .schema
            return guided.json_schema_regex(schema)
        if kind == "json_object":
            raise ValueError(
                "response_format json_object (free-form JSON) is not "
                "supported: a DFA cannot express unbounded-depth JSON. "
                "Use response_format json_schema or guided_json with a "
                "schema.")
        if kind not in (None, "text"):
            raise ValueError(f"unsupported response_format type {kind!r}")
    return None


def _choice_options(options, i: int):
    """Per-choice SamplingOptions: a seeded request varies the seed by
    choice index, otherwise n identical seeds would return n identical
    completions (noise depends only on (seed, position))."""
    if i == 0 or options.seed is None:
        return options
    import dataclasses
    return dataclasses.replace(options, seed=options.seed + i)


async def _gather_cancelling(coros):
    """gather() where one failure cancels the siblings so they free
    their engine slots instead of generating into a discarded response
    (asyncio.TaskGroup semantics, but available on Python 3.10)."""
    tasks = [asyncio.ensure_future(c) for c in coros]
    try:
        return await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        # wait for the cancellations to land (TaskGroup semantics):
        # siblings must have freed their engine slots before the error
        # response goes out, and their exceptions must be retrieved
        await asyncio.gather(*tasks, return_exceptions=True)
        raise


def _choice_jobs(prompts, options, n):
    """The OpenAI choice grid: every (prompt, sample) pair gets a
    choice index prompt_idx * n + sample_idx. Returns
    [(index, prompt_ids, per-choice options)]."""
    return [(p * n + j, pids, _choice_options(options, j))
            for p, pids in enumerate(prompts) for j in range(n)]


def _merged_streams(engine, jobs, model, deadline=None):
    """Run the jobs [(choice_index, prompt_ids, options)] concurrently
    and yield (choice_index, StepOutput) in completion order — the
    OpenAI n>1 / batched-prompt streaming shape (each chunk carries its
    choice index). A pump failure propagates to the consumer (and
    cancels its siblings via the generator's finally); closing the
    generator cancels all pumps and frees their slots."""
    async def gen():
        q: asyncio.Queue = asyncio.Queue()

        async def pump(idx, pids, opts):
            try:
                async with aclosing(engine.stream(
                        list(pids), opts, model=model,
                        deadline=deadline)) as it:
                    async for out in it:
                        await q.put((idx, out))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                await q.put((idx, e))
                return
            await q.put((idx, None))

        tasks = [asyncio.ensure_future(pump(*job)) for job in jobs]
        try:
            done = 0
            while done < len(jobs):
                i, out = await q.get()
                if out is None:
                    done += 1
                    continue
                if isinstance(out, BaseException):
                    raise out
                yield i, out
        finally:
            for t in tasks:
                t.cancel()
    return gen()


async def _sse_stream(request: web.Request, gen) -> web.StreamResponse:
    """Relay an SSE generator, preparing the response lazily: the 200
    and its headers go out with the FIRST payload, so an admission shed
    or a deadline expiry that surfaces before any byte is written
    becomes a clean structured 503/504 instead of a truncated stream.
    (Raised after bytes have been relayed, the same failures can only
    truncate — the connection is dropped.)"""
    engine = request.app[ENGINE_KEY]
    resp: Optional[web.StreamResponse] = None

    async def ensure_prepared() -> web.StreamResponse:
        nonlocal resp
        if resp is None:
            headers = {"Content-Type": "text/event-stream",
                       "Cache-Control": "no-cache",
                       "X-Accel-Buffering": "no",
                       **_load_headers(engine)}
            trace = request.get("trace")
            if trace is not None:
                # streams take their trace id at prepare time (the
                # middleware can no longer add headers then)
                headers["x-trace-id"] = trace.trace_id
            resp = web.StreamResponse(status=200, headers=headers)
            await resp.prepare(request)
        return resp

    try:
        async for payload in gen:
            await ensure_prepared()
            await resp.write(f"data: {payload}\n\n".encode())
        await ensure_prepared()
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
    except (ConnectionResetError, ConnectionError):
        # client went away mid-stream; generator cleanup aborts the request
        request["trace_status"] = "client_disconnect"
        await gen.aclose()
        if resp is None:
            resp = web.Response(status=500)     # never reaches the client
    except AdmissionRejected as e:
        await gen.aclose()
        if resp is None:
            return _shed_error(engine, str(e))
        resp.force_close()
    except DeadlineExceeded:
        await gen.aclose()
        if resp is None:
            return _deadline_error()
        resp.force_close()
    except _QueueDelayShed:
        await gen.aclose()
        if resp is None:
            return _shed_error(engine)
        resp.force_close()
    return resp


# ---------------------------------------------------------------- handlers

def _lp_skip(out) -> bool:
    """OpenAI alignment: a token that STOPPED the sequence (EOS / stop
    token / stop string) is excluded from the returned text, so it gets
    no logprobs entry either. (Earlier tokens of a multi-token stop
    string were already emitted before the match — a known, bounded
    deviation.) Length-finished tokens are real content and stay."""
    return out.finished and out.finish_reason == "stop"


def _chat_lp_entry(tok, token_id: int, logprob, want_top: bool,
                   alts=None):
    """One chat-logprobs content entry. `alts` [(token_id, logprob)]
    are the device-computed top-K alternatives of the same raw model
    distribution the chosen logprob reports (engine/runner.py); paths
    that don't produce them (e.g. speculative windows never run with
    alternatives requested) fall back to the chosen entry. Token
    text/bytes come from the tokenizer's own token representation so
    multi-byte-split pieces stay distinct."""
    text, raw = tok.id_to_token(token_id)
    lp = logprob if logprob is not None else 0.0
    entry = proto.ChatLogprobToken(token=text, logprob=lp, bytes=raw)
    if want_top:
        if alts:
            tops = []
            for tid, tlp in alts:
                ttext, traw = tok.id_to_token(int(tid))
                tops.append(proto.ChatLogprobTop(
                    token=ttext, logprob=float(tlp), bytes=traw))
            entry.top_logprobs = tops
        else:
            entry.top_logprobs = [proto.ChatLogprobTop(
                token=text, logprob=lp, bytes=raw)]
    return entry


def _completion_logprobs(tok, token_ids, logprobs, want_top: bool,
                         alts_list=None) -> "proto.CompletionLogprobs":
    """Legacy completions logprobs block. alts_list (parallel to
    token_ids) holds [(id, logprob)] device-computed top-N
    alternatives; entries without them fall back to the chosen
    token."""
    texts = [tok.id_to_token(t)[0] for t in token_ids]
    lps = [lp if lp is not None else 0.0 for lp in logprobs]
    top = None
    if want_top:
        top = []
        for i, (text, lp) in enumerate(zip(texts, lps)):
            alts = alts_list[i] if alts_list else None
            if alts:
                top.append({tok.id_to_token(int(t))[0]: float(l)
                            for t, l in alts})
            else:
                top.append({text: lp})
    return proto.CompletionLogprobs(tokens=texts, token_logprobs=lps,
                                    top_logprobs=top)


async def _prompt_echo_blocks(engine, tok, prompts, req):
    """[(prompt_text, CompletionLogprobs-or-None)] per prompt for
    legacy echo=true: the prompt text prefixes the completion; with
    logprobs requested, teacher-forced prompt logprobs for ALL prompts
    are computed in ONE padded batched device call (position 0 reports
    null, OpenAI format). Each block is shared by its n choices."""
    import numpy as np
    texts = [tok.decode(p) for p in prompts]
    if req.logprobs is None:
        return [(t, None) for t in texts]
    runner = engine.engine.runner
    T = max(len(p) for p in prompts)
    arr = np.zeros((len(prompts), T), np.int32)
    for r, p in enumerate(prompts):
        arr[r, :len(p)] = p

    def compute():
        # rows are padded to a shared bucket: slice each to its len-1
        out = np.asarray(runner.prompt_logprobs(arr))
        return [out[r, :len(p) - 1].tolist()
                for r, p in enumerate(prompts)]

    all_lps = await asyncio.get_running_loop().run_in_executor(
        None, compute)
    blocks = []
    for text, pids, lps in zip(texts, prompts, all_lps):
        pieces = [tok.id_to_token(t)[0] for t in pids]
        token_lps = [None] + [float(v) for v in lps]
        top = None
        if req.logprobs > 0:
            top = [None] + [{pc: lp} for pc, lp in
                            zip(pieces[1:], token_lps[1:])]
        blocks.append((text, proto.CompletionLogprobs(
            tokens=pieces, token_logprobs=token_lps, top_logprobs=top)))
    return blocks


def _merge_echo_lp(echo_lp, lp_block):
    """Prepend the prompt's logprobs block to a completion's."""
    if echo_lp is None:
        return lp_block
    merged = proto.CompletionLogprobs(
        tokens=echo_lp.tokens + lp_block.tokens,
        token_logprobs=echo_lp.token_logprobs + lp_block.token_logprobs,
        top_logprobs=(echo_lp.top_logprobs + lp_block.top_logprobs
                      if echo_lp.top_logprobs is not None
                      and lp_block.top_logprobs is not None else None))
    return merged


async def chat_completions(request: web.Request) -> web.StreamResponse:
    engine = request.app[ENGINE_KEY]
    try:
        req = proto.ChatCompletionRequest(**await request.json())
    except (ValidationError, json.JSONDecodeError) as e:
        return _error(400, f"invalid request: {e}")
    if not 1 <= req.n <= 128:
        return _error(400, "n must be between 1 and 128")
    try:
        engine.engine.resolve_model(req.model or None)
    except ValueError as e:
        return _error(404, str(e))
    deadline, bad = _deadline_from(request)
    if bad is not None:
        return bad
    if engine.engine.admission_full():
        # cheap-shed fast path: refuse before tokenization/template
        # work — under a shed storm the 503s must cost near-nothing
        return _shed_error(engine)

    tok = engine.tokenizer
    t_tok = time.monotonic()
    prompt = tok.apply_chat_template(
        [m.model_dump() for m in req.messages])
    prompt_ids = tok.encode(prompt)
    request["trace_tokenize_s"] = time.monotonic() - t_tok
    if len(prompt_ids) >= engine.engine.cfg.max_model_len:
        return _error(400, f"prompt has {len(prompt_ids)} tokens, which "
                           f"exceeds max_model_len "
                           f"{engine.engine.cfg.max_model_len}")
    max_tokens = req.max_completion_tokens or req.max_tokens
    try:
        options = _sampling_options(req, max_tokens)
        await _precompile_guided(engine, options)
    except ValueError as e:
        return _error(400, f"invalid guided decoding constraint: {e}")
    rid = proto._gen_id("chatcmpl")

    if req.stream:
        include_usage = bool(req.stream_options
                             and req.stream_options.include_usage)

        async def gen():
            # OpenAI chunk shape: with include_usage every chunk carries
            # "usage": null until the final usage chunk; without it the
            # field is omitted entirely
            exclude = None if include_usage else {"usage"}
            num_tokens = 0

            def chunk_for(i, out):
                nonlocal num_tokens
                _stash_timing(request, out)
                if out.new_token is not None:
                    num_tokens += 1
                lp_block = None
                if (req.logprobs and out.new_token is not None
                        and not _lp_skip(out)):
                    lp_block = proto.ChatLogprobs(content=[
                        _chat_lp_entry(tok, out.new_token,
                                       out.logprob,
                                       bool(req.top_logprobs),
                                       out.top_alts)])
                # a token can produce no text yet (partial UTF-8 in
                # the detokenizer) — its logprob entry must still
                # be delivered
                if out.text_delta or out.finished or lp_block:
                    chunk = proto.ChatCompletionChunk(
                        id=rid, model=req.model,
                        choices=[proto.ChatCompletionChunkChoice(
                            index=i,
                            delta=proto.DeltaMessage(
                                content=out.text_delta or None),
                            finish_reason=out.finish_reason if out.finished
                            else None,
                            logprobs=lp_block)])
                    return chunk.model_dump_json(exclude=exclude)
                return None

            role_chunks = [
                proto.ChatCompletionChunk(
                    id=rid, model=req.model,
                    choices=[proto.ChatCompletionChunkChoice(
                        index=i,
                        delta=proto.DeltaMessage(role="assistant",
                                                 content=""))]
                ).model_dump_json(exclude=exclude)
                for i in range(req.n)]
            # aclosing => a dropped consumer deterministically runs
            # every stream's cleanup (slot aborts), not at GC's leisure
            async with aclosing(_merged_streams(
                    engine, _choice_jobs([prompt_ids], options, req.n),
                    req.model or None, deadline)) as it:
                async for payload in _guarded_payloads(
                        it, role_chunks, chunk_for):
                    yield payload
            if include_usage:
                # OpenAI semantics: one final chunk, empty choices, usage
                tail = proto.ChatCompletionChunk(
                    id=rid, model=req.model, choices=[],
                    usage=proto.UsageInfo(
                        prompt_tokens=len(prompt_ids),
                        completion_tokens=num_tokens,
                        total_tokens=len(prompt_ids) + num_tokens))
                yield tail.model_dump_json()
        return await _sse_stream(request, gen())

    async def collect_one(i: int):
        parts: List[str] = []
        lp_entries: List = []
        finish_reason = None
        tokens = 0
        async with aclosing(engine.stream(
                list(prompt_ids), _choice_options(options, i),
                model=req.model or None, deadline=deadline)) as it:
            async for out in it:
                _check_overload_finish(out)
                _stash_timing(request, out)
                parts.append(out.text_delta)
                if out.new_token is not None:
                    tokens += 1
                    if req.logprobs and not _lp_skip(out):
                        lp_entries.append(_chat_lp_entry(
                            tok, out.new_token, out.logprob,
                            bool(req.top_logprobs), out.top_alts))
                if out.finished:
                    finish_reason = out.finish_reason
        choice = proto.ChatCompletionChoice(
            index=i,
            message=proto.ChatChoiceMessage(content="".join(parts)),
            finish_reason=finish_reason,
            logprobs=(proto.ChatLogprobs(content=lp_entries)
                      if req.logprobs else None))
        return choice, tokens

    try:
        results = await _gather_cancelling(
            [collect_one(i) for i in range(req.n)])
    except AdmissionRejected as e:
        return _shed_error(engine, str(e))
    except DeadlineExceeded:
        return _deadline_error()
    except _QueueDelayShed:
        return _shed_error(engine)
    num_tokens = sum(t for _, t in results)
    resp = proto.ChatCompletionResponse(
        id=rid, model=req.model,
        choices=[c for c, _ in results],
        usage=proto.UsageInfo(
            prompt_tokens=len(prompt_ids),
            completion_tokens=num_tokens,
            total_tokens=len(prompt_ids) + num_tokens))
    return web.json_response(resp.model_dump())


async def completions(request: web.Request) -> web.StreamResponse:
    engine = request.app[ENGINE_KEY]
    try:
        req = proto.CompletionRequest(**await request.json())
    except (ValidationError, json.JSONDecodeError) as e:
        return _error(400, f"invalid request: {e}")
    if not 1 <= req.n <= 128:
        return _error(400, "n must be between 1 and 128")
    try:
        engine.engine.resolve_model(req.model or None)
    except ValueError as e:
        return _error(404, str(e))
    deadline, bad = _deadline_from(request)
    if bad is not None:
        return bad
    if engine.engine.admission_full():
        return _shed_error(engine)

    tok = engine.tokenizer
    prompt = req.prompt
    # cap the choice grid BEFORE tokenizing a potentially huge batch on
    # the event loop ([int] prompts are one prompt, not a batch)
    if (isinstance(prompt, list) and prompt
            and isinstance(prompt[0], (str, list))
            and len(prompt) * req.n > 128):
        return _error(400, "len(prompt) * n must be <= 128")
    try:
        t_tok = time.monotonic()
        prompts = _as_token_lists(engine, prompt)
        request["trace_tokenize_s"] = time.monotonic() - t_tok
    except ValueError as e:
        return _error(400, str(e))
    if not prompts or any(not p for p in prompts):
        return _error(400, "prompt must not be (or contain) empty input")
    if len(prompts) * req.n > 128:
        return _error(400, "len(prompt) * n must be <= 128")
    for pids in prompts:
        if len(pids) >= engine.engine.cfg.max_model_len:
            return _error(400, f"prompt has {len(pids)} tokens, which "
                               f"exceeds max_model_len "
                               f"{engine.engine.cfg.max_model_len}")
    try:
        options = _sampling_options(req, req.max_tokens)
        await _precompile_guided(engine, options)
    except ValueError as e:
        return _error(400, f"invalid guided decoding constraint: {e}")
    rid = proto._gen_id("cmpl")

    # echo blocks are computed BEFORE any response starts: first-time
    # compiles and failures become a clean 500/400 here instead of a
    # truncated SSE stream (same policy as _precompile_guided)
    echo_blocks = []
    if req.echo:
        echo_blocks = await _prompt_echo_blocks(engine, tok, prompts, req)

    if req.stream:
        include_usage = bool(req.stream_options
                             and req.stream_options.include_usage)

        async def gen():
            exclude = None if include_usage else {"usage"}
            num_tokens = 0

            def chunk_for(i, out):
                nonlocal num_tokens
                _stash_timing(request, out)
                if out.new_token is not None:
                    num_tokens += 1
                lp_block = None
                if (req.logprobs is not None
                        and out.new_token is not None
                        and not _lp_skip(out)):
                    lp_block = _completion_logprobs(
                        tok, [out.new_token], [out.logprob],
                        req.logprobs > 0, [out.top_alts])
                if out.text_delta or out.finished or lp_block:
                    chunk = proto.CompletionChunk(
                        id=rid, model=req.model,
                        choices=[proto.CompletionChunkChoice(
                            index=i,
                            text=out.text_delta,
                            finish_reason=out.finish_reason if out.finished
                            else None,
                            logprobs=lp_block)])
                    return chunk.model_dump_json(exclude=exclude)
                return None

            echo_chunks = [
                proto.CompletionChunk(
                    id=rid, model=req.model,
                    choices=[proto.CompletionChunkChoice(
                        index=p * req.n + j, text=echo_text,
                        logprobs=echo_lp)]
                ).model_dump_json(exclude=exclude)
                for p, (echo_text, echo_lp) in enumerate(echo_blocks)
                for j in range(req.n)]
            async with aclosing(_merged_streams(
                    engine, _choice_jobs(prompts, options, req.n),
                    req.model or None, deadline)) as it:
                async for payload in _guarded_payloads(
                        it, echo_chunks, chunk_for):
                    yield payload
            if include_usage:
                n_prompt = sum(len(p) for p in prompts)
                tail = proto.CompletionChunk(
                    id=rid, model=req.model, choices=[],
                    usage=proto.UsageInfo(
                        prompt_tokens=n_prompt,
                        completion_tokens=num_tokens,
                        total_tokens=n_prompt + num_tokens))
                yield tail.model_dump_json()
        return await _sse_stream(request, gen())

    async def collect_one(idx: int, pids, opts):
        parts: List[str] = []
        out_ids: List[int] = []
        out_lps: List = []
        out_alts: List = []
        tokens = 0
        finish_reason = None
        async with aclosing(engine.stream(
                list(pids), opts, model=req.model or None,
                deadline=deadline)) as it:
            async for out in it:
                _check_overload_finish(out)
                _stash_timing(request, out)
                parts.append(out.text_delta)
                if out.new_token is not None:
                    tokens += 1
                    if not _lp_skip(out):
                        out_ids.append(out.new_token)
                        out_lps.append(out.logprob)
                        out_alts.append(out.top_alts)
                if out.finished:
                    finish_reason = out.finish_reason
        lp_block = (_completion_logprobs(tok, out_ids, out_lps,
                                         req.logprobs > 0, out_alts)
                    if req.logprobs is not None else None)
        echo_text = ""
        if req.echo:
            echo_text, echo_lp = echo_blocks[idx // req.n]
            lp_block = (_merge_echo_lp(echo_lp, lp_block)
                        if lp_block is not None else None)
        choice = proto.CompletionChoice(
            index=idx,
            text=echo_text + "".join(parts),
            finish_reason=finish_reason,
            logprobs=lp_block)
        return choice, tokens

    try:
        results = await _gather_cancelling(
            [collect_one(*job)
             for job in _choice_jobs(prompts, options, req.n)])
    except AdmissionRejected as e:
        return _shed_error(engine, str(e))
    except DeadlineExceeded:
        return _deadline_error()
    except _QueueDelayShed:
        return _shed_error(engine)
    num_tokens = sum(t for _, t in results)
    n_prompt = sum(len(p) for p in prompts)
    resp = proto.CompletionResponse(
        id=rid, model=req.model,
        choices=[c for c, _ in results],
        usage=proto.UsageInfo(
            prompt_tokens=n_prompt, completion_tokens=num_tokens,
            total_tokens=n_prompt + num_tokens))
    return web.json_response(resp.model_dump())


def _as_token_lists(engine, raw, tok=None) -> List[List[int]]:
    """OpenAI-style `input`/`prompt`: str | [str] | [int] | [[int]].
    `tok` picks the tokenizer: completions pass the chat tokenizer
    (default); pooling endpoints pass engine.embedding_tokenizer (the
    encoder checkpoint's own when one is configured)."""
    tok = tok or engine.tokenizer
    if isinstance(raw, str):
        return [tok.encode(raw)]
    if not isinstance(raw, list):
        raise ValueError("input must be str, [str], [int], or [[int]]")
    if raw and all(isinstance(x, int) and not isinstance(x, bool)
                   for x in raw):
        return [list(raw)]
    out: List[List[int]] = []
    for item in raw:
        if isinstance(item, str):
            out.append(tok.encode(item))
        elif isinstance(item, list) and all(
                isinstance(x, int) and not isinstance(x, bool)
                for x in item):
            out.append(list(item))
        else:
            raise ValueError("input must be str, [str], [int], or [[int]]")
    return out


def _check_pool_model(engine, model) -> Optional[web.Response]:
    """Pooling endpoints serve only the BASE model: embeddings pool raw
    hidden states, which the LoRA path does not color (adapters would
    need an adapter-aware encode). Unknown models 404, adapters 400."""
    try:
        adapter_id = engine.engine.resolve_model(model or None)
    except ValueError as e:
        return _error(404, str(e))
    if adapter_id != 0:
        return _error(400, f"model {model!r} is a LoRA adapter; "
                           f"embeddings/rerank/score serve the base "
                           f"model only")
    return None


async def _pooled(request: web.Request, token_lists: List[List[int]]):
    """Run the embedding batch off the event loop (device-blocking)."""
    engine = request.app[ENGINE_KEY]
    max_len = engine.engine.max_embed_len
    for toks in token_lists:
        if not toks:
            raise ValueError("empty input")
        if len(toks) > max_len:
            raise ValueError(f"input has {len(toks)} tokens, which "
                             f"exceeds the embedding length cap "
                             f"{max_len}")
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        None, engine.engine.embed_tokens, token_lists)


async def embeddings(request: web.Request) -> web.Response:
    """OpenAI-compatible /v1/embeddings (reference surface:
    src/vllm_router/routers/main_router.py:42-160 proxies this path to
    the engine). With --embedding-model, vectors come from a real
    bidirectional encoder (models/encoder.py); otherwise they are
    mean-pooled hidden states of the causal chat model — an API-shape
    approximation whose quality is unvalidated, declared to clients via
    the non-standard "embedding_source" field (docs/router.md)."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
        bad = _check_pool_model(engine, body.get("model"))
        if bad is not None:
            return bad
        token_lists = _as_token_lists(
            engine, body.get("input"),
            tok=engine.engine.embedding_tokenizer)
        if not token_lists:
            return _error(400, "missing 'input'")
        vecs = await _pooled(request, token_lists)
    except (ValueError, TypeError, json.JSONDecodeError) as e:
        return _error(400, f"invalid request: {e}")
    n_tokens = sum(len(t) for t in token_lists)
    return web.json_response({
        "object": "list",
        "model": body.get("model") or engine.model_name,
        "embedding_source": engine.engine.embedding_source,
        "data": [{"object": "embedding", "index": i,
                  "embedding": vec.tolist()}
                 for i, vec in enumerate(vecs)],
        "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
    })


def _cosine(a, b):
    import numpy as np
    num = float(np.dot(a, b))
    den = float(np.linalg.norm(a) * np.linalg.norm(b)) or 1e-12
    return num / den


async def rerank(request: web.Request) -> web.Response:
    """/v1/rerank: order documents by embedding similarity to the query
    (bi-encoder scoring over the served model's hidden states)."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
        bad = _check_pool_model(engine, body.get("model"))
        if bad is not None:
            return bad
        query = body.get("query")
        docs = body.get("documents")
        if not isinstance(query, str) or not isinstance(docs, list) \
                or not docs or not all(isinstance(d, str) for d in docs):
            return _error(400, "need 'query' (str) and 'documents' "
                               "(non-empty list of str)")
        token_lists = _as_token_lists(
            engine, [query] + list(docs),
            tok=engine.engine.embedding_tokenizer)
        vecs = await _pooled(request, token_lists)
    except (ValueError, TypeError, json.JSONDecodeError) as e:
        return _error(400, f"invalid request: {e}")
    q, dvecs = vecs[0], vecs[1:]
    scored = sorted(
        ({"index": i, "document": {"text": d},
          "relevance_score": _cosine(q, v)}
         for i, (d, v) in enumerate(zip(docs, dvecs))),
        key=lambda r: r["relevance_score"], reverse=True)
    top_n = body.get("top_n")
    if isinstance(top_n, int) and top_n > 0:
        scored = scored[:top_n]
    return web.json_response({
        "id": proto._gen_id("rerank"),
        "model": body.get("model") or engine.model_name,
        "results": scored,
        "usage": {"total_tokens": sum(len(t) for t in token_lists)},
    })


async def score(request: web.Request) -> web.Response:
    """/v1/score: similarity of text_1 against each text_2 entry."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
        bad = _check_pool_model(engine, body.get("model"))
        if bad is not None:
            return bad
        t1, t2 = body.get("text_1"), body.get("text_2")
        if isinstance(t2, str):
            texts = [t2]
        elif isinstance(t2, list) and t2 and all(isinstance(x, str)
                                                 for x in t2):
            texts = list(t2)
        else:
            texts = None
        if not isinstance(t1, str) or texts is None:
            return _error(400, "need 'text_1' (str) and 'text_2' "
                               "(str or non-empty list of str)")
        token_lists = _as_token_lists(
            engine, [t1] + texts,
            tok=engine.engine.embedding_tokenizer)
        vecs = await _pooled(request, token_lists)
    except (ValueError, TypeError, json.JSONDecodeError) as e:
        return _error(400, f"invalid request: {e}")
    base = vecs[0]
    return web.json_response({
        "id": proto._gen_id("score"),
        "model": body.get("model") or engine.model_name,
        "data": [{"index": i, "score": _cosine(base, v)}
                 for i, v in enumerate(vecs[1:])],
        "usage": {"total_tokens": sum(len(t) for t in token_lists)},
    })


async def list_models(request: web.Request) -> web.Response:
    engine = request.app[ENGINE_KEY]
    served = engine.engine.served_models
    base = served[0]
    cards = proto.ModelList(data=[
        proto.ModelCard(id=name, root=base if i else None,
                        parent=base if i else None)
        for i, name in enumerate(served)])
    return web.json_response(cards.model_dump())


async def health(request: web.Request) -> web.Response:
    return web.json_response({"status": "ok"})


async def load(request: web.Request) -> web.Response:
    """Cheap load report (queue depth, running seqs, free KV blocks,
    estimated queue delay, advertised capacity) — lock-free, so it
    answers even while the engine lock is held across a compile. The
    same numbers ride on every reply as x-engine-* headers and on
    /metrics as tpu: gauges."""
    engine = request.app[ENGINE_KEY]
    return web.json_response(engine.engine.load_report())


async def version(request: web.Request) -> web.Response:
    return web.json_response({"version": __version__})


async def debug_perf(request: web.Request) -> web.Response:
    """``GET /debug/perf``: the engine-efficiency ring — recent
    window-level real/pad/dead breakdowns, recent XLA compile events,
    cumulative totals + rates, and the KV block pool's fragmentation
    census. Aggregate-only data, but served under the same auth
    posture as /debug/traces (the /debug namespace is operator
    surface, not probe surface). Query param ``limit=N`` bounds the
    rings returned (default 50)."""
    engine = request.app[ENGINE_KEY]
    eng = engine.engine
    try:
        limit = max(1, int(request.query.get("limit", "50")))
    except ValueError:
        limit = 50
    return web.json_response({
        "totals": eng.eff.report(),
        "rates": eng.eff.rates(),
        "windows": eng.eff.recent_windows(limit),
        "compiles": eng.eff.recent_compiles(limit),
        "kv_pool": eng.block_mgr.frag_report(),
    })


async def metrics(request: web.Request) -> web.Response:
    engine = request.app[ENGINE_KEY]
    return web.Response(body=engine.engine.render_metrics(),
                        content_type="text/plain")


async def admin_kvplane_migrate_out(request: web.Request) -> web.Response:
    """kvplane planner entry point: evict victim sequences to the KV
    tier store and free their blocks. The victims' chunks are published
    before preemption, so a re-admission here (or a warm on the
    destination replica) injects instead of recomputing — a miss at
    worst, never corruption. Body: {"max_seqs": n, "target_blocks": n}."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
    except Exception:
        body = {}
    max_seqs = int(body.get("max_seqs", 2))
    target_blocks = int(body.get("target_blocks", 0))
    # migrate_out takes the engine lock and then flushes the KV writer
    # (blocking I/O) — keep it off the event loop
    result = await asyncio.to_thread(
        engine.engine.migrate_out, max_seqs=max_seqs,
        target_blocks=target_blocks)
    status = 409 if "error" in result else 200
    return web.json_response(result, status=status)


async def admin_kvplane_warm(request: web.Request) -> web.Response:
    """kvplane planner destination side: pull the named chunk keys
    through the tier stack so the fastest tier holds them before the
    migrated traffic lands. Body: {"keys": ["<hex>", ...]}."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
    except Exception:
        body = {}
    keys = body.get("keys") or []
    if not isinstance(keys, list):
        return _error(400, "keys must be a list of hex strings")
    result = await asyncio.to_thread(engine.engine.warm_chunks, keys)
    return web.json_response(result)


async def admin_lora_load(request: web.Request) -> web.Response:
    """Load a LoRA adapter at runtime and start serving it as its own
    model id. Body: {"name": "sql-adapter", "src": "random:7"|"/path.npz"}.

    Failure semantics are the r9 shed!=sick contract at the adapter
    stage: a failed load (bad source, OOM during restack) answers a
    structured 503 + Retry-After — "not now", NEVER a breaker signal —
    because the engine itself is healthy and serving its other models.
    The router's resilience layer already classifies exactly this shape
    as shed. Idempotent re-loads answer 200 with loaded=false."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
    except Exception:
        body = {}
    name = str(body.get("name") or "").strip()
    src = str(body.get("src") or "").strip()
    if not name or not src:
        return _error(400, "adapter load needs {'name': ..., 'src': "
                           "'random:SEED' or '/path/to/adapter.npz'}")
    try:
        # restack + device swap holds the engine lock — keep it off
        # the event loop like every other lock-taking admin verb
        loaded = await asyncio.to_thread(
            engine.engine.load_adapter, name, src)
    except Exception as e:
        logger.warning("adapter load %s from %s failed: %s", name, src, e)
        resp = _error(503, f"adapter {name!r} failed to load: {e}; "
                           f"the engine is healthy and still serving "
                           f"its current models — retry later",
                      err_type="overloaded_error")
        resp.headers["Retry-After"] = "5"
        return resp
    return web.json_response({
        "loaded": loaded, "name": name,
        "models": list(engine.engine.served_models)})


async def admin_lora_evict(request: web.Request) -> web.Response:
    """Stop serving adapter ``name`` (body: {"name": ...}). Unknown
    adapter answers 404; the stacked row is tombstoned so in-flight
    requests on the adapter finish normally."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
    except Exception:
        body = {}
    name = str(body.get("name") or "").strip()
    if not name:
        return _error(400, "adapter evict needs {'name': ...}")
    try:
        await asyncio.to_thread(engine.engine.evict_adapter, name)
    except KeyError as e:
        return _error(404, str(e.args[0]) if e.args else
                      f"adapter {name!r} is not loaded",
                      err_type="not_found_error")
    return web.json_response({
        "evicted": name, "models": list(engine.engine.served_models)})


async def tokenize(request: web.Request) -> web.Response:
    engine = request.app[ENGINE_KEY]
    body = await request.json()
    ids = engine.tokenizer.encode(body.get("prompt", ""))
    return web.json_response({"tokens": ids, "count": len(ids)})


async def detokenize(request: web.Request) -> web.Response:
    engine = request.app[ENGINE_KEY]
    body = await request.json()
    return web.json_response(
        {"prompt": engine.tokenizer.decode(body.get("tokens", []))})


# ---------------------------------------------------------------- app

# probe/scrape endpoints stay open when an API key is enforced: K8s
# probes and the Prometheus scraper carry no credentials (reference
# parity: the stack's engines enforce VLLM_API_KEY on the OpenAI surface
# while /health keeps answering probes,
# helm/templates/deployment-vllm-multi.yaml:143-150 + probe blocks)
AUTH_EXEMPT_PATHS = frozenset({"/health", "/metrics", "/version",
                               "/load"})
# NOTE: the /debug namespace (/debug/traces, /debug/perf) is
# deliberately NOT exempt — /debug/traces carries per-request data
# (trace ids, timings, token counts) and /debug/perf shares the
# operator-surface posture; readers on a secured deployment present
# the engine key


def _auth_middleware(api_key: str):
    import secrets as _secrets

    # compare bytes: compare_digest on str raises TypeError for
    # non-ASCII input, which would turn a malformed credential into a
    # 500 instead of a 401
    expected = f"Bearer {api_key}".encode("utf-8", "surrogateescape")

    @web.middleware
    async def check_auth(request: web.Request, handler):
        if request.path in AUTH_EXEMPT_PATHS:
            return await handler(request)
        provided = request.headers.get("Authorization", "").encode(
            "utf-8", "surrogateescape")
        if not _secrets.compare_digest(provided, expected):
            return _error(401, "invalid or missing API key "
                               "(Authorization: Bearer ...)")
        return await handler(request)

    return check_auth


def build_app(engine: AsyncLLMEngine,
              api_key: Optional[str] = None,
              trace_ring_entries: int = 2048,
              trace_sample_rate: float = 1.0) -> web.Application:
    """api_key None reads ENGINE_API_KEY from the environment (the
    chart's secret delivery, helm/templates/deployment-engine.yaml);
    empty/unset disables enforcement."""
    import os
    if api_key is None:
        api_key = os.environ.get("ENGINE_API_KEY", "")
    tracer = TraceRecorder("engine", ring_entries=trace_ring_entries,
                           sample_rate=trace_sample_rate)
    middlewares = [_auth_middleware(api_key)] if api_key else []
    if middlewares:
        logger.info("API-key enforcement on: all endpoints require "
                    "Bearer auth except %s",
                    ", ".join(sorted(AUTH_EXEMPT_PATHS)))
    @web.middleware
    async def stamp_load_headers(request: web.Request, handler):
        # every reply carries the engine's pressure signals (SSE
        # streams get theirs at prepare time in _sse_stream; a
        # response already prepared by its handler cannot take more
        # headers)
        resp = await handler(request)
        if not resp.prepared:
            for k, v in _load_headers(engine).items():
                resp.headers[k] = v
        return resp
    middlewares = [*middlewares, stamp_load_headers,
                   _trace_middleware(tracer)]

    app = web.Application(client_max_size=32 * 1024 * 1024,
                          middlewares=middlewares)
    app[ENGINE_KEY] = engine
    app[TRACER_KEY] = tracer
    app.router.add_get("/debug/traces",
                       debug_traces_handler(lambda: tracer))
    app.router.add_get("/debug/perf", debug_perf)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_post("/v1/rerank", rerank)
    app.router.add_post("/v2/rerank", rerank)
    app.router.add_post("/v1/score", score)
    app.router.add_get("/v1/models", list_models)
    app.router.add_get("/health", health)
    app.router.add_get("/load", load)
    app.router.add_get("/version", version)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/tokenize", tokenize)
    app.router.add_post("/detokenize", detokenize)
    app.router.add_post("/admin/kvplane/migrate_out",
                        admin_kvplane_migrate_out)
    app.router.add_post("/admin/kvplane/warm", admin_kvplane_warm)
    app.router.add_post("/admin/lora/load", admin_lora_load)
    app.router.add_post("/admin/lora/evict", admin_lora_evict)

    async def on_startup(app):
        # warmup (if any) was done before the loop started
        engine.start(asyncio.get_event_loop(), warmup=False)

    async def on_cleanup(app):
        engine.stop()
        # flush queued KV-tier saves + close tier sockets (pod rotation
        # must not drop the write-behind queue)
        engine.engine.close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser("pstpu-engine",
                                description="TPU-native OpenAI-compatible "
                                            "serving engine")
    p.add_argument("--model", default="debug-tiny")
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--checkpoint", default=None,
                   help="HF checkpoint dir (random weights if omitted)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--max-model-len", type=int, default=2048)
    p.add_argument("--dtype", choices=["bfloat16", "float32"],
                   default="bfloat16")
    p.add_argument("--max-num-seqs", type=int, default=8)
    p.add_argument("--max-waiting-seqs", type=int, default=None,
                   help="bounded admission: shed (503 + Retry-After) "
                        "once this many sequences queue un-admitted, "
                        "instead of queuing forever (default: "
                        "unbounded)")
    p.add_argument("--max-queue-delay-ms", type=float, default=None,
                   help="shed (503 + Retry-After) a request still "
                        "waiting for admission after this long "
                        "(default: never)")
    p.add_argument("--prefill-chunk", type=int, default=512)
    p.add_argument("--decode-window", type=int, default=8,
                   help="tokens generated per fused device dispatch: "
                        "higher = throughput (one host sync per window), "
                        "lower = smoother streaming cadence")
    p.add_argument("--kv-len-buckets", default=None,
                   help="comma-separated attention-length buckets "
                        "(default: powers of two up to max-model-len)")
    p.add_argument("--no-window-adapt", action="store_true",
                   help="disable continuous batching across fused "
                        "windows: every decode dispatch computes "
                        "max-num-seqs x decode-window token positions "
                        "whatever the batch holds (the pre-r17 "
                        "behavior; the effwatch A/B control)")
    p.add_argument("--decode-batch-buckets", default=None,
                   help="comma-separated decode batch buckets the "
                        "adaptive dispatch may shrink to (default: "
                        "powers of two up to max-num-seqs); each "
                        "bucket is a warmed executable per window "
                        "bucket, so keep the set small")
    p.add_argument("--decode-window-buckets", default=None,
                   help="comma-separated decode window-length buckets "
                        "(default: powers of two up to decode-window)")
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--pipeline-parallel-size", type=int, default=1,
                   help="multi-slice DCN passthrough knob (must be 1; "
                        "see EngineConfig)")
    p.add_argument("--expert-parallel-size", type=int, default=1,
                   help="shard a MoE model's experts over the mesh's ep "
                        "axis (must divide num_experts; composes with "
                        "--tensor-parallel-size)")
    p.add_argument("--speculative-ngram-tokens", type=int, default=0,
                   help="n-gram (prompt-lookup) speculative decoding "
                        "draft length; eligible rows (greedy, unguided, "
                        "unshaped) emit up to N+1 verified tokens per "
                        "decode step (0 = off)")
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="decode windows queued on the device at once; "
                        "3 hides more host/tunnel RTT behind device "
                        "work at the cost of admission latency")
    p.add_argument("--dp-gather-attention-ok", action="store_true",
                   help="acknowledge serving on a dp>1 mesh WITHOUT "
                        "the paged attention kernel (gathered-view "
                        "fallback, ~3x decode KV traffic); without "
                        "this flag such a mesh refuses to construct")
    p.add_argument("--quantization", choices=["int8"], default=None,
                   help="weight-only int8: halves decode weight-"
                        "streaming HBM traffic (norms/biases/router "
                        "stay in --dtype)")
    p.add_argument("--kv-cache-dtype", choices=["bfloat16", "float32",
                                                "int8"],
                   default="bfloat16",
                   help="KV cache precision; int8 stores per-(token, "
                        "head)-scaled int8 blocks — halves long-context "
                        "decode KV HBM traffic (models/kv.py)")
    p.add_argument("--moe-capacity-factor", type=float, default=None,
                   help="MoE prefill capacity factor (ops/moe.py): >= "
                        "num_experts/top_k disables token dropping at "
                        "dense-compute cost; default keeps the model "
                        "family value")
    p.add_argument("--embedding-model", default=None,
                   help="real embedding model for /v1/embeddings + "
                        "rerank/score (models/encoder.py): an encoder "
                        "preset name or a HF BertModel checkpoint dir. "
                        "Default: mean-pooled causal hidden states, "
                        "flagged embedding_source=causal-mean-pool")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--chat-template", default=None,
                   help="Jinja file overriding the tokenizer chat template")
    p.add_argument("--enable-prefix-caching", action="store_true",
                   help="retain finished sequences' full KV blocks in "
                        "the paged pool and attach them to matching "
                        "prompts by reference — zero-copy prefix hits "
                        "(the reference's --enable-prefix-caching)")
    p.add_argument("--kv-block-size", type=int, default=64,
                   help="paged-KV block size in tokens (models/kv.py)")
    p.add_argument("--kv-pool-tokens", type=int, default=None,
                   help="total KV pool capacity in tokens (default: "
                        "max-num-seqs * max-model-len worst case). A "
                        "smaller pool admits by LIVE context and "
                        "preempts under pressure — more concurrent "
                        "long-context slots in the same HBM")
    p.add_argument("--lora-adapters", default=None,
                   help="comma-separated name=source pairs; source is an "
                        ".npz adapter checkpoint (models/lora.py) or "
                        "random:SEED. Each adapter is served as its own "
                        "model id (reference: --enable-lora, "
                        "deployment-vllm-multi.yaml:65-67)")
    p.add_argument("--lora-rank", type=int, default=8)
    p.add_argument("--lora-alpha", type=float, default=16.0)
    p.add_argument("--lora-targets", default="q,v",
                   help="comma-separated projections to adapt "
                        "(q,k,v,o,gate,up,down)")
    p.add_argument("--hbm-peak-gbps", type=float, default=819.0,
                   help="HBM peak bandwidth the tpu:engine_mbu_perc "
                        "gauge normalizes effective bytes/s against "
                        "(GB/s; set to the serving chip's datasheet "
                        "number)")
    p.add_argument("--perf-ring-entries", type=int, default=256,
                   help="window-level efficiency breakdowns kept in "
                        "memory (bounded ring on GET /debug/perf)")
    p.add_argument("--trace-ring-entries", type=int, default=2048,
                   help="completed request traces kept in memory "
                        "(bounded ring served on GET /debug/traces)")
    p.add_argument("--trace-sample-rate", type=float, default=1.0,
                   help="fraction of DIRECT requests traced into the "
                        "ring; an inbound traceparent's sampled flag "
                        "(the router's decision) always wins")
    p.add_argument("--kv-transfer-config", default=None,
                   help="JSON dict enabling KV tiering, e.g. "
                        '\'{"kv_role": "kv_both", "local_cpu_gb": 4, '
                        '"remote_url": "tpukv://cache:8100"}\' '
                        "(the reference engine's --kv-transfer-config "
                        "equivalent; see kvcache/connector.py)")
    p.add_argument("--no-kvplane-defrag", action="store_true",
                   help="disable the between-windows free-list defrag "
                        "pass the engine runs after fragmented "
                        "allocation failures (docs/kv-tiering.md)")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = parse_args(argv)
    honor_platform_env()
    set_ulimit()
    kv_transfer = json.loads(args.kv_transfer_config) \
        if args.kv_transfer_config else None
    cfg = EngineConfig(
        model=args.model, tokenizer=args.tokenizer,
        chat_template=args.chat_template,
        checkpoint=args.checkpoint, max_model_len=args.max_model_len,
        dtype=args.dtype, kv_dtype=args.kv_cache_dtype,
        max_num_seqs=args.max_num_seqs, prefill_chunk=args.prefill_chunk,
        max_waiting_seqs=args.max_waiting_seqs,
        max_queue_delay_ms=args.max_queue_delay_ms,
        hbm_peak_gbps=args.hbm_peak_gbps,
        perf_ring_entries=args.perf_ring_entries,
        decode_window=args.decode_window,
        window_adapt=not args.no_window_adapt,
        decode_batch_buckets=tuple(
            int(x) for x in args.decode_batch_buckets.split(","))
        if args.decode_batch_buckets else (),
        decode_window_buckets=tuple(
            int(x) for x in args.decode_window_buckets.split(","))
        if args.decode_window_buckets else (),
        kv_len_buckets=tuple(int(x) for x in args.kv_len_buckets.split(","))
        if args.kv_len_buckets else (),
        enable_prefix_caching=args.enable_prefix_caching,
        kvplane_defrag=not args.no_kvplane_defrag,
        kv_block_size=args.kv_block_size,
        kv_pool_tokens=args.kv_pool_tokens,
        tensor_parallel_size=args.tensor_parallel_size,
        pipeline_parallel_size=args.pipeline_parallel_size,
        expert_parallel_size=args.expert_parallel_size,
        moe_capacity_factor=args.moe_capacity_factor,
        quantization=args.quantization,
        speculative_ngram_tokens=args.speculative_ngram_tokens,
        pipeline_depth=args.pipeline_depth,
        dp_gather_attention_ok=args.dp_gather_attention_ok,
        seed=args.seed,
        embedding_model=args.embedding_model,
        kv_transfer_config=kv_transfer,
        lora_adapters=dict(pair.split("=", 1)
                           for pair in args.lora_adapters.split(","))
        if args.lora_adapters else None,
        lora_rank=args.lora_rank, lora_alpha=args.lora_alpha,
        lora_targets=tuple(args.lora_targets.split(",")))
    engine = AsyncLLMEngine(cfg)
    if not args.no_warmup:
        engine.engine.runner.warmup()

    async def _serve():
        app = build_app(engine,
                        trace_ring_entries=args.trace_ring_entries,
                        trace_sample_rate=args.trace_sample_rate)
        # cancel handlers when the peer disconnects (aiohttp >= 3.9
        # defaults this OFF): a request whose client has gone must
        # abort its engine-side generation even if it is still QUEUED —
        # without this, disconnects are only noticed at SSE write time,
        # and a backlog of orphaned requests keeps the engine busy for
        # clients that left minutes ago. Cancellation closes the stream
        # generator, whose finally aborts the sequence
        # (async_engine.stream).
        runner = web.AppRunner(app, handler_cancellation=True)
        await runner.setup()
        site = web.TCPSite(runner, args.host, args.port)
        await site.start()
        logger.info("engine serving %s on %s:%d", cfg.model, args.host,
                    args.port)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(_serve())


if __name__ == "__main__":
    main()
