"""Guided decoding: regex/choice-constrained generation via token DFAs.

The reference serves engines whose surface includes vLLM's guided
decoding (``guided_regex`` / ``guided_choice`` request extensions);
this is the TPU-native design:

- A small BYTE-level regex engine compiles the pattern to a DFA
  (Thompson NFA → subset construction → dead-state pruning). Supported
  syntax: literals, ``.``, ``[...]`` classes with ranges/negation,
  ``|``, ``(...)``, ``*`` ``+`` ``?`` ``{m}`` ``{m,n}``, and the
  escapes ``\\d \\w \\s \\D \\W \\S`` plus escaped metacharacters.
  Non-ASCII literals constrain their exact UTF-8 byte sequence.
- The DFA is then lifted from bytes to TOKENS: for every vocab id the
  token's bytes (tokenizer.id_to_token) are walked from every DFA
  state, producing ``token_next [n_states, vocab]`` (−1 = forbidden).
  EOS is allowed exactly in accepting states (self-loop), so a guided
  sequence can only terminate on a complete match.
- The table is DEVICE-side: the fused multi-step decode window
  (engine/runner.py) carries each row's DFA state in the scan, masks
  logits with one [B, V] gather per step, and advances the state from
  the sampled id — constrained sampling costs one gather, not a host
  round-trip per token. The engine mirrors states on host (numpy walk)
  so slot composition changes can re-upload, exactly like the decode
  token/position carries.

Compiled grammars are LRU-cached per (pattern, tokenizer vocab id).
"""

import functools
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

MAX_DFA_STATES = 512
DEAD = -1

_DIGIT = frozenset(range(ord("0"), ord("9") + 1))
_WORD = frozenset(
    set(range(ord("a"), ord("z") + 1)) | set(range(ord("A"), ord("Z") + 1))
    | _DIGIT | {ord("_")})
_SPACE = frozenset({9, 10, 11, 12, 13, 32})
_ANY = frozenset(range(256))   # '.' matches any byte (incl. newline)


class RegexError(ValueError):
    pass


# --------------------------------------------------------------- parsing
# Grammar: alt := concat ('|' concat)* ; concat := repeat* ;
# repeat := atom ('*'|'+'|'?'|'{m[,n]}')* ; atom := literal | class |
# '(' alt ')' | '.'


class _Parser:
    def __init__(self, pattern: str):
        self.bytes_ = pattern.encode("utf-8")
        self.i = 0

    def peek(self) -> Optional[int]:
        return self.bytes_[self.i] if self.i < len(self.bytes_) else None

    def next(self) -> int:
        b = self.bytes_[self.i]
        self.i += 1
        return b

    def parse(self):
        node = self._alt()
        if self.i != len(self.bytes_):
            raise RegexError(f"unexpected {chr(self.bytes_[self.i])!r} "
                             f"at byte {self.i}")
        return node

    def _alt(self):
        branches = [self._concat()]
        while self.peek() == ord("|"):
            self.next()
            branches.append(self._concat())
        return ("alt", branches) if len(branches) > 1 else branches[0]

    def _concat(self):
        parts = []
        while True:
            c = self.peek()
            if c is None or c in (ord("|"), ord(")")):
                break
            parts.append(self._repeat())
        if not parts:
            return ("eps",)
        return ("cat", parts) if len(parts) > 1 else parts[0]

    def _repeat(self):
        node = self._atom()
        while True:
            c = self.peek()
            if c == ord("*"):
                self.next()
                node = ("star", node)
            elif c == ord("+"):
                self.next()
                node = ("cat", [node, ("star", node)])
            elif c == ord("?"):
                self.next()
                node = ("alt", [node, ("eps",)])
            elif c == ord("{"):
                node = self._bounded(node)
            else:
                return node

    def _bounded(self, node):
        self.next()   # '{'
        lo = self._int()
        hi = lo
        if self.peek() == ord(","):
            self.next()
            hi = self._int() if self.peek() != ord("}") else None
        if self.peek() != ord("}"):
            raise RegexError("unterminated {m,n}")
        self.next()
        if lo > 256 or (hi is not None and (hi < lo or hi > 256)):
            raise RegexError(f"bad repetition bounds {{{lo},{hi}}}: "
                             f"counts are capped at 256")
        parts = [node] * lo
        if hi is None:
            parts.append(("star", node))
        else:
            parts.extend(("alt", [node, ("eps",)]) for _ in range(hi - lo))
        return ("cat", parts) if parts else ("eps",)

    def _int(self) -> int:
        digits = b""
        while self.peek() is not None and self.peek() in _DIGIT:
            digits += bytes([self.next()])
        if not digits:
            raise RegexError("expected integer in {m,n}")
        return int(digits)

    def _atom(self):
        c = self.next() if self.peek() is not None else None
        if c is None:
            raise RegexError("unexpected end of pattern")
        if c == ord("("):
            if self.bytes_[self.i:self.i + 2] == b"?:":
                self.i += 2   # non-capturing group marker: same thing here
            node = self._alt()
            if self.peek() != ord(")"):
                raise RegexError("unbalanced parenthesis")
            self.next()
            return node
        if c == ord("["):
            return ("set", self._class())
        if c == ord("."):
            return ("set", _ANY)
        if c == ord("\\"):
            return ("set", self._escape())
        if c in b"*+?{":
            raise RegexError(f"dangling quantifier {chr(c)!r}")
        if c in b"^$":
            raise RegexError(
                "anchors are implicit: matching is whole-string (a "
                "leading ^ / trailing $ is stripped; mid-pattern "
                "anchors are unsupported)")
        return ("set", frozenset({c}))

    def _escape(self) -> FrozenSet[int]:
        if self.peek() is None:
            raise RegexError("trailing backslash")
        c = self.next()
        table = {ord("d"): _DIGIT, ord("D"): _ANY - _DIGIT,
                 ord("w"): _WORD, ord("W"): _ANY - _WORD,
                 ord("s"): _SPACE, ord("S"): _ANY - _SPACE,
                 ord("n"): frozenset({10}), ord("t"): frozenset({9}),
                 ord("r"): frozenset({13})}
        if c in table:
            return table[c]
        if c in b"bBAZz":
            raise RegexError(
                f"unsupported zero-width escape \\{chr(c)}")
        return frozenset({c})   # escaped literal / metacharacter

    def _class(self) -> FrozenSet[int]:
        negate = False
        if self.peek() == ord("^"):
            self.next()
            negate = True
        members: Set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexError("unterminated character class")
            if c == ord("]") and not first:
                self.next()
                break
            first = False
            c = self.next()
            if c == ord("\\"):
                members |= self._escape()
                continue
            if (self.peek() == ord("-")
                    and self.i + 1 < len(self.bytes_)
                    and self.bytes_[self.i + 1] != ord("]")):
                self.next()   # '-'
                hi = self.next()
                if hi == ord("\\"):
                    raise RegexError("range endpoint cannot be an escape")
                if hi < c:
                    raise RegexError("reversed character range")
                members |= set(range(c, hi + 1))
            else:
                members.add(c)
        return frozenset(_ANY - members if negate else members)


# ------------------------------------------------- NFA -> DFA compilation

def _build_nfa(node, nfa, start: int) -> int:
    """Thompson construction. nfa: {"eps": [set], "edges": [list of
    (byteset, dst)]}; returns the accepting position for `node` hung
    off `start`."""
    kind = node[0]
    if kind == "eps":
        return start
    if kind == "set":
        dst = _new_state(nfa)
        nfa["edges"][start].append((node[1], dst))
        return dst
    if kind == "cat":
        cur = start
        for part in node[1]:
            cur = _build_nfa(part, nfa, cur)
        return cur
    if kind == "alt":
        out = _new_state(nfa)
        for branch in node[1]:
            b_start = _new_state(nfa)
            nfa["eps"][start].add(b_start)
            b_end = _build_nfa(branch, nfa, b_start)
            nfa["eps"][b_end].add(out)
        return out
    if kind == "star":
        hub = _new_state(nfa)
        nfa["eps"][start].add(hub)
        body_start = _new_state(nfa)
        nfa["eps"][hub].add(body_start)
        body_end = _build_nfa(node[1], nfa, body_start)
        nfa["eps"][body_end].add(hub)
        return hub
    raise AssertionError(kind)


def _new_state(nfa) -> int:
    nfa["eps"].append(set())
    nfa["edges"].append([])
    return len(nfa["eps"]) - 1


def _eps_closure(nfa, states: FrozenSet[int]) -> FrozenSet[int]:
    stack, seen = list(states), set(states)
    while stack:
        s = stack.pop()
        for t in nfa["eps"][s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


class ByteDFA:
    """trans [n_states, 256] int32 (DEAD = -1), accept [n_states] bool,
    state 0 = start."""

    def __init__(self, trans: np.ndarray, accept: np.ndarray):
        self.trans = trans
        self.accept = accept

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]

    def matches(self, data: bytes) -> bool:
        s = 0
        for b in data:
            s = int(self.trans[s, b])
            if s == DEAD:
                return False
        return bool(self.accept[s])


def compile_regex(pattern: str) -> ByteDFA:
    """Byte-level regex -> DFA (full-string match semantics). Leading
    ^ / trailing $ are stripped (they are implicit here); anchors
    anywhere else are rejected rather than silently matched as
    literals."""
    if pattern.startswith("^"):
        pattern = pattern[1:]
    if pattern.endswith("$"):
        # the $ is an anchor only if preceded by an EVEN number of
        # backslashes (an odd count escapes it into a literal)
        slashes = len(pattern) - 1 - len(pattern[:-1].rstrip("\\"))
        if slashes % 2 == 0:
            pattern = pattern[:-1]
    nfa = {"eps": [], "edges": []}
    start = _new_state(nfa)
    accept_pos = _build_nfa(_Parser(pattern).parse(), nfa, start)

    d0 = _eps_closure(nfa, frozenset({start}))
    index: Dict[FrozenSet[int], int] = {d0: 0}
    order: List[FrozenSet[int]] = [d0]
    rows: List[np.ndarray] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = np.full((256,), DEAD, np.int32)
        # group outgoing byte edges
        move: Dict[int, Set[int]] = {}
        for s in cur:
            for byteset, dst in nfa["edges"][s]:
                for b in byteset:
                    move.setdefault(b, set()).add(dst)
        for b, dsts in move.items():
            nxt = _eps_closure(nfa, frozenset(dsts))
            if nxt not in index:
                if len(order) >= MAX_DFA_STATES:
                    raise RegexError(
                        f"pattern needs > {MAX_DFA_STATES} DFA states")
                index[nxt] = len(order)
                order.append(nxt)
            row[b] = index[nxt]
        rows.append(row)
    trans = np.stack(rows)
    accept = np.array([accept_pos in st for st in order], bool)
    if not accept.any():
        raise RegexError("pattern accepts nothing")
    return ByteDFA(trans, accept)


def choice_regex(choices: List[str]) -> str:
    """guided_choice sugar: alternation of escaped literals."""
    if not choices:
        raise RegexError("guided_choice requires at least one choice")
    return "(" + "|".join(_regex_literal(c) for c in choices) + ")"


# ------------------------------------------------ JSON-schema -> regex

# JSON primitive regexes (exact canonical formatting: no insignificant
# whitespace inside values). String content follows RFC 8259: raw
# control bytes (0x00-0x1F) are excluded — the class lists them as
# literal members — and backslash escapes are restricted to the legal
# set, so every accepted string is json.loads-parseable.
_JSON_STRING = ('"([^"\\\\' + "".join(chr(c) for c in range(0x20))
                + ']|\\\\(["\\\\/bfnrt]|u[0-9a-fA-F]{4}))*"')
_JSON_INT = r"-?(0|[1-9]\d*)"
_JSON_NUMBER = _JSON_INT + r"(\.\d+)?([eE][+-]?\d+)?"
_JSON_BOOL = r"(true|false)"
_JSON_NULL = r"null"


def _regex_literal(s: str) -> str:
    out = []
    for ch in s:
        if ch in "\\.[](){}|*+?^$-":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def _json_value_regex(schema, depth: int) -> str:
    if depth > 8:
        raise RegexError("guided_json: schema nesting too deep (>8)")
    if not isinstance(schema, dict):
        raise RegexError("guided_json: each schema node must be an object")
    if "enum" in schema:
        import json as _json
        # enum values render as their canonical JSON literal
        return ("(" + "|".join(
            _regex_literal(_json.dumps(v)) for v in schema["enum"]) + ")")
    t = schema.get("type")
    if t == "string":
        pat = schema.get("pattern")
        if pat is not None:
            # user pattern constrains the string CONTENT (full-match
            # semantics), grouped so alternations cannot escape the
            # quotes. JSON validity of the content (no raw controls /
            # stray backslashes) is the pattern author's contract.
            return '"(' + pat + ')"'
        return _JSON_STRING
    if t == "integer":
        return _JSON_INT
    if t == "number":
        return _JSON_NUMBER
    if t == "boolean":
        return _JSON_BOOL
    if t == "null":
        return _JSON_NULL
    if t == "array":
        item = _json_value_regex(schema.get("items", {"type": "string"}),
                                 depth + 1)
        lo = schema.get("minItems")
        hi = schema.get("maxItems")
        if lo is None and hi is None:
            body = f"({item}(, {item})*)?"
        else:
            lo = int(lo or 0)
            if hi is None:
                # unbounded {m,} is not in the regex subset: emulate
                # with m-1 required copies then *
                tail = (f"(, {item})" * max(lo - 1, 0)
                        + f"(, {item})*")
            else:
                hi = int(hi)
                if hi < lo or hi < 0:
                    raise RegexError("guided_json: bad min/maxItems")
                if hi == 0:
                    return r"\[\]"
                tail = (f"(, {item})" * max(lo - 1, 0)
                        + f"(, {item})?" * (hi - max(lo, 1)))
            body = f"{item}{tail}"
            if lo == 0:
                body = f"({body})?"
        return r"\[" + body + r"\]"
    if t == "object" or "properties" in schema:
        props = schema.get("properties", {})
        if not props:
            raise RegexError(
                "guided_json: object schemas need non-empty 'properties' "
                "(a regex DFA cannot express arbitrary-depth free-form "
                "JSON)")
        import json as _json
        parts = []
        for name, sub in props.items():   # declaration order
            # json.dumps both quotes AND escapes the name (controls,
            # quotes, backslashes), then the result is regex-escaped —
            # same recipe as enum values
            parts.append(_regex_literal(_json.dumps(name)) + ": "
                         + _json_value_regex(sub, depth + 1))
        return r"\{" + ", ".join(parts) + r"\}"
    raise RegexError(f"guided_json: unsupported schema node {schema!r}")


def json_schema_regex(schema) -> str:
    """vLLM's ``guided_json``: compile a JSON-schema subset to a regex
    for the byte-DFA engine. Output is CANONICAL JSON — every declared
    property, in declaration order, separated by ", " with ": " after
    keys and no other insignificant whitespace (DFA-friendly and
    deterministic; the 'required' list is ignored because every
    property is always emitted). Supported nodes: object/properties,
    array (items, minItems/maxItems), string (optional content
    'pattern'), integer, number, boolean, null, enum. Free-form
    objects (no 'properties') are rejected — a finite automaton cannot
    express unbounded-depth JSON."""
    import json as _json
    if isinstance(schema, str):
        schema = _json.loads(schema)
    return _json_value_regex(schema, 0)


# --------------------------------------------------- token-level lifting

class CompiledGrammar:
    """token_next [n_states, vocab] int32: next DFA state after emitting
    a vocab id (DEAD = forbidden). EOS self-loops in accepting states
    and is forbidden elsewhere, so generation can only stop on a
    complete match."""

    def __init__(self, pattern: str, token_next: np.ndarray):
        self.pattern = pattern
        self.token_next = token_next
        self.n_states = token_next.shape[0]

    def next_state(self, state: int, token: int) -> int:
        return int(self.token_next[state, token])


def _token_bytes(tokenizer, vocab: int) -> List[Optional[bytes]]:
    out: List[Optional[bytes]] = []
    for tid in range(vocab):
        try:
            _, raw = tokenizer.id_to_token(tid)
            out.append(bytes(raw))
        except Exception:
            out.append(None)
    return out


@functools.lru_cache(maxsize=64)
def _compile_cached(pattern: str, tok_key: int):
    tokenizer = _TOKENIZERS[tok_key]
    dfa = compile_regex(pattern)
    vocab = tokenizer.vocab_size
    token_next = np.full((dfa.n_states, vocab), DEAD, np.int32)
    # walk every token's bytes from every state, fully vectorized over
    # states: cur [n_states] advances one byte at a time (dead rows
    # stay dead via a guarded gather)
    specials = (set(getattr(tokenizer, "special_token_ids", None) or ())
                | {tokenizer.bos_token_id, tokenizer.pad_token_id})
    eos = tokenizer.eos_token_id
    tok_bytes = _token_bytes(tokenizer, vocab)
    base = np.arange(dfa.n_states, dtype=np.int32)
    for tid in range(vocab):
        if tid == eos:
            token_next[dfa.accept, tid] = base[dfa.accept]
            continue
        raw = tok_bytes[tid]
        if raw is None or len(raw) == 0 or tid in specials:
            continue   # forbidden under guidance
        cur = base.copy()
        for b in raw:
            alive = cur != DEAD
            cur[alive] = dfa.trans[cur[alive], b]
        token_next[:, tid] = cur
    # sanity: every live non-accepting state must have a way forward
    # (otherwise sampling would mask everything); dead-ends become
    # unreachable by forbidding the tokens that lead to them, iterated
    # until no NEW dead-end appears (dead states never come back, so
    # the loop runs at most #dead+1 passes, each one vectorized)
    known_dead: set = set()
    while True:
        has_out = (token_next != DEAD).any(axis=1)
        new_dead = [int(s) for s in np.nonzero(~has_out)[0]
                    if int(s) not in known_dead]
        if not new_dead:
            break
        known_dead.update(new_dead)
        token_next[np.isin(token_next, new_dead)] = DEAD
    if not (token_next[0] != DEAD).any():
        raise RegexError(
            f"pattern {pattern!r} is unsatisfiable with this tokenizer's "
            f"vocabulary")
    return CompiledGrammar(pattern, token_next)


# tokenizer registry keyed by id() so the lru_cache key stays hashable
_TOKENIZERS: Dict[int, object] = {}


def compile_grammar(pattern: str, tokenizer) -> CompiledGrammar:
    key = id(tokenizer)
    _TOKENIZERS[key] = tokenizer
    return _compile_cached(pattern, key)
