"""ModelRunner: owns params + KV cache and the two cached XLA executables.

TPU execution model:
- ``decode``: ONE executable for the whole engine lifetime — batch is
  always [max_num_seqs] (free slots run as padding rows), so every step
  after warmup is a cache hit. Sampling is fused in; only int32 token ids
  come back to host.
- ``prefill``: one executable per length bucket (engine_cfg.prefill_buckets),
  prompt chunks are right-padded to the bucket. Works on a single slot via
  dynamic batch-axis slice so running sequences keep their state.
- Both donate the KV cache => XLA updates it in place in HBM.

The reference has no equivalent (engine external, SURVEY.md §1 L2); this
is the TPU-native core the stack serves from.
"""

import time
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.sampler import SamplingParams, sample
from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.models.kv import KVCache, make_cache
from production_stack_tpu.models import llama
from production_stack_tpu.ops.rope import rope_table
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class ModelRunner:
    def __init__(self, model_cfg: ModelConfig, engine_cfg: EngineConfig,
                 params=None, mesh=None):
        self.model_cfg = model_cfg
        self.engine_cfg = engine_cfg
        self.mesh = mesh
        # rope table must cover the cache length, not just the model's
        # native max (see ops/rope.py clamping note)
        self.rope = rope_table(engine_cfg.max_model_len, model_cfg.head_dim_,
                               model_cfg.rope_theta)
        if params is None:
            t0 = time.time()
            params = llama.init_params(model_cfg, jax.random.PRNGKey(
                engine_cfg.seed))
            logger.info("random-initialized %s (%.2fs)", model_cfg.name,
                        time.time() - t0)
        self.params = params
        self.cache: KVCache = make_cache(
            model_cfg.num_layers, engine_cfg.max_num_seqs,
            engine_cfg.max_model_len, model_cfg.num_kv_heads,
            model_cfg.head_dim_,
            dtype=jnp.bfloat16 if engine_cfg.kv_dtype == "bfloat16"
            else jnp.float32)
        if mesh is not None:
            # tensor-parallel serving: weights/cache sharded over the
            # slice's chips; XLA derives all ICI collectives from here
            from jax.sharding import NamedSharding
            from production_stack_tpu.parallel.sharding import (
                cache_pspec, param_shardings)
            tp = mesh.shape.get("tp", 1)
            if model_cfg.num_kv_heads % tp:
                raise ValueError(
                    f"tensor_parallel_size {tp} must divide num_kv_heads "
                    f"{model_cfg.num_kv_heads} (KV-head replication is not "
                    f"implemented yet)")
            self.params = jax.device_put(
                self.params, param_shardings(mesh, self.params))
            cache_sh = NamedSharding(mesh, cache_pspec())
            self.cache = KVCache(jax.device_put(self.cache.k, cache_sh),
                                 jax.device_put(self.cache.v, cache_sh))
        self._key = jax.random.PRNGKey(engine_cfg.seed ^ 0x5EED)

        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=(1,))
        self._prefill_fn = jax.jit(self._prefill_impl, donate_argnums=(1,))
        # KV-tiering primitives (kvcache/connector.py), cached per chunk size
        self._extract_fns = {}
        self._inject_fns = {}

    # ------------------------------------------------------------------
    # jitted impls (pure)
    # ------------------------------------------------------------------

    def _decode_impl(self, params, cache: KVCache, tokens: jnp.ndarray,
                     positions: jnp.ndarray, sampling: SamplingParams,
                     key: jax.Array):
        """tokens/positions [B] -> sampled ids [B], cache'."""
        logits, cache = llama.forward(
            params, self.model_cfg, tokens[:, None], positions[:, None],
            cache, rope=self.rope)
        ids = sample(logits[:, 0, :], sampling, key)
        return ids, cache

    def _prefill_impl(self, params, cache: KVCache, tokens: jnp.ndarray,
                      start: jnp.ndarray, length: jnp.ndarray,
                      slot: jnp.ndarray, sampling: SamplingParams,
                      key: jax.Array):
        """tokens [Tb] (padded chunk) into `slot` at offset `start`.

        Returns (sampled id for the chunk's last real token, cache').
        """
        L = self.model_cfg.num_layers
        S = self.engine_cfg.max_model_len
        Hkv, D = self.model_cfg.num_kv_heads, self.model_cfg.head_dim_
        Tb = tokens.shape[0]

        k_slot = jax.lax.dynamic_slice(cache.k, (0, slot, 0, 0, 0),
                                       (L, 1, S, Hkv, D))
        v_slot = jax.lax.dynamic_slice(cache.v, (0, slot, 0, 0, 0),
                                       (L, 1, S, Hkv, D))
        positions = (start + jnp.arange(Tb))[None, :]
        logits, slot_cache = llama.forward(
            params, self.model_cfg, tokens[None, :], positions,
            KVCache(k_slot, v_slot), rope=self.rope)
        new_k = jax.lax.dynamic_update_slice(cache.k, slot_cache.k,
                                             (0, slot, 0, 0, 0))
        new_v = jax.lax.dynamic_update_slice(cache.v, slot_cache.v,
                                             (0, slot, 0, 0, 0))
        last = jax.lax.dynamic_slice(logits, (0, length - 1, 0),
                                     (1, 1, logits.shape[-1]))[:, 0, :]
        ids = sample(last, sampling, key)
        return ids[0], KVCache(new_k, new_v)

    # ------------------------------------------------------------------
    # host API
    # ------------------------------------------------------------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def decode(self, tokens, positions, sampling: SamplingParams):
        """Batched decode step over all slots. Returns np-convertible ids [B]."""
        ids, self.cache = self._decode_fn(
            self.params, self.cache, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32), sampling, self._next_key())
        return ids

    def prefill(self, chunk_tokens, start: int, slot: int,
                sampling_row: SamplingParams):
        """Prefill one padded chunk into a slot. Returns sampled id (device)."""
        bucket = self.engine_cfg.bucket_for(len(chunk_tokens))
        length = len(chunk_tokens)
        padded = list(chunk_tokens) + [0] * (bucket - length)
        token_id, self.cache = self._prefill_fn(
            self.params, self.cache, jnp.asarray(padded, jnp.int32),
            jnp.int32(start), jnp.int32(length), jnp.int32(slot),
            sampling_row, self._next_key())
        return token_id

    def extract_chunk(self, slot: int, start: int, size: int):
        """Slice [L, size, Hkv, D] k/v out of a slot (no donation; the
        result is an independent buffer, safe to D2H after later steps
        donate the cache). Dispatch is async — np.asarray() later blocks."""
        fn = self._extract_fns.get(size)
        if fn is None:
            L = self.model_cfg.num_layers
            Hkv, D = self.model_cfg.num_kv_heads, self.model_cfg.head_dim_

            def _impl(cache: KVCache, slot, start):
                k = jax.lax.dynamic_slice(cache.k, (0, slot, start, 0, 0),
                                          (L, 1, size, Hkv, D))[:, 0]
                v = jax.lax.dynamic_slice(cache.v, (0, slot, start, 0, 0),
                                          (L, 1, size, Hkv, D))[:, 0]
                return k, v

            fn = self._extract_fns[size] = jax.jit(_impl)
        return fn(self.cache, jnp.int32(slot), jnp.int32(start))

    def inject_chunk(self, slot: int, start: int, k_chunk, v_chunk) -> None:
        """Write host [L, size, Hkv, D] k/v into a slot (donates cache —
        in-place HBM update)."""
        size = k_chunk.shape[1]
        fn = self._inject_fns.get(size)
        if fn is None:
            def _impl(cache: KVCache, k_chunk, v_chunk, slot, start):
                idx = (0, slot, start, 0, 0)
                new_k = jax.lax.dynamic_update_slice(
                    cache.k, k_chunk[:, None], idx)
                new_v = jax.lax.dynamic_update_slice(
                    cache.v, v_chunk[:, None], idx)
                return KVCache(new_k, new_v)

            fn = self._inject_fns[size] = jax.jit(_impl,
                                                  donate_argnums=(0,))
        self.cache = fn(self.cache, jnp.asarray(k_chunk),
                        jnp.asarray(v_chunk), jnp.int32(slot),
                        jnp.int32(start))

    def warmup(self) -> float:
        """Compile decode + all prefill buckets. Returns seconds spent."""
        t0 = time.time()
        B = self.engine_cfg.max_num_seqs
        sampling = SamplingParams.filled(B)
        row = SamplingParams.filled(1)
        self.decode([0] * B, [0] * B, sampling)
        for bucket in self.engine_cfg.prefill_buckets:
            self.prefill([0] * bucket, 0, 0, row)
        jax.block_until_ready(self.cache.k)
        dt = time.time() - t0
        logger.info("warmup compiled decode + %d prefill buckets in %.1fs",
                    len(self.engine_cfg.prefill_buckets), dt)
        return dt
