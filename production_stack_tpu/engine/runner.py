"""ModelRunner: owns params + KV cache and the cached XLA executables.

TPU execution model:
- ``decode``: a *multi-step window* — ``lax.scan`` fuses
  ``engine_cfg.decode_window`` forward+sample steps into ONE executable
  dispatch with ONE device→host sync for the whole window (int32 ids
  [B, W]), amortizing Python dispatch overhead ~W×. Batch is always
  [max_num_seqs] (free slots run as padding rows). Executables are cached
  per (window, kv-length bucket, greedy): attention cost scales with the
  live context (kv bucket), not max_model_len, and all-greedy batches
  skip the [B, V] sampling sort entirely.
- ``prefill``: FULL-BATCH — every admissible sequence's next chunk is
  prefilled in ONE dispatch (tokens [B, Tb]; idle rows are parked at
  position S where their writes clamp harmlessly onto S-1). One
  executable per (chunk-length bucket, kv bucket).
- Decode inputs are *device-carried*: each window's last sampled ids and
  advanced positions stay on device and feed the next window directly —
  the host uploads fresh state only when slot composition changes
  (admission / finish). A steady decode window costs exactly one
  dispatch + one device→host sync, which matters doubly when the chip
  is reached over a high-RTT tunnel.
- Both donate the KV cache => XLA updates it in place in HBM.

The reference has no equivalent (engine external, SURVEY.md §1 L2); this
is the TPU-native core the stack serves from.
"""

import time
from functools import partial

import numpy as np
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.sampler import (SamplingParams,
                                                 adjust_logits, sample)
from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.models.kv import KVCache, make_cache
from production_stack_tpu.models import llama
from production_stack_tpu.ops.rope import rope_table
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class ModelRunner:
    def __init__(self, model_cfg: ModelConfig, engine_cfg: EngineConfig,
                 params=None, mesh=None, lora_stacked=None,
                 lora_scaling: float = 1.0):
        self.model_cfg = model_cfg
        self.engine_cfg = engine_cfg
        self.mesh = mesh
        # stacked multi-LoRA adapters, layer axis leading for lax.scan
        # (models/lora.py); row selection comes in via sampling.adapter
        from production_stack_tpu.models import lora as lora_mod
        self._lora = lora_mod.layer_slice(lora_stacked)
        self._lora_scaling = lora_scaling
        # rope table must cover the cache length, not just the model's
        # native max (see ops/rope.py clamping note)
        self.rope = rope_table(engine_cfg.max_model_len, model_cfg.head_dim_,
                               model_cfg.rope_theta,
                               scaling=model_cfg.rope_scaling)
        if params is None:
            t0 = time.time()
            params = llama.init_params(model_cfg, jax.random.PRNGKey(
                engine_cfg.seed))
            logger.info("random-initialized %s (%.2fs)", model_cfg.name,
                        time.time() - t0)
        if engine_cfg.quantization == "int8":
            from production_stack_tpu.models import quant
            # donate: XLA frees each fp buffer as its int8 copy is
            # produced, avoiding a ~1.5x transient HBM peak — which is
            # exactly when --quantization is needed (weights that barely
            # fit). The incoming params are consumed.
            params = jax.jit(quant.quantize_params,
                             donate_argnums=0)(params)
        self.params = params
        # paged pool [L, N, Hkv, Bs, D] + per-slot block tables [B, MB]
        # (models/kv.py); the tables device array is refreshed by the
        # engine whenever its allocator changes a row. Under a mesh the
        # block axis shards over dp (parallel/sharding.cache_pspec), so
        # N is padded up to a dp multiple — the extra blocks are simply
        # allocatable (the engine sizes its BlockManager from
        # cache.num_blocks, not the config).
        n_blocks = engine_cfg.num_kv_blocks
        if mesh is not None:
            dp_size = mesh.shape.get("dp", 1)
            n_blocks = -(-n_blocks // dp_size) * dp_size
        kv_dt = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                 "int8": jnp.int8}[engine_cfg.kv_dtype]
        self.cache: KVCache = make_cache(
            model_cfg.num_layers, n_blocks,
            engine_cfg.kv_block_size, model_cfg.num_kv_heads,
            model_cfg.head_dim_, dtype=kv_dt)
        self._tables = jnp.zeros(
            (engine_cfg.max_num_seqs, engine_cfg.max_blocks_per_seq),
            jnp.int32)
        self._tables_host = np.zeros(
            (engine_cfg.max_num_seqs, engine_cfg.max_blocks_per_seq),
            np.int32)
        self._tables_dirty = False
        if mesh is not None:
            # tensor-parallel serving: weights/cache sharded over the
            # slice's chips; XLA derives all ICI collectives from here
            from jax.sharding import NamedSharding
            from production_stack_tpu.parallel.sharding import (
                cache_pspec, param_shardings)
            from production_stack_tpu.ops import (pallas_attention,
                                                  pallas_paged)
            if not pallas_paged.mesh_tp_only(mesh):
                # block-axis-sharded pools (dp > 1) forfeit the paged
                # kernel (ops/pallas_paged.py mesh_tp_only): the
                # gathered-view fallback re-materializes ~3x the KV
                # traffic. Never let a helm value stumble into that —
                # and never stumble into it SILENTLY: the fallback is
                # announced at engine start in every world, not just
                # when the kernel would otherwise have run.
                cliff = (
                    "serving mesh %s shards the KV pool's block axis: "
                    "the pallas paged-attention kernel only runs "
                    "shard-local on tp-only meshes, so this config "
                    "serves on the gathered-view jnp path (~3x decode "
                    "KV traffic). Prefer tp-only serving meshes with "
                    "replicaCount for data parallelism." % dict(
                        mesh.shape))
                if not pallas_attention.flash_enabled():
                    # kernel unavailable on this backend anyway (CPU /
                    # interpret): informational, nothing to refuse
                    logger.warning(
                        "paged-attention kernel disabled for this "
                        "mesh: " + cliff)
                elif engine_cfg.dp_gather_attention_ok:
                    logger.warning(
                        "dp_gather_attention_ok=True: " + cliff)
                else:
                    raise ValueError(
                        cliff + " Set dp_gather_attention_ok=True to "
                        "serve on the gather path anyway.")
            tp = mesh.shape.get("tp", 1)
            if model_cfg.num_kv_heads % tp:
                raise ValueError(
                    f"tensor_parallel_size {tp} must divide num_kv_heads "
                    f"{model_cfg.num_kv_heads} (KV-head replication is not "
                    f"implemented yet)")
            ep = mesh.shape.get("ep", 1)
            if ep > 1:
                # validated here (not only in LLMEngine) so explicitly
                # passed meshes fail with a clear error too
                if not model_cfg.num_experts:
                    raise ValueError(
                        f"mesh has ep={ep} but model {model_cfg.name!r} "
                        f"is dense (no experts)")
                if model_cfg.num_experts % ep:
                    raise ValueError(
                        f"ep={ep} does not divide num_experts="
                        f"{model_cfg.num_experts}")
            self.params = jax.device_put(
                self.params, param_shardings(mesh, self.params))
            cache_sh = NamedSharding(mesh, cache_pspec())
            if self.cache.quantized:
                from production_stack_tpu.parallel.sharding import (
                    cache_scale_pspec)
                scale_sh = NamedSharding(mesh, cache_scale_pspec())
                self.cache = KVCache(
                    jax.device_put(self.cache.k, cache_sh),
                    jax.device_put(self.cache.v, cache_sh),
                    jax.device_put(self.cache.ks, scale_sh),
                    jax.device_put(self.cache.vs, scale_sh))
            else:
                self.cache = KVCache(
                    jax.device_put(self.cache.k, cache_sh),
                    jax.device_put(self.cache.v, cache_sh))
            from jax.sharding import PartitionSpec as _P
            self._tables_sharding = NamedSharding(mesh, _P())
            self._tables = jax.device_put(self._tables,
                                          self._tables_sharding)
            if self._lora is not None:
                # adapters are small (rank << hidden): replicate
                from jax.sharding import PartitionSpec
                self._lora = jax.device_put(
                    self._lora, NamedSharding(mesh, PartitionSpec()))
        else:
            self._tables_sharding = None
        self._key = jax.random.PRNGKey(engine_cfg.seed ^ 0x5EED)
        # device-carried decode inputs: (tokens [B], positions [B]);
        # refreshed from host mirrors only when the engine marks them stale
        self._dec_tokens = None
        self._dec_pos = None
        self._dec_gstate = None   # guided-decoding DFA states [B]
        # penalty state (uploaded only when some live row uses OpenAI
        # logit shaping — engine._dispatch_decode): generated-token
        # counts [B, V] ride the decode carry; prompt membership [B, V]
        # is per-window constant
        self._dec_counts = None
        self._dec_prompt_seen = None
        # EOS id for min_tokens masking; the engine sets it from its
        # tokenizer after construction (static per executable)
        self._eos_id = 0

        # compile observer (engine/efficiency.py): an object with
        # compile_started/compile_finished hooks, stamped around every
        # serving-executable build in _compile_with_fallback so compile
        # stalls are attributable (counters, histogram, trace events)
        # instead of bare log lines. None = no accounting (bare runner
        # in tests).
        self.compile_observer = None
        # executable caches: decode keyed (batch, steps, kv_len,
        # variant), prefill keyed (chunk bucket, kv bucket)
        self._decode_fns = {}
        self._prefill_fns = {}
        # per-batch-bucket sliced views of the sampling params and
        # block tables (invalidated when the source object changes):
        # batch-bucketed dispatches must not pay a 14-array re-slice
        # per window
        self._sampling_slices = (None, {})
        self._tables_slices = (None, {})
        # KV-tiering primitives (kvcache/connector.py), cached per chunk size
        self._extract_fns = {}
        self._inject_fns = {}
        # embeddings path, cached per (batch, padded length)
        self._embed_fns = {}
        # prompt-logprobs (echo) path, cached per (batch, padded length)
        self._prompt_lp_fns = {}

    def set_lora(self, lora_stacked, lora_scaling: float = None) -> None:
        """Swap the stacked adapter pytree in place (runtime adapter
        load, engine.load_adapter). Same layer_slice + replicate-under-
        mesh treatment as construction; per-row selection still rides
        sampling.adapter, so existing executables stay valid — the
        stacked tensors only grew a row along the adapter axis, which
        is a runtime input, not a compile-time shape for the rows in
        use... but a NEW row count IS a new input shape, so touched
        executables recompile once on next dispatch (expected, bounded:
        one build per adapter-count change per bucket)."""
        from production_stack_tpu.models import lora as lora_mod
        lora = lora_mod.layer_slice(lora_stacked)
        if lora is not None and self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            lora = jax.device_put(
                lora, NamedSharding(self.mesh, PartitionSpec()))
        self._lora = lora
        if lora_scaling is not None:
            self._lora_scaling = lora_scaling
        # adapter-count change means new stacked shapes: drop the
        # serving executables so the next dispatch builds against them
        # instead of feeding mismatched shapes to a stale jit cache
        # (the base-only paths — embed, prompt-logprobs, KV
        # extract/inject — never see the stack and keep their caches)
        self._decode_fns = {}
        self._prefill_fns = {}

    # ------------------------------------------------------------------
    # jitted impls (pure)
    # ------------------------------------------------------------------

    def _sample_position(self, last, sampling: SamplingParams, counts,
                         prompt_seen, pos, gstate, guide_next, guide_id,
                         key, *, greedy: bool, seeded: bool, plain: bool,
                         guided: bool, penalized: bool, eos_id: int,
                         topk: int):
        """The full single-position sampling treatment downstream of a
        forward's [B, V] logits, SHARED verbatim by _decode_impl (every
        step) and _decode_spec_impl (draft position 0 of every
        macro-step) so a row emits identically whichever executable its
        window ran on:

        penalty shaping (sampler.adjust_logits — counts ride the scan
        carry; the token being sampled is output index
        pos + 1 - prompt_len), the guided-DFA mask + state advance
        (one [B, V] gather per step, engine/guided.py), argmax or
        sample() (the sampled token lands at pos + 1 — the
        deterministic per-seed index; seeded/plain fork executables so
        default batches skip per-row PRNG / the [B, V] sort), the
        counts update, and the chosen-token logprob + top-K
        alternatives under the same post-shaping f32 distribution.

        Returns (ids [B], logprob [B], top_ids [B, K], top_lps [B, K],
        gstate', counts')."""
        B = last.shape[0]
        if penalized:
            last = adjust_logits(last, sampling, counts, prompt_seen,
                                 pos + 1 - sampling.prompt_len, eos_id)
        if guided:
            nxt_row = guide_next[guide_id, gstate, :]
            is_g = (guide_id > 0)[:, None]
            last = jnp.where(is_g & (nxt_row < 0), -jnp.inf, last)
        if greedy:
            ids = jnp.argmax(last, axis=-1).astype(jnp.int32)
        else:
            ids = sample(last, sampling, key,
                         positions=pos + 1 if seeded else None,
                         plain=plain)
        if guided:
            adv = jnp.take_along_axis(nxt_row, ids[:, None],
                                      axis=-1)[:, 0]
            gstate = jnp.where(guide_id > 0,
                               jnp.maximum(adv, 0), gstate)
        if penalized:
            counts = counts.at[jnp.arange(B), ids].add(1)
        lsm = jax.nn.log_softmax(last.astype(jnp.float32), axis=-1)
        lp = jnp.take_along_axis(lsm, ids[:, None], axis=-1)[:, 0]
        if topk:
            tl, ti = jax.lax.top_k(lsm, topk)
        else:
            tl = jnp.zeros((B, 1), jnp.float32)
            ti = jnp.zeros((B, 1), jnp.int32)
        return ids, lp, ti, tl, gstate, counts

    def _decode_impl(self, params, cache: KVCache, tables: jnp.ndarray,
                     tokens: jnp.ndarray,
                     positions: jnp.ndarray, sampling: SamplingParams,
                     key: jax.Array, guide_next: jnp.ndarray,
                     guide_id: jnp.ndarray, guide_state: jnp.ndarray,
                     out_counts: jnp.ndarray, prompt_seen: jnp.ndarray,
                     *, steps: int, kv_len: int,
                     greedy: bool, seeded: bool = False,
                     guided: bool = False, plain: bool = False,
                     penalized: bool = False, eos_id: int = 0,
                     topk: int = 0):
        """tokens/positions [B] -> (ids [B, steps], logprobs [B, steps],
        tokens', positions', cache').

        `steps` forwards are fused via lax.scan; each step feeds its
        sampled ids back as the next step's tokens, and the final
        (tokens, positions) come back as device arrays to carry into the
        next window without a host round-trip. K/V writes go through the
        block tables; rows whose position has reached max_model_len
        (parked rows, finished windows' tails) are masked invalid and
        write to the trash block. Attention reads the first
        ceil(kv_len/Bs) blocks of every slot; the host guarantees every
        live position stays < kv_len AND its table row covers the whole
        window (engine._ensure_blocks).

        logprobs are the chosen tokens' log p under the PRE-temperature
        but POST-shaping distribution — after penalties/logit_bias
        (adjust_logits) and the guided-DFA mask, before temperature/
        top-p/top-k. For unshaped, unguided rows that is exactly the
        raw model distribution; shaped rows report the distribution
        they were actually decoded from (documented in docs/engine.md
        and protocol.py). One [B, V] log_softmax per step, noise next
        to the weight streaming, so they're always computed rather
        than forking the executable cache.
        """
        S = self.engine_cfg.max_model_len

        def body(carry, i):
            cache, toks, pos, gstate, counts = carry
            logits, cache = llama.forward(
                params, self.model_cfg, toks[:, None], pos[:, None],
                cache, block_tables=tables,
                rope=self.rope, kv_len=kv_len, use_flash=None,
                mesh=self.mesh,
                lora_params=self._lora, adapter_ids=sampling.adapter,
                lora_scaling=self._lora_scaling,
                token_valid=(pos < S)[:, None])
            ids, lp, ti, tl, gstate, counts = self._sample_position(
                logits[:, 0, :], sampling, counts, prompt_seen, pos,
                gstate, guide_next, guide_id,
                jax.random.fold_in(key, i), greedy=greedy,
                seeded=seeded, plain=plain, guided=guided,
                penalized=penalized, eos_id=eos_id, topk=topk)
            return ((cache, ids, pos + 1, gstate, counts),
                    (ids, lp, ti, tl))

        (cache, toks, pos, gstate, counts), (ids, lps, tis, tls) = \
            jax.lax.scan(
                body, (cache, tokens, positions, guide_state, out_counts),
                jnp.arange(steps))
        # ids/lps [B, steps]; tis/tls [B, steps, K]
        return (ids.T, lps.T, tis.transpose(1, 0, 2),
                tls.transpose(1, 0, 2), toks, pos, gstate, counts,
                cache)

    def _decode_spec_impl(self, params, cache: KVCache,
                          tables: jnp.ndarray,
                          tokens: jnp.ndarray, positions: jnp.ndarray,
                          history: jnp.ndarray, spec_ok: jnp.ndarray,
                          sampling: SamplingParams, key: jax.Array,
                          guide_next: jnp.ndarray, guide_id: jnp.ndarray,
                          guide_state: jnp.ndarray,
                          out_counts: jnp.ndarray,
                          prompt_seen: jnp.ndarray, *, steps: int,
                          kv_len: int, spec: int, mixed: bool = False,
                          seeded: bool = False, guided: bool = False,
                          plain: bool = False, penalized: bool = False,
                          eos_id: int = 0, topk: int = 0):
        """Decode window with PER-ROW n-gram (prompt-lookup) speculation.

        tokens/positions [B]; history [B, S] device-resident token ids
        (hist[b, t] = sequence b's token at position t, live through
        `positions[b]`); spec_ok [B] bool marks rows that speculate —
        greedy, unshaped, unguided, no-alternatives rows (the engine
        computes eligibility per row). Each of the `steps` macro-steps
        drafts `spec` tokens per row by copying what followed the most
        recent PRIOR occurrence of the current bigram in the history,
        verifies all spec+1 positions in one forward, and emits the
        agreeing prefix plus the bonus token — between 1 and spec+1
        tokens per macro-step, exact greedy semantics by construction
        (every emitted token is an argmax given the true prefix).

        Rows with spec_ok=False emit exactly one token per macro-step
        (acceptance forced to 0) and get the full single-step treatment
        at draft position 0: penalty shaping (adjust_logits), the
        guided-DFA mask + state advance, temperature sampling for
        non-greedy rows (`mixed`), and top-K alternatives. One shaped,
        guided, sampled, or top_logprobs row therefore no longer
        collapses speculation for the whole batch — it just declines it
        for itself.

        Returns (ids [B, steps, spec+1], logprobs same, top-K ids/lps
        [B, steps, K], counts [B, steps] valid-token counts, tokens',
        positions', history', gstate', out_counts', cache'). Rejected
        draft positions hold garbage K/V past the live length; the
        write-then-attend invariant (models/kv.py) makes them
        unobservable, exactly like window tail waste.
        """
        B = tokens.shape[0]
        S = history.shape[1]
        K = spec
        S_max = self.engine_cfg.max_model_len

        def draft_row(hist, pos):
            # latest i < pos with (hist[i-1], hist[i]) == current bigram
            a = hist[jnp.maximum(pos - 1, 0)]
            c = hist[pos]
            idx = jnp.arange(S)
            m = ((idx >= 1) & (idx < pos)
                 & (jnp.roll(hist, 1) == a) & (hist == c))
            j = jnp.max(jnp.where(m, idx, 0))     # 0 = no match
            return jax.lax.dynamic_slice(hist, (j + 1,), (K,))

        def body(carry, i):
            cache, toks, pos, hist, gstate, counts = carry
            draft = jax.vmap(draft_row)(hist, pos)          # [B, K]
            step_toks = jnp.concatenate([toks[:, None], draft], axis=1)
            step_pos = pos[:, None] + jnp.arange(K + 1)[None, :]
            logits, cache = llama.forward(
                params, self.model_cfg, step_toks, step_pos, cache,
                block_tables=tables,
                rope=self.rope, kv_len=kv_len, use_flash=None,
                mesh=self.mesh,
                lora_params=self._lora, adapter_ids=sampling.adapter,
                lora_scaling=self._lora_scaling,
                token_valid=step_pos < S_max)
            expected = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # draft position 0 = the ordinary next token: the SHARED
            # single-position treatment (_sample_position) so every row
            # emits exactly what _decode_impl would have emitted. For
            # spec-eligible rows (greedy, unshaped, unguided) every
            # transform in it is identity and tok0 == the raw argmax,
            # so substituting it for expected[:, 0] changes nothing on
            # the speculative fast path.
            tok0, lp0, ti, tl, gstate, counts = self._sample_position(
                logits[:, 0, :], sampling, counts, prompt_seen, pos,
                gstate, guide_next, guide_id,
                jax.random.fold_in(key, i), greedy=not mixed,
                seeded=seeded, plain=plain, guided=guided,
                penalized=penalized, eos_id=eos_id, topk=topk)
            expected = expected.at[:, 0].set(tok0)
            lp = jnp.take_along_axis(
                jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1),
                expected[..., None], axis=-1)[..., 0]       # [B, K+1]
            lp = lp.at[:, 0].set(lp0)
            agree = (draft == expected[:, :K])
            accepted = jnp.sum(jnp.cumprod(
                agree.astype(jnp.int32), axis=1), axis=1)   # [B] in 0..K
            accepted = jnp.where(spec_ok, accepted, 0)
            count = accepted + 1                            # emitted
            new_pos = pos + count
            new_toks = jnp.take_along_axis(
                expected, (count - 1)[:, None], axis=1)[:, 0]

            def write_row(h, p, emitted):
                return jax.lax.dynamic_update_slice(h, emitted,
                                                    (p + 1,))
            hist = jax.vmap(write_row)(hist, pos, expected)
            return ((cache, new_toks, new_pos, hist, gstate, counts),
                    (expected, lp, ti, tl, count))

        ((cache, toks, pos, hist, gstate, counts),
         (ids, lps, tis, tls, cnt)) = jax.lax.scan(
            body, (cache, tokens, positions, history, guide_state,
                   out_counts),
            jnp.arange(steps))
        # scan stacks on axis 0: -> [B, steps, K+1] / [B, steps]
        return (ids.transpose(1, 0, 2), lps.transpose(1, 0, 2),
                tis.transpose(1, 0, 2), tls.transpose(1, 0, 2),
                cnt.T, toks, pos, hist, gstate, counts, cache)

    def _prefill_impl(self, params, cache: KVCache, tables: jnp.ndarray,
                      tokens: jnp.ndarray,
                      starts: jnp.ndarray, lengths: jnp.ndarray,
                      sampling: SamplingParams, key: jax.Array,
                      guide_next: jnp.ndarray, guide_id: jnp.ndarray,
                      guide_state: jnp.ndarray,
                      out_counts: jnp.ndarray, prompt_seen: jnp.ndarray,
                      *, kv_len: int, guided: bool = False,
                      penalized: bool = False, eos_id: int = 0,
                      topk: int = 0):
        """Full-batch chunk prefill. tokens [B, Tb], starts/lengths [B].

        Every row writes its chunk at its own offset through its block
        table; idle rows (parked at start S) and right-padding tokens
        are masked invalid and write to the trash block. Attention
        reads the first ceil(kv_len/Bs) blocks; host guarantees
        start + real chunk length <= kv_len for every participating
        row, whose table covers its whole chunk (blocks are allocated
        for the full prompt at admission).
        Returns (sampled id of each row's last real token [B], its
        logprob [B], cache').
        """
        Tb = tokens.shape[1]
        S = self.engine_cfg.max_model_len
        positions = starts[:, None] + jnp.arange(Tb)[None, :]
        # real tokens per row: right-padding and idle rows must not
        # write K/V, route in MoE layers, or steal expert capacity
        token_valid = ((jnp.arange(Tb)[None, :] < lengths[:, None])
                       & (starts < S)[:, None])
        logits, cache = llama.forward(
            params, self.model_cfg, tokens, positions, cache,
            block_tables=tables,
            rope=self.rope, kv_len=kv_len,
            use_flash=None, mesh=self.mesh,
            lora_params=self._lora, adapter_ids=sampling.adapter,
            lora_scaling=self._lora_scaling, token_valid=token_valid)
        last = jnp.take_along_axis(
            logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
        )[:, 0, :]
        if penalized:
            # first sampled token: counts cover any already-emitted
            # output (preemption-resume rows), prompt_seen the prompt
            last = adjust_logits(
                last, sampling, out_counts, prompt_seen,
                starts + lengths - sampling.prompt_len, eos_id)
        if guided:
            # first output token: mask from each guided row's start state
            nxt_row = guide_next[guide_id, guide_state, :]
            is_g = (guide_id > 0)[:, None]
            last = jnp.where(is_g & (nxt_row < 0), -jnp.inf, last)
        ids = sample(last, sampling, key,
                     positions=starts + jnp.maximum(lengths, 1))
        lsm = jax.nn.log_softmax(last, axis=-1)
        lp = jnp.take_along_axis(lsm, ids[:, None], axis=-1)[:, 0]
        if topk:
            tl, ti = jax.lax.top_k(lsm, topk)
        else:
            B2 = last.shape[0]
            tl = jnp.zeros((B2, 1), jnp.float32)
            ti = jnp.zeros((B2, 1), jnp.int32)
        return ids, lp, ti, tl, cache

    # ------------------------------------------------------------------
    # host API
    # ------------------------------------------------------------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def set_block_tables(self, tables) -> None:
        """Note a change to the host block-table mirror [B, MB] int32.

        The upload is DEFERRED to the next dispatch that reads the
        tables (`_dev_tables`): the engine touches table rows several
        times per window (per-sequence block growth, admission,
        parking), and eager uploads would pay one host->device transfer
        per touch — each a full round-trip when the chip sits behind a
        high-latency tunnel. Deferral coalesces them into at most one
        upload per dispatch."""
        self._tables_host = tables
        self._tables_dirty = True

    def _dev_tables(self) -> jnp.ndarray:
        if self._tables_dirty:
            t = jnp.asarray(self._tables_host, jnp.int32)
            if self._tables_sharding is not None:
                t = jax.device_put(t, self._tables_sharding)
            self._tables = t
            self._tables_dirty = False
        return self._tables

    def set_decode_state(self, tokens, positions,
                         guide_states=None, history=None) -> None:
        """Upload fresh decode inputs (host mirrors -> device carry).
        history [B, S] token ids (speculative n-gram drafting) is only
        uploaded when the engine runs with speculation enabled."""
        self._dec_tokens = jnp.asarray(tokens, jnp.int32)
        self._dec_pos = jnp.asarray(positions, jnp.int32)
        self._dec_gstate = (jnp.zeros_like(self._dec_tokens)
                            if guide_states is None
                            else jnp.asarray(guide_states, jnp.int32))
        self._dec_hist = (None if history is None
                          else jnp.asarray(history, jnp.int32))

    def set_penalty_state(self, out_counts, prompt_seen) -> None:
        """Upload OpenAI logit-shaping state: generated-token counts
        [B, V] int32 (rides the decode carry like tokens/positions) and
        prompt membership [B, V] bool. Only called when some live row
        uses penalties/min_tokens/logit_bias."""
        self._dec_counts = jnp.asarray(out_counts, jnp.int32)
        self._dec_prompt_seen = jnp.asarray(prompt_seen, bool)

    def _batch_sized(self, x, B: int):
        """Slice a host/device array's leading axis to the dispatch
        batch B (identity when already sized — the common steady
        case pays nothing)."""
        return x if x.shape[0] == B else x[:B]

    def _cached_slice(self, store_attr: str, source, B: int, make):
        """Memoize per-batch-bucket sliced views of a source object
        (sampling params, block tables) until the source is replaced:
        steady bucketed windows re-dispatch with the same inputs and
        must not re-slice per window."""
        src, cache = getattr(self, store_attr)
        if src is not source:
            cache = {}
            setattr(self, store_attr, (source, cache))
        out = cache.get(B)
        if out is None:
            out = cache[B] = make()
        return out

    def decode(self, sampling: SamplingParams, steps: int = 1,
               kv_len: Optional[int] = None, greedy: bool = False,
               seeded: bool = False, guide_table=None, guide_ids=None,
               spec: int = 0, spec_ok=None, plain: bool = False,
               penalized: bool = False, topk: int = 0):
        """Multi-step decode window over the CARRIED batch: the batch
        axis is whatever ``set_decode_state`` last uploaded — the
        engine's batch-bucketed compaction (docs/engine.md "Continuous
        batching across windows") uploads only the low ``B_bucket``
        slots, and every input here (sampling mirrors, block tables,
        guided ids, penalty carry) is sliced to that bucket, so parked
        rows beyond it are simply not computed. Executables are cached
        per (batch, steps, kv bucket, variant). Returns
        (ids, logprobs, counts, tops): without speculation ids/logprobs
        are [B, steps] and counts is None; with spec > 0 they are
        [B, steps, spec+1] plus counts [B, steps] of valid tokens per
        macro-step (_decode_spec_impl) — speculation is PER-ROW via
        spec_ok [B] bool (rows with False single-step with the full
        shaping/guided/sampling treatment). tops is None unless
        topk > 0: then (ids [B, steps, K], logprobs [B, steps, K])
        top-K alternatives per step. The first np.asarray() is the
        window's single sync.

        guide_table [G, S, V] device int32 + guide_ids [B] activate
        constrained sampling (engine/guided.py); the per-row DFA state
        rides the device carry like tokens/positions."""
        kv_len = kv_len or self.engine_cfg.max_model_len
        seeded = seeded and not greedy
        plain = plain and not greedy
        guided = guide_table is not None
        gshape = guide_table.shape if guided else (1, 1, 1)
        # the dispatch batch IS the carried batch: the engine's
        # compaction uploads bucketed mirrors, everything else here
        # follows that shape
        B = int(self._dec_tokens.shape[0])
        src_sampling = sampling
        sampling = self._cached_slice(
            "_sampling_slices", src_sampling, B,
            lambda: jax.tree_util.tree_map(
                lambda x: self._batch_sized(x, B), src_sampling))
        full_tables = self._dev_tables()
        tables = self._cached_slice(
            "_tables_slices", full_tables, B,
            lambda: self._batch_sized(full_tables, B))
        if not guided:
            guide_table = jnp.zeros((1, 1, 1), jnp.int32)
            guide_ids = jnp.zeros((B,), jnp.int32)
        else:
            guide_ids = self._batch_sized(
                jnp.asarray(guide_ids, jnp.int32), B)
        if penalized:
            counts = self._batch_sized(self._dec_counts, B)
            seen = self._batch_sized(self._dec_prompt_seen, B)
        else:
            # dummy carries: the unpenalized executable never reads or
            # writes them, so keep them tiny
            counts = jnp.zeros((B, 1), jnp.int32)
            seen = jnp.zeros((B, 1), bool)
        if spec:
            mixed = not greedy
            args = (self.params, self.cache, tables,
                    self._dec_tokens, self._dec_pos, self._dec_hist,
                    self._batch_sized(jnp.asarray(spec_ok, bool), B),
                    sampling,
                    self._next_key(), guide_table,
                    guide_ids, self._dec_gstate,
                    counts, seen)
            key = ("spec", B, steps, kv_len, spec, mixed, seeded, guided,
                   gshape, plain, penalized, topk)

            def make_spec():
                logger.info("compiling speculative decode window "
                            "(batch=%d steps=%d kv=%d draft=%d%s%s%s%s)",
                            B, steps, kv_len, spec,
                            " mixed" if mixed else "",
                            " guided" if guided else "",
                            " penalized" if penalized else "",
                            f" topk={topk}" if topk else "")
                return jax.jit(
                    partial(self._decode_spec_impl, steps=steps,
                            kv_len=kv_len, spec=spec, mixed=mixed,
                            seeded=seeded, guided=guided, plain=plain,
                            penalized=penalized, eos_id=self._eos_id,
                            topk=topk),
                    donate_argnums=(1,))

            fn = self._compile_with_fallback(self._decode_fns, key,
                                             make_spec, args,
                                             kind="decode_spec",
                                             window=steps, kv_len=kv_len,
                                             batch=B)
            (ids, lps, tis, tls, cnt, self._dec_tokens, self._dec_pos,
             self._dec_hist, self._dec_gstate, counts_out,
             self.cache) = fn(*args)
            if penalized:
                self._dec_counts = counts_out
            return ids, lps, cnt, (tis, tls) if topk else None
        cache_key = (B, steps, kv_len, greedy, seeded, guided, gshape,
                     plain, penalized, topk)
        args = (self.params, self.cache, tables,
                self._dec_tokens, self._dec_pos,
                sampling, self._next_key(), guide_table,
                guide_ids, self._dec_gstate,
                counts, seen)

        def make_decode():
            logger.info("compiling decode window (batch=%d steps=%d "
                        "kv=%d greedy=%s%s%s%s)", B, steps, kv_len,
                        greedy,
                        " seeded" if seeded else "",
                        " guided" if guided else "",
                        " penalized" if penalized else "")
            return jax.jit(
                partial(self._decode_impl, steps=steps, kv_len=kv_len,
                        greedy=greedy, seeded=seeded, guided=guided,
                        plain=plain, penalized=penalized,
                        eos_id=self._eos_id, topk=topk),
                donate_argnums=(1,))

        fn = self._compile_with_fallback(self._decode_fns, cache_key,
                                         make_decode, args,
                                         kind="decode", window=steps,
                                         kv_len=kv_len, batch=B)
        (ids, lps, tis, tls, self._dec_tokens, self._dec_pos,
         self._dec_gstate, counts_out, self.cache) = fn(*args)
        if penalized:
            self._dec_counts = counts_out
        return ids, lps, None, (tis, tls) if topk else None

    def _compile_with_fallback(self, cache: dict, key, make_fn, args,
                               kind: str = "", window: int = 0,
                               kv_len: int = 0, batch: int = 0):
        """Fetch-or-compile an executable; if the pallas paged kernel
        fails to BUILD for this combination (backend or VMEM limits
        beyond paged_viable's estimate), recompile THIS key on the jnp
        attention path and cache that. The fallback is per-executable:
        kernel build failures are per-geometry (one chunk size missing
        a VMEM budget says nothing about the others), so combinations
        that already compiled — or will — keep the kernel. Compilation
        is an explicit lower+compile BEFORE any buffers are donated, so
        a runtime failure of a working executable propagates unchanged
        (retrying it would re-pass a donated, deleted cache buffer).

        Every cache miss is stamped through ``compile_observer``
        (kind, window, kv bucket, wall duration — the fallback recompile
        is part of the same stall and folds into one event): compiles
        block the engine loop for seconds, so they must be countable
        and visible in /debug/traces, not just log lines."""
        fn = cache.get(key)
        if fn is not None:
            return fn
        from production_stack_tpu.ops import pallas_attention
        obs = self.compile_observer
        t0 = time.monotonic()
        if obs is not None:
            obs.compile_started(kind, window, kv_len, batch)
        try:
            try:
                fn = make_fn()
                fn.lower(*args).compile()   # donation applies at execution
            except Exception:
                if not pallas_attention.flash_enabled():
                    raise
                logger.exception(
                    "pallas paged attention failed to compile for %r; "
                    "recompiling this executable on the jnp attention "
                    "path", key)
                with pallas_attention.force_jnp():
                    fn = make_fn()
                    fn.lower(*args).compile()
        finally:
            if obs is not None:
                obs.compile_finished(kind, window, kv_len, t0,
                                     time.monotonic() - t0, batch)
        cache[key] = fn
        return fn

    def prefill(self, tokens, starts, lengths, sampling: SamplingParams,
                kv_len: int, guide_table=None, guide_ids=None,
                guide_states=None, penalized: bool = False,
                topk: int = 0):
        """Full-batch chunk prefill (see _prefill_impl). tokens [B, Tb]
        int32 np; starts/lengths [B]. Returns device (ids, logprobs,
        tops) — ids/logprobs [B]; tops None unless topk > 0, then
        ([B, K] ids, [B, K] logprobs) alternatives.

        Prefill executables compile lazily per (chunk, kv bucket); if the
        pallas flash kernel fails to BUILD for a combination (backend or
        VMEM limits beyond flash_viable's estimate), that combination —
        and only that combination — is recompiled and cached on the jnp
        attention path (_compile_with_fallback). The fallback is
        compile-scoped: compilation happens via an explicit
        lower+compile before any buffers are donated, so a runtime
        failure of an already-working executable propagates unchanged
        (retrying it would re-pass a donated, deleted cache buffer).
        """
        Tb = tokens.shape[1]
        guided = guide_table is not None
        B = self.engine_cfg.max_num_seqs
        if not guided:
            guide_table = jnp.zeros((1, 1, 1), jnp.int32)
            guide_ids = np.zeros((B,), np.int32)
            guide_states = np.zeros((B,), np.int32)
        if penalized:
            counts, seen = self._dec_counts, self._dec_prompt_seen
        else:
            counts = jnp.zeros((B, 1), jnp.int32)
            seen = jnp.zeros((B, 1), bool)
        args = (self.params, self.cache, self._dev_tables(),
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(starts, jnp.int32),
                jnp.asarray(lengths, jnp.int32), sampling, self._next_key(),
                guide_table, jnp.asarray(guide_ids, jnp.int32),
                jnp.asarray(guide_states, jnp.int32), counts, seen)
        gshape = guide_table.shape if guided else None

        def make_prefill():
            logger.info("compiling prefill (chunk=%d kv=%d%s%s)", Tb,
                        kv_len, " guided" if guided else "",
                        " penalized" if penalized else "")
            return jax.jit(partial(self._prefill_impl, kv_len=kv_len,
                                   guided=guided, penalized=penalized,
                                   eos_id=self._eos_id, topk=topk),
                           donate_argnums=(1,))

        fn = self._compile_with_fallback(
            self._prefill_fns,
            (Tb, kv_len, guided, gshape, penalized, topk),
            make_prefill, args, kind="prefill", window=Tb,
            kv_len=kv_len, batch=B)
        ids, lps, tis, tls, self.cache = fn(*args)
        return ids, lps, (tis, tls) if topk else None

    def embed(self, tokens, lengths):
        """Mean-pooled final hidden states for padded prompts.

        tokens [N, Tb] int32 np (right-padded), lengths [N] -> fp32
        [N, H]. Powers /v1/embeddings (and rerank/score built on it);
        no KV cache involved, nothing donated, safe to dispatch from the
        server thread next to the engine loop.
        """
        N, Tb = tokens.shape
        fn = self._embed_fns.get((N, Tb))
        if fn is None:
            logger.info("compiling embed (batch=%d len=%d)", N, Tb)

            def _impl(params, toks, lens):
                mask = (jnp.arange(Tb)[None, :] < lens[:, None])
                h = llama.encode(params, self.model_cfg, toks,
                                 rope=self.rope, token_valid=mask)
                pooled = jnp.sum(
                    h.astype(jnp.float32) * mask[:, :, None], axis=1)
                return pooled / jnp.maximum(lens, 1)[:, None]

            fn = self._embed_fns[(N, Tb)] = jax.jit(_impl)
        return fn(self.params, jnp.asarray(tokens, jnp.int32),
                  jnp.asarray(lengths, jnp.int32))

    def prompt_logprobs(self, tokens):
        """Teacher-forced logprobs of a prompt batch.

        tokens [N, T] int32 np -> fp32 [N, Tb-1] where Tb is T padded
        to a power-of-two bucket (bounded compile count; callers slice
        their row to [:len-1] — entry t is log p(tokens[t+1] |
        tokens[:t+1]) under the raw model distribution, position 0 has
        none, and entries past a row's real length are padding
        garbage). The LM head runs in 256-token chunks so only a
        [N, 256, vocab] fp32 slab materializes — an 8k echo prompt on a
        150k vocab would otherwise spike ~5 GB of HBM. Like embed(),
        cache-free and nothing donated: safe to dispatch from the
        server thread next to the engine loop."""
        N, T = tokens.shape
        Tb = max(16, 1 << (T - 1).bit_length())
        Tb = min(Tb, self.engine_cfg.max_model_len)
        if Tb < T:
            raise ValueError(f"prompt length {T} exceeds max_model_len")
        pad = np.zeros((N, Tb), np.int32)
        pad[:, :T] = tokens
        fn = self._prompt_lp_fns.get((N, Tb))
        if fn is None:
            logger.info("compiling prompt-logprobs (batch=%d len=%d)",
                        N, Tb)
            C = min(256, Tb)
            n_chunks = -(-(Tb - 1) // C)

            def _impl(params, toks):
                h = llama.encode(params, self.model_cfg, toks,
                                 rope=self.rope)
                hh = h[:, :-1]
                tg = toks[:, 1:]
                padded = n_chunks * C
                hh = jnp.pad(hh, ((0, 0), (0, padded - (Tb - 1)),
                                  (0, 0)))
                tg = jnp.pad(tg, ((0, 0), (0, padded - (Tb - 1))))
                hh = hh.reshape(N, n_chunks, C, -1).transpose(1, 0, 2, 3)
                tg = tg.reshape(N, n_chunks, C).transpose(1, 0, 2)

                def body(_, xs):
                    hc, tc = xs
                    logits = llama._lm_head(params, self.model_cfg, hc)
                    lse = jax.nn.logsumexp(logits, axis=-1)
                    tgt = jnp.take_along_axis(
                        logits, tc[..., None], axis=-1)[..., 0]
                    return None, tgt - lse

                _, lps = jax.lax.scan(body, None, (hh, tg))
                return lps.transpose(1, 0, 2).reshape(N, -1)[:, :Tb - 1]

            fn = self._prompt_lp_fns[(N, Tb)] = jax.jit(_impl)
        return fn(self.params, jnp.asarray(pad, jnp.int32))

    def _slot_block_offsets(self, tables, slot, start, size: int):
        """(block ids [size], intra-block offsets [size]) for a slot's
        virtual positions start..start+size-1 (through its table row)."""
        Bs = self.engine_cfg.kv_block_size
        MB = self.engine_cfg.max_blocks_per_seq
        pos = start + jnp.arange(size)
        row = jnp.take(tables, slot, axis=0)                  # [MB]
        blk = jnp.take(row, jnp.clip(pos // Bs, 0, MB - 1))   # [size]
        return blk, pos % Bs

    def extract_chunk(self, slot: int, start: int, size: int):
        """Gather [L, size, Hkv, D] k/v out of a slot's blocks (no
        donation; the result is an independent buffer, safe to D2H after
        later steps donate the cache). Dispatch is async —
        np.asarray() later blocks."""
        fn = self._extract_fns.get(size)
        if fn is None:
            def _impl(cache: KVCache, tables, slot, start):
                blk, off = self._slot_block_offsets(tables, slot, start,
                                                    size)
                # advanced indices (block, offset) put [size] first:
                # [size, L, Hkv, D] -> chunk layout [L, size, Hkv, D]
                k = cache.k[:, blk, :, off, :].transpose(1, 0, 2, 3)
                v = cache.v[:, blk, :, off, :].transpose(1, 0, 2, 3)
                if cache.quantized:
                    # tiers store full-precision chunks (portable across
                    # kv_dtype configs of the same fingerprint
                    # namespace). Multiply in f32 — the same precision
                    # the attention kernels dequantize at — THEN round
                    # to the bf16 wire dtype
                    ks = cache.ks[:, blk, :, off].transpose(1, 0, 2)
                    vs = cache.vs[:, blk, :, off].transpose(1, 0, 2)
                    k = (k.astype(jnp.float32)
                         * ks[..., None]).astype(jnp.bfloat16)
                    v = (v.astype(jnp.float32)
                         * vs[..., None]).astype(jnp.bfloat16)
                return k, v

            fn = self._extract_fns[size] = jax.jit(_impl)
        return fn(self.cache, self._dev_tables(), jnp.int32(slot),
                  jnp.int32(start))

    def inject_chunk(self, slot: int, start: int, k_chunk, v_chunk) -> None:
        """Scatter host [L, size, Hkv, D] k/v into a slot's blocks
        (donates cache — in-place HBM update). The slot's table must
        already cover start+size positions (admission allocates the
        full prompt's blocks before tier injection runs)."""
        size = k_chunk.shape[1]
        fn = self._inject_fns.get(size)
        if fn is None:
            def _impl(cache: KVCache, tables, k_chunk, v_chunk, slot,
                      start):
                blk, off = self._slot_block_offsets(tables, slot, start,
                                                    size)
                if cache.quantized:
                    # tier chunks are full precision; re-quantize on the
                    # way in ([L, size, Hkv, D] vectors, same recipe as
                    # serving writes — models/kv.quantize_chunk)
                    from production_stack_tpu.models.kv import (
                        quantize_chunk)
                    kq, ksc = quantize_chunk(k_chunk)
                    vq, vsc = quantize_chunk(v_chunk)
                    k = cache.k.at[:, blk, :, off, :].set(
                        kq.transpose(1, 0, 2, 3))
                    v = cache.v.at[:, blk, :, off, :].set(
                        vq.transpose(1, 0, 2, 3))
                    ks = cache.ks.at[:, blk, :, off].set(
                        ksc.transpose(1, 0, 2))
                    vs = cache.vs.at[:, blk, :, off].set(
                        vsc.transpose(1, 0, 2))
                    return KVCache(k, v, ks, vs)
                kc = k_chunk.astype(cache.k.dtype).transpose(1, 0, 2, 3)
                vc = v_chunk.astype(cache.v.dtype).transpose(1, 0, 2, 3)
                k = cache.k.at[:, blk, :, off, :].set(kc)
                v = cache.v.at[:, blk, :, off, :].set(vc)
                return KVCache(k, v)

            fn = self._inject_fns[size] = jax.jit(_impl,
                                                  donate_argnums=(0,))
        self.cache = fn(self.cache, self._dev_tables(), jnp.asarray(k_chunk),
                        jnp.asarray(v_chunk), jnp.int32(slot),
                        jnp.int32(start))

    def warmup(self) -> float:
        """Compile the hot executables at the smallest kv bucket:
        with ``window_adapt`` on, the FULL (batch bucket x window
        bucket) grid for the greedy and plain-sampled variants — the
        adaptive dispatch walks that grid in steady state, and a
        combination left cold here is a multi-second compile stall
        mid-serving (the effwatch zero-steady-state-compiles gate
        pins this) — plus the full-sort sampled variant and the
        speculative executable at the full shape only. With adaptation
        off, just the three variants at (max_num_seqs, decode_window).
        Every prefill bucket compiles at its minimal kv bucket. Larger
        kv buckets and rarely-hit variants (guided/penalized/topk,
        adapted sampled-sort shapes) compile lazily on first use
        (one-time, logged). Returns seconds spent."""
        import numpy as np
        t0 = time.time()
        cfg = self.engine_cfg
        B = cfg.max_num_seqs
        S = cfg.max_model_len
        kv0 = cfg.kv_len_buckets[0]
        sampling = SamplingParams.filled(B)

        def park(b: int, history: bool = False) -> None:
            # park every row at S: warmup writes only clamp onto S-1
            self.set_decode_state(
                np.zeros((b,), np.int32), np.full((b,), S, np.int32),
                history=np.zeros((b, S), np.int32) if history else None)

        if cfg.speculative_ngram_tokens:
            # spec-enabled greedy windows use the speculative executable,
            # not the plain greedy one — compile the real hot path
            park(B, history=True)
            self.decode(sampling, steps=cfg.decode_window,
                        kv_len=kv0, greedy=True,
                        spec=cfg.speculative_ngram_tokens,
                        spec_ok=np.ones((B,), bool))
        batches = cfg.decode_batch_buckets if cfg.window_adapt else (B,)
        windows = (cfg.decode_window_buckets if cfg.window_adapt
                   else (cfg.decode_window,))
        for b in batches:
            for w in windows:
                park(b)
                self.decode(sampling, steps=w, kv_len=kv0, greedy=True)
                # the API default (temperature=1, top_p=1, top_k=0)
                # runs the sort-free plain variant — warm it across
                # the grid too so default-sampling storms never pay a
                # mid-serving compile either
                park(b)
                self.decode(sampling, steps=w, kv_len=kv0,
                            greedy=False, plain=True)
        # truncated sampling (top_p<1 / top_k / min_p) runs the
        # full-sort executable: warm the full shape only (adapted
        # shapes compile lazily — the sort dominates its cost anyway)
        park(B)
        self.decode(sampling, steps=cfg.decode_window, kv_len=kv0,
                    greedy=False)
        for bucket in cfg.prefill_buckets:
            # prefill() falls back to the jnp path by itself if the
            # flash kernel cannot compile on this backend
            self.prefill(np.zeros((B, bucket), np.int32),
                         np.full((B,), S, np.int32),
                         np.ones((B,), np.int32), sampling,
                         cfg.kv_bucket_for(bucket))
        jax.block_until_ready(self.cache.k)
        dt = time.time() - t0
        logger.info(
            "warmup compiled decode grid (batch %s x window %s, kv %d) "
            "+ %d prefill buckets in %.1fs", list(batches),
            list(windows), kv0, len(cfg.prefill_buckets), dt)
        return dt
