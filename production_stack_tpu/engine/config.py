"""Engine configuration.

Mirrors the knobs the reference exposes as `vllm serve` flags rendered by
Helm (reference: helm/templates/deployment-vllm-multi.yaml:68-93 —
--max-model-len, --dtype, --enable-chunked-prefill, --tensor-parallel-size,
--enable-prefix-caching) as a typed config for the in-repo engine.
"""

import dataclasses
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass
class EngineConfig:
    model: str = "debug-tiny"
    tokenizer: Optional[str] = None          # defaults to model path
    chat_template: Optional[str] = None      # Jinja file overriding the
                                             # tokenizer's chat template
    max_model_len: int = 2048                # max prompt+generation length
    max_num_seqs: int = 8                    # concurrent batch slots
    prefill_chunk: int = 512                 # chunked-prefill chunk size
    # prefill lengths are bucketed to these sizes to bound XLA compiles
    prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128, 256, 512)
    # decode tokens generated per device dispatch (multi-step decoding):
    # one lax.scan-fused executable emits `decode_window` tokens per slot
    # with a single host sync, amortizing Python dispatch overhead.
    # Sequences that stop mid-window discard the tail (vLLM's
    # num-scheduler-steps tradeoff). 1 = token-at-a-time.
    decode_window: int = 8
    # Continuous batching ACROSS fused windows (docs/engine.md
    # "Continuous batching across windows"): when window_adapt is on,
    # every decode dispatch compacts live rows into the low slots and
    # picks the smallest batch bucket covering them (parked rows stop
    # generating pad token-steps), sizes the window from the live
    # rows' remaining token budgets + an EOS-rate horizon (finished
    # tails stop spanning a long window), and prefers the shortest
    # window bucket while requests wait for admission (prefill — and
    # therefore new-row admission — happens sooner). Bucket sets are
    # power-of-two by default and auto-derived in __post_init__; the
    # executable space is (batch bucket x window bucket x kv bucket),
    # so keep both sets SMALL — warmup pre-compiles the grid so
    # steady-state serving never compiles.
    window_adapt: bool = True
    # power-of-two batch buckets <= max_num_seqs the decode dispatch
    # may shrink to (auto: 1, 2, 4, ..., max_num_seqs). Operators may
    # pass arbitrary ascending sizes (e.g. a fleet whose typical
    # concurrency is 6 adds a 6 bucket) at warmup-compile cost.
    decode_batch_buckets: Tuple[int, ...] = ()
    # window-length buckets <= decode_window the dispatch may shrink
    # to (auto: 1, 2, 4, ..., decode_window)
    decode_window_buckets: Tuple[int, ...] = ()
    # decode windows queued on the device at once (engine.step
    # pipelining). 2 keeps the device saturated in the common case:
    # window N+1 is queued while N runs, and the host processes N's
    # tokens during N+1. Behind a high-RTT tunnel, 3 can buy extra
    # overlap (host round-trips hide behind two device windows);
    # deeper queues add latency to composition changes (admission
    # waits behind every queued window).
    pipeline_depth: int = 2
    # attention is computed over the cache prefix [:kv_len] where kv_len is
    # the smallest bucket covering every live position — decode cost scales
    # with live context, not max_model_len. Auto-derived in __post_init__.
    kv_len_buckets: Tuple[int, ...] = ()
    # paged KV (models/kv.py): pool block size in tokens, and the pool's
    # total KV capacity in tokens (None = worst case max_num_seqs *
    # max_model_len). A bounded pool admits a batch by its LIVE context
    # rather than reserving worst case per slot, with recompute
    # preemption (engine.py _preempt) as the pressure valve — so e.g.
    # batch 32 x 8k-capable slots fit where 8 fully-reserved ones did.
    kv_block_size: int = 64
    kv_pool_tokens: Optional[int] = None
    dtype: str = "bfloat16"
    kv_dtype: str = "bfloat16"
    tensor_parallel_size: int = 1
    # multi-slice passthrough knobs (SURVEY §2.9: the reference exposes
    # PP/EP only as engine passthrough; same here — the chart forwards
    # them, the engine validates). Values > 1 are rejected until the
    # engine grows pipeline/expert sharding over DCN.
    pipeline_parallel_size: int = 1
    expert_parallel_size: int = 1
    # MoE prefill capacity factor override (ops/moe.py): None keeps the
    # model family default (ModelConfig.moe_capacity_factor)
    moe_capacity_factor: Optional[float] = None
    # weight-only int8 (models/quant.py): halves decode weight-streaming
    # HBM traffic; None serves in --dtype precision
    quantization: Optional[str] = None
    # n-gram (prompt-lookup) speculative decoding: draft length per
    # macro-step (0 = off). Eligibility is PER ROW: greedy, unguided,
    # unshaped, no-alternatives rows speculate; other rows single-step
    # inside the same window (engine/runner._decode_spec_impl).
    speculative_ngram_tokens: int = 0
    # Serving meshes that shard the KV pool's block axis (dp > 1) cannot
    # run the pallas paged-attention kernel shard-local; they fall back
    # to the gathered-view jnp path, which re-materializes ~3x the KV
    # traffic the kernel exists to delete. That perf cliff must be
    # CHOSEN: constructing a runner on such a mesh with flash enabled
    # raises unless this flag acknowledges the fallback (then it's one
    # loud warning). tp-only meshes are unaffected.
    dp_gather_attention_ok: bool = False
    seed: int = 0
    checkpoint: Optional[str] = None         # HF checkpoint dir; random if None
    # real embedding model for /v1/embeddings + rerank/score
    # (models/encoder.py): an ENCODER_PRESETS name or a HF BertModel
    # checkpoint dir. None keeps the causal-mean-pool approximation
    # (flagged in responses as embedding_source=causal-mean-pool).
    embedding_model: Optional[str] = None
    # in-HBM prefix cache (engine/block_manager.py): finished sequences'
    # full KV blocks stay in the pool under chain-hash keys; matching
    # prompts attach them by reference — zero copies, zero extra HBM
    # (the reference's --enable-prefix-caching)
    enable_prefix_caching: bool = False
    max_top_k: int = 64                      # static top-k bound for sampler
    # KV tiering (the reference's --kv-transfer-config JSON; see
    # kvcache/connector.py). Keys: kv_role, chunk_size, local_cpu_gb,
    # local_disk_path, local_disk_gb, remote_url.
    kv_transfer_config: Optional[Dict[str, Any]] = None
    # kvplane intra-replica defrag: when a step's admissions hit the
    # fragmented-failure regime, compact the BlockManager free list
    # between fused windows (block_manager.defrag — host-side index
    # reordering, KV bytes never move)
    kvplane_defrag: bool = True
    # Multi-LoRA serving (reference: --enable-lora + LoraAdapter CRD
    # proposal, helm/templates/deployment-vllm-multi.yaml:65-67).
    # name -> .npz path (models/lora.py format), or name -> "random:SEED"
    # for synthetic adapters (tests/demos). Each adapter is served as its
    # own model id next to the base model.
    lora_adapters: Optional[Dict[str, str]] = None
    lora_rank: int = 8
    lora_alpha: float = 16.0
    lora_targets: Tuple[str, ...] = ("q", "v")
    # Overload protection (docs/engine.md "Overload protection"):
    # bounded admission — add_request raises AdmissionRejected (the
    # server answers 503 + Retry-After) once this many sequences are
    # queued un-admitted, instead of growing the waiting deque without
    # bound until every client times out at once. None = unbounded
    # (the pre-overload-protection behavior).
    max_waiting_seqs: Optional[int] = None
    # queue-time cap: a sequence still waiting (never admitted, no
    # output) after this many milliseconds is shed by the scheduler
    # (finish_reason "queue_delay" -> 503 + Retry-After at the server)
    # rather than serviced long after its useful-by time. None = never.
    max_queue_delay_ms: Optional[float] = None
    # Efficiency telemetry (engine/efficiency.py; docs/engine.md
    # "Efficiency telemetry"): the HBM peak bandwidth the MBU gauge
    # normalizes against (GB/s; v5e-class default — the 819 GB/s the
    # measured steady state is quoted against in BASELINE.md), and the
    # bounded ring of per-window breakdowns served on GET /debug/perf.
    hbm_peak_gbps: float = 819.0
    perf_ring_entries: int = 256

    def __post_init__(self):
        if self.dtype not in ("bfloat16", "float32"):
            raise ValueError(
                f"dtype={self.dtype!r} unsupported: TPU serving runs "
                f"bfloat16 (MXU-native) or float32")
        if self.kv_dtype not in ("bfloat16", "float32", "int8"):
            raise ValueError(
                f"kv_dtype={self.kv_dtype!r} unsupported: bfloat16, "
                f"float32, or int8 (quantized cache — halves "
                f"long-context decode HBM traffic, models/kv.py)")
        if self.pipeline_parallel_size != 1:
            raise NotImplementedError(
                "pipeline-parallel SERVING is not implemented: decode "
                "would pipeline one token at a time (pure bubble) "
                "without multi-batch in-flight scheduling. PP exists "
                "for training (parallel/pipeline.py, GPipe over the "
                "'pp' mesh axis); serving scales via tensor_parallel_"
                "size/expert_parallel_size within a slice and "
                "replicaCount across slices")
        if self.expert_parallel_size < 1:
            raise ValueError("expert_parallel_size must be >= 1")
        if not 0 <= self.speculative_ngram_tokens <= 16:
            raise ValueError("speculative_ngram_tokens must be in 0..16")
        if self.speculative_ngram_tokens and self.window_adapt:
            # the speculative executable is the most expensive compile,
            # and warming it across the full (batch x window) grid
            # would multiply warmup by the grid size — while leaving
            # the grid cold trades that for multi-second mid-serving
            # compile stalls at every geometry the adaptive dispatch
            # reaches. Until the spec grid has its own bounded warmup
            # story, speculation pins the full fixed geometry.
            self.window_adapt = False
        if not 1 <= self.pipeline_depth <= 8:
            raise ValueError("pipeline_depth must be in 1..8 (each queued "
                             "window delays admission by one window)")
        if self.quantization not in (None, "int8"):
            raise ValueError(
                f"quantization={self.quantization!r} unsupported: only "
                f"weight-only 'int8' (models/quant.py) is implemented")
        if self.kv_block_size < 8 or self.kv_block_size % 8:
            raise ValueError(
                f"kv_block_size={self.kv_block_size} must be a multiple "
                f"of 8 (TPU minor-dim tiling of the [Bs, D] block panel)")
        # blocks never need to exceed one sequence's worth of positions
        self.kv_block_size = min(
            self.kv_block_size,
            max(8, (self.max_model_len + 7) // 8 * 8))
        if self.kv_pool_tokens is not None and self.kv_pool_tokens <= 0:
            raise ValueError("kv_pool_tokens must be positive")
        if self.max_waiting_seqs is not None and self.max_waiting_seqs < 0:
            raise ValueError("max_waiting_seqs must be >= 0 "
                             "(0 sheds anything that cannot be admitted "
                             "immediately; None = unbounded)")
        if self.max_queue_delay_ms is not None \
                and self.max_queue_delay_ms <= 0:
            raise ValueError("max_queue_delay_ms must be positive")
        if self.hbm_peak_gbps <= 0:
            raise ValueError("hbm_peak_gbps must be positive")
        if self.perf_ring_entries < 1:
            raise ValueError("perf_ring_entries must be >= 1")
        # chunks never exceed prefill_chunk (or the cache), so larger
        # buckets would only waste warmup compiles and executable HBM
        self.prefill_chunk = min(self.prefill_chunk, self.max_model_len)
        buckets = sorted(b for b in self.prefill_buckets
                         if b <= self.prefill_chunk)
        if not buckets or buckets[-1] < self.prefill_chunk:
            buckets.append(self.prefill_chunk)
        self.prefill_buckets = tuple(buckets)
        self.decode_window = max(1, min(self.decode_window,
                                        self.max_model_len))

        def _bucket_set(given, cap: int, what: str) -> Tuple[int, ...]:
            """Validate a user bucket set (ascending, positive,
            <= cap, cap always covered) or derive the power-of-two
            default 1, 2, 4, ..., cap."""
            if given:
                buckets = sorted({int(b) for b in given if 0 < b <= cap})
                if not buckets:
                    raise ValueError(
                        f"{what} has no usable entries in [1, {cap}]: "
                        f"{given}")
            else:
                buckets, b = [], 1
                while b < cap:
                    buckets.append(b)
                    b *= 2
            if not buckets or buckets[-1] < cap:
                buckets.append(cap)
            return tuple(buckets)

        self.decode_batch_buckets = _bucket_set(
            self.decode_batch_buckets, self.max_num_seqs,
            "decode_batch_buckets")
        self.decode_window_buckets = _bucket_set(
            self.decode_window_buckets, self.decode_window,
            "decode_window_buckets")
        if not self.kv_len_buckets:
            # powers of two from 512 (or the cache size if smaller) up to
            # max_model_len: at 32k context that's 7 buckets — bounded
            # compile count, per-step attention cost within 2x of live len
            b, buckets = 512, []
            while b < self.max_model_len:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_model_len)
            self.kv_len_buckets = tuple(
                x for x in buckets if x <= self.max_model_len)
        else:
            # user-supplied buckets: sort, drop over-long ones, and always
            # cover max_model_len — kv_bucket_for must never return a
            # kv_len smaller than a legal live position
            buckets = sorted(b for b in self.kv_len_buckets
                             if 0 < b <= self.max_model_len)
            if not buckets or buckets[-1] < self.max_model_len:
                buckets.append(self.max_model_len)
            self.kv_len_buckets = tuple(buckets)

    @property
    def max_blocks_per_seq(self) -> int:
        """Block-table width MB: blocks covering max_model_len."""
        return -(-self.max_model_len // self.kv_block_size)

    @property
    def num_kv_blocks(self) -> int:
        """Pool size in blocks, INCLUDING trash block 0. Clamped to
        [one full-length sequence, worst case for the whole batch]."""
        worst = self.max_num_seqs * self.max_blocks_per_seq
        if self.kv_pool_tokens is None:
            n = worst
        else:
            n = -(-self.kv_pool_tokens // self.kv_block_size)
        return min(max(n, self.max_blocks_per_seq), worst) + 1

    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if length <= b:
                return b
        return self.prefill_buckets[-1]

    def kv_bucket_for(self, length: int) -> int:
        """Smallest kv-length bucket covering `length` cache positions."""
        for b in self.kv_len_buckets:
            if length <= b:
                return b
        return self.kv_len_buckets[-1]

    def batch_bucket_for(self, rows: int) -> int:
        """Smallest decode batch bucket covering `rows` slots. (The
        window axis has no covering lookup on purpose: the dispatch
        picks the LARGEST window bucket under an expected-dead budget
        — engine._choose_window — not the smallest covering one.)"""
        for b in self.decode_batch_buckets:
            if rows <= b:
                return b
        return self.decode_batch_buckets[-1]
