"""Engine configuration.

Mirrors the knobs the reference exposes as `vllm serve` flags rendered by
Helm (reference: helm/templates/deployment-vllm-multi.yaml:68-93 —
--max-model-len, --dtype, --enable-chunked-prefill, --tensor-parallel-size,
--enable-prefix-caching) as a typed config for the in-repo engine.
"""

import dataclasses
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass
class EngineConfig:
    model: str = "debug-tiny"
    tokenizer: Optional[str] = None          # defaults to model path
    chat_template: Optional[str] = None      # Jinja file overriding the
                                             # tokenizer's chat template
    max_model_len: int = 2048                # max prompt+generation length
    max_num_seqs: int = 8                    # concurrent batch slots
    prefill_chunk: int = 512                 # chunked-prefill chunk size
    # prefill lengths are bucketed to these sizes to bound XLA compiles
    prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128, 256, 512)
    dtype: str = "bfloat16"
    kv_dtype: str = "bfloat16"
    tensor_parallel_size: int = 1
    seed: int = 0
    checkpoint: Optional[str] = None         # HF checkpoint dir; random if None
    enable_prefix_caching: bool = False
    max_top_k: int = 64                      # static top-k bound for sampler
    # KV tiering (the reference's --kv-transfer-config JSON; see
    # kvcache/connector.py). Keys: kv_role, chunk_size, local_cpu_gb,
    # local_disk_path, local_disk_gb, remote_url.
    kv_transfer_config: Optional[Dict[str, Any]] = None

    def __post_init__(self):
        # chunks never exceed prefill_chunk (or the cache), so larger
        # buckets would only waste warmup compiles and executable HBM
        self.prefill_chunk = min(self.prefill_chunk, self.max_model_len)
        buckets = sorted(b for b in self.prefill_buckets
                         if b <= self.prefill_chunk)
        if not buckets or buckets[-1] < self.prefill_chunk:
            buckets.append(self.prefill_chunk)
        self.prefill_buckets = tuple(buckets)

    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if length <= b:
                return b
        return self.prefill_buckets[-1]
