"""PII detection for incoming requests (feature gate ``PIIDetection``).

Capability parity with reference src/vllm_router/experimental/pii/
(types.py:1-53 PIIType enum; analyzers/base.py + analyzers/regex.py
dependency-free analyzer; middleware.py:60-154 request-blocking with
conservative block-on-error). Differences by design:

  * aiohttp middleware (this stack's server) instead of FastAPI;
  * REDACT is implemented, not just declared: matched spans are replaced
    with ``[REDACTED:<type>]`` and the sanitized body is handed to the
    proxy, so requests can proceed PII-free — the reference lists redaction
    as future work (types.py:10);
  * credit-card candidates are Luhn-validated to cut false positives.

The analyzer abstraction allows a model-based backend (the reference wraps
Microsoft Presidio) to slot in later; the regex analyzer is the
dependency-free default, as in the reference.
"""

import asyncio
import enum
import json
import os
import re
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from aiohttp import web

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class PIIAction(enum.Enum):
    BLOCK = "block"
    REDACT = "redact"


class PIIType(enum.Enum):
    EMAIL = "email"
    PHONE = "phone"
    SSN = "ssn"
    CREDIT_CARD = "credit_card"
    IP_ADDRESS = "ip_address"
    API_KEY = "api_key"
    BANK_ACCOUNT = "bank_account"
    IBAN = "iban"
    PASSPORT = "passport"
    DRIVERS_LICENSE = "drivers_license"
    TAX_ID = "tax_id"
    MEDICAL_RECORD = "medical_record"
    MAC_ADDRESS = "mac_address"
    DOB = "date_of_birth"
    PASSWORD = "password"
    SECRET_URL_CRED = "url_credential"
    # entity types only a model can find (NERPIIAnalyzer; the regex
    # analyzer never produces them — names/places have no pattern)
    PERSON = "person"
    LOCATION = "location"
    ORGANIZATION = "organization"


@dataclass
class PIIMatch:
    pii_type: PIIType
    start: int
    end: int
    text: str


@dataclass
class PIIAnalysisResult:
    detected: bool = False
    types: Set[PIIType] = field(default_factory=set)
    matches: List[PIIMatch] = field(default_factory=list)


class PIIAnalyzer(ABC):
    """Analyzer abstraction (reference analyzers/base.py:1-65)."""

    @abstractmethod
    def analyze(self, text: str,
                types: Optional[Set[PIIType]] = None) -> PIIAnalysisResult:
        ...


def _iban_ok(candidate: str) -> bool:
    """ISO 7064 mod-97 check (same false-positive cut as Luhn for cards)."""
    s = candidate[4:] + candidate[:4]
    num = "".join(str(int(c, 36)) for c in s)
    return int(num) % 97 == 1


def _luhn_ok(digits: str) -> bool:
    total, alt = 0, False
    for ch in reversed(digits):
        d = ord(ch) - 48
        if alt:
            d *= 2
            if d > 9:
                d -= 9
        total += d
        alt = not alt
    return total % 10 == 0


def _keyword_id_pattern(keyword: str, lo: int, hi: int) -> str:
    """Keyword-prefixed identifier pattern with two alternatives:
    (a) an explicit separator (:, =, #, or the words number/no) admits an
        any-case token, so "passport no: ab1234567" is caught;
    (b) bare whitespace admits only an UPPERCASE token, so prose like
        "my passport b4monday trip" or "dl 100mbps" never matches.
    Both require at least one digit in the token."""
    return (
        rf"\b(?i:{keyword})\s*(?:(?i:number|no)|#|:|=)+\s*[:=]?\s*"
        rf"(?=[A-Za-z0-9]*\d)[A-Za-z0-9]{{{lo},{hi}}}\b"
        rf"|\b(?i:{keyword})\s+(?=[A-Z0-9]*\d)[A-Z0-9]{{{lo},{hi}}}\b"
    )


class RegexPIIAnalyzer(PIIAnalyzer):
    """Dependency-free pattern analyzer (reference analyzers/regex.py)."""

    PATTERNS: Dict[PIIType, str] = {
        PIIType.EMAIL:
            r"\b[A-Za-z0-9._%+-]+@[A-Za-z0-9.-]+\.[A-Za-z]{2,}\b",
        # classic NANP-style shapes only; the trailing lookahead rejects a
        # fourth digit group so card-number-like runs never match
        PIIType.PHONE:
            r"(?<![\w.)-])(?:\+\d{1,2}[ .-]?)?(?:\(\d{3}\)[ .-]?"
            r"|\d{3}[ .-])\d{3}[ .-]\d{4}(?![ .-]?\d)",
        PIIType.SSN:
            r"\b\d{3}-\d{2}-\d{4}\b",
        PIIType.CREDIT_CARD:
            r"\b\d(?:[ -]?\d){12,18}\b",   # 13-19 digits, ends on a digit
        PIIType.IP_ADDRESS:
            r"\b(?:(?:25[0-5]|2[0-4]\d|1?\d?\d)\.){3}"
            r"(?:25[0-5]|2[0-4]\d|1?\d?\d)\b"
            r"|\b(?:[A-Fa-f0-9]{1,4}:){7}[A-Fa-f0-9]{1,4}\b",
        PIIType.API_KEY:
            r"\b(?:sk|pk|rk)-[A-Za-z0-9_-]{16,}\b"
            r"|\bAKIA[0-9A-Z]{16}\b"
            r"|\bgh[pousr]_[A-Za-z0-9]{20,}\b"
            r"|\bxox[baprs]-[A-Za-z0-9-]{10,}\b",
        PIIType.IBAN:
            r"\b[A-Z]{2}\d{2}[A-Z0-9]{11,30}\b",
        PIIType.BANK_ACCOUNT:
            r"(?i)\b(?:account|acct)\.?\s*(?:number|no|#)?\s*[:=]?\s*"
            r"\d{8,17}\b",
        PIIType.PASSPORT: _keyword_id_pattern("passport", 6, 9),
        PIIType.DRIVERS_LICENSE:
            _keyword_id_pattern(r"driver'?s?\s+licen[cs]e|dl", 5, 13),
        PIIType.TAX_ID:
            r"\b\d{2}-\d{7}\b",
        PIIType.MEDICAL_RECORD:
            _keyword_id_pattern(r"mrn|medical\s+record", 6, 12),
        PIIType.MAC_ADDRESS:
            r"\b(?:[0-9A-Fa-f]{2}[:-]){5}[0-9A-Fa-f]{2}\b",
        PIIType.DOB:
            r"(?i)\b(?:dob|date\s+of\s+birth|born(?:\s+on)?)\s*[:=]?\s*"
            r"\d{1,4}[/-]\d{1,2}[/-]\d{1,4}\b",
        PIIType.PASSWORD:
            r"(?i)\b(?:password|passwd|pwd)\s*[:=]\s*\S{4,}",
        PIIType.SECRET_URL_CRED:
            r"\b[a-z][a-z0-9+.-]*://[^/\s:@]+:[^/\s:@]+@",
    }

    def __init__(self):
        self._compiled = {t: re.compile(p) for t, p in self.PATTERNS.items()}

    def analyze(self, text: str,
                types: Optional[Set[PIIType]] = None) -> PIIAnalysisResult:
        result = PIIAnalysisResult()
        for pii_type, pattern in self._compiled.items():
            if types is not None and pii_type not in types:
                continue
            for m in pattern.finditer(text):
                if pii_type == PIIType.CREDIT_CARD:
                    digits = re.sub(r"\D", "", m.group())
                    if not (13 <= len(digits) <= 19 and _luhn_ok(digits)):
                        continue
                if pii_type == PIIType.IBAN and not _iban_ok(m.group()):
                    continue
                result.detected = True
                result.types.add(pii_type)
                result.matches.append(PIIMatch(pii_type, m.start(), m.end(),
                                               m.group()))
        return result


class NERPIIAnalyzer(PIIAnalyzer):
    """Model-based analyzer: a BERT token-classification checkpoint
    (HF ``BertForTokenClassification`` layout) run through this repo's
    JAX encoder (models/encoder.py encode_hidden) with the classifier
    head applied on top — the TPU-native counterpart of the reference's
    Presidio/spaCy analyzer
    (reference src/vllm_router/experimental/pii/analyzers/presidio.py:1-172),
    finding entities regex cannot (names, places, organizations).

    Spec form: ``ner:<checkpoint-dir>``. The dir must hold config.json
    with ``id2label`` (BIO or bare labels: PER/PERSON -> PERSON,
    LOC/GPE -> LOCATION, ORG -> ORGANIZATION; O and unmapped labels are
    ignored), weights (safetensors or .bin, ``bert.*`` + ``classifier.*``),
    and a fast tokenizer (char offsets come from its offset mapping).
    Construction failures RAISE — the operator explicitly configured a
    model; silently scanning with regex instead would be a silent
    security downgrade. analyze() runs on middleware threads; jit keeps
    repeat calls at one host dispatch per (batched) length bucket."""

    _LABEL_MAP = {
        "PER": PIIType.PERSON, "PERSON": PIIType.PERSON,
        "LOC": PIIType.LOCATION, "LOCATION": PIIType.LOCATION,
        "GPE": PIIType.LOCATION,
        "ORG": PIIType.ORGANIZATION,
        "ORGANIZATION": PIIType.ORGANIZATION,
    }

    def __init__(self, path: str):
        import json

        import jax
        import jax.numpy as jnp

        from production_stack_tpu.models import encoder as enc
        from production_stack_tpu.models import hf_loader

        with open(os.path.join(path, "config.json")) as f:
            hf_cfg = json.load(f)
        if "id2label" not in hf_cfg:
            raise ValueError(
                f"{path}/config.json has no id2label — not a token-"
                f"classification checkpoint")
        self._id2label = {int(k): v for k, v in hf_cfg["id2label"].items()}
        self._cfg = enc.config_from_hf_json(hf_cfg, name=f"ner:{path}")
        import numpy as np

        sd = hf_loader.read_state_dict(path)
        self._params = enc.params_from_state_dict(self._cfg, sd)

        def np_(t):
            return t.detach().cpu().numpy() if hasattr(t, "detach") \
                else np.asarray(t)
        self._head_w = jnp.asarray(np_(sd["classifier.weight"]).T,
                                   jnp.float32)        # [H, num_labels]
        self._head_b = jnp.asarray(np_(sd["classifier.bias"]),
                                   jnp.float32)
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(path)
        if not getattr(self._tok, "is_fast", False):
            raise ValueError(
                f"tokenizer at {path} is not a fast tokenizer; the NER "
                f"analyzer needs offset mappings for span extraction")

        def _logits(tokens, lengths):
            h = enc.encode_hidden(self._params, self._cfg, tokens,
                                  lengths)
            return h.astype(jnp.float32) @ self._head_w + self._head_b

        self._fn = jax.jit(_logits)

    def analyze(self, text: str,
                types: Optional[Set[PIIType]] = None) -> PIIAnalysisResult:
        import numpy as _np
        result = PIIAnalysisResult()
        enc_out = self._tok(
            text, return_offsets_mapping=True, truncation=True,
            max_length=self._cfg.max_position_embeddings,
            return_attention_mask=False)
        ids = enc_out["input_ids"]
        offsets = enc_out["offset_mapping"]
        if not ids:
            return result
        # pad to power-of-two length buckets: request lengths vary
        # almost per request, and an exact-shape jit would recompile
        # the encoder (seconds, on a middleware thread) for every new
        # length and grow the executable cache without bound. Padding
        # is masked out of attention by `lengths` (encode_hidden) and
        # never enters the offsets loop below.
        n = len(ids)
        bucket = min(max(16, 1 << (n - 1).bit_length()),
                     self._cfg.max_position_embeddings)
        toks = _np.zeros((1, bucket), _np.int32)
        toks[0, :n] = ids
        logits = _np.asarray(self._fn(
            toks, _np.asarray([n], _np.int32)))[0]          # [T, L]
        labels = logits.argmax(-1)
        # BIO decode into char spans: I- (or bare-label) tokens extend
        # the running entity; a B- token always STARTS a new one, so
        # adjacent same-type entities ("alice smith bob jones" as
        # B-PER I-PER B-PER I-PER) stay two matches. Special tokens
        # ([CLS]/[SEP]) carry (0, 0) offsets and break merges.
        cur_type, cur_start, cur_end = None, 0, 0

        def flush():
            if cur_type is not None and cur_end > cur_start:
                if types is None or cur_type in types:
                    result.detected = True
                    result.types.add(cur_type)
                    result.matches.append(PIIMatch(
                        cur_type, cur_start, cur_end,
                        text[cur_start:cur_end]))

        for i, (a, b) in enumerate(offsets):
            label = self._id2label.get(int(labels[i]), "O")
            kind = self._LABEL_MAP.get(label.split("-", 1)[-1])
            if a == b or kind is None:      # special/pad token or O
                flush()
                cur_type = None
                continue
            begins = label.startswith("B-")
            if not begins and kind is cur_type and a <= cur_end + 1:
                cur_end = b                  # extend (wordpiece / space)
            else:
                flush()
                cur_type, cur_start, cur_end = kind, a, b
        flush()
        return result


def make_analyzer(spec: str = "regex") -> PIIAnalyzer:
    if spec == "regex":
        return RegexPIIAnalyzer()
    if spec.startswith("ner:"):
        return NERPIIAnalyzer(spec[len("ner:"):])
    raise ValueError(f"unknown PII analyzer {spec!r} (available: regex, "
                     f"ner:<token-classification checkpoint dir>)")


# ---------------------------------------------------------------- config


@dataclass
class PIIConfig:
    """Reference config surface (pii/config.py): analyzer, action, types."""
    analyzer: str = "regex"
    action: PIIAction = PIIAction.BLOCK
    types: Optional[Set[PIIType]] = None     # None = all

    @classmethod
    def from_args(cls, analyzer: str, action: str,
                  types_csv: Optional[str]) -> "PIIConfig":
        types = None
        if types_csv:
            types = {PIIType(t.strip()) for t in types_csv.split(",")
                     if t.strip()}
        return cls(analyzer=analyzer, action=PIIAction(action), types=types)


# ---------------------------------------------------------------- middleware


def _extract_texts(body: dict) -> List[Tuple[str, object]]:
    """(text, setter-path) pairs from the OpenAI body fields that carry
    user text: chat message content (string or multimodal content-part
    list), `prompt`, `input`."""
    out = []
    messages = body.get("messages")
    if isinstance(messages, list):
        for i, m in enumerate(messages):
            if not isinstance(m, dict):
                continue
            content = m.get("content")
            if isinstance(content, str):
                out.append((content, ("messages", i)))
            elif isinstance(content, list):   # multimodal content parts
                for j, part in enumerate(content):
                    if isinstance(part, dict) and \
                            isinstance(part.get("text"), str):
                        out.append((part["text"], ("messages", i, j)))
    for key in ("prompt", "input"):
        val = body.get(key)
        if isinstance(val, str):
            out.append((val, (key,)))
        elif isinstance(val, list):
            for i, item in enumerate(val):
                if isinstance(item, str):
                    out.append((item, (key, i)))
    return out


def _apply_redaction(body: dict, path, redacted_text: str) -> None:
    if path[0] == "messages":
        if len(path) == 3:   # multimodal content part
            body["messages"][path[1]]["content"][path[2]]["text"] = \
                redacted_text
        else:
            body["messages"][path[1]]["content"] = redacted_text
    elif len(path) == 1:
        body[path[0]] = redacted_text
    else:
        body[path[0]][path[1]] = redacted_text


def redact(text: str, matches: List[PIIMatch]) -> str:
    """Replace matched spans with [REDACTED:<type>] tags.

    Overlapping matches from different patterns (e.g. a card number inside
    an 'account number: …' span) are merged first — offsets were computed
    on the original string, so replacements must never nest."""
    merged: List[PIIMatch] = []
    for m in sorted(matches, key=lambda m: (m.start, -m.end)):
        if merged and m.start < merged[-1].end:
            if m.end > merged[-1].end:   # extend the covering span
                prev = merged[-1]
                merged[-1] = PIIMatch(prev.pii_type, prev.start, m.end,
                                      text[prev.start:m.end])
            continue
        merged.append(m)
    for m in reversed(merged):
        text = (text[:m.start] + f"[REDACTED:{m.pii_type.value}]"
                + text[m.end:])
    return text


PII_SCAN_PATHS = ("/v1/chat/completions", "/v1/completions",
                  "/v1/embeddings")


class PIIMiddleware:
    """Scans request bodies; blocks (400) or redacts before proxying.

    Conservative on errors: an analyzer failure blocks the request rather
    than letting unscanned text through (reference middleware.py:99-103).
    The redacted body is stashed on the request for the proxy to forward
    (aiohttp requests are read-once, so the original body stays intact
    for non-scanned paths).
    """

    def __init__(self, config: PIIConfig):
        self.config = config
        self.analyzer = make_analyzer(config.analyzer)
        self.scanned = 0
        self.blocked = 0
        self.redacted = 0

    def _scan(self, body: dict):
        """Analyze (and under REDACT, mutate) the body. Pure CPU work —
        called via run_in_executor so multi-MB prompts never stall the
        event loop (same treatment as the semantic cache's embed)."""
        detected_types: Set[PIIType] = set()
        mutated = False
        for text, path in _extract_texts(body):
            result = self.analyzer.analyze(text, self.config.types)
            if not result.detected:
                continue
            detected_types |= result.types
            if self.config.action == PIIAction.REDACT:
                _apply_redaction(body, path, redact(text, result.matches))
                mutated = True
        return detected_types, mutated

    @web.middleware
    async def middleware(self, request: web.Request, handler):
        if request.method != "POST" or \
                request.path not in PII_SCAN_PATHS:
            return await handler(request)
        try:
            raw = await request.read()
            try:
                body = json.loads(raw) if raw else {}
            except (json.JSONDecodeError, UnicodeDecodeError):
                # malformed client JSON is not an analyzer failure: let
                # the proxy produce its invalid_request_error
                return await handler(request)
            if not isinstance(body, dict):
                return await handler(request)
            self.scanned += 1
            detected_types, mutated = \
                await asyncio.get_running_loop().run_in_executor(
                    None, self._scan, body)
            if detected_types and self.config.action == PIIAction.BLOCK:
                self.blocked += 1
                logger.warning("blocked request with PII: %s",
                               sorted(t.value for t in detected_types))
                return web.json_response(
                    {"error": {
                        "message": "request blocked: detected PII of "
                                   "types "
                                   f"{sorted(t.value for t in detected_types)}",
                        "type": "invalid_request_error",
                        "code": "pii_detected"}}, status=400)
            if mutated:
                self.redacted += 1
                request["pii_sanitized_raw"] = json.dumps(body).encode()
        except web.HTTPException:
            raise
        except Exception as e:
            # conservative: failure to scan blocks the request
            logger.error("PII analysis failed; blocking request: %s", e)
            self.blocked += 1
            return web.json_response(
                {"error": {"message": "PII analysis failed",
                           "type": "server_error",
                           "code": "pii_analysis_error"}}, status=400)
        return await handler(request)
