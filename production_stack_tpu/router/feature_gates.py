"""Kubernetes-style feature gates for experimental router features.

Capability parity with reference src/vllm_router/experimental/
feature_gates.py:1-141 (stages ALPHA/BETA/GA, --feature-gates=Name=true
CLI + env var), without the reference's duplicated-initializer quirk.
"""

import enum
import os
from typing import Dict, Optional

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

ENV_VAR = "PSTPU_FEATURE_GATES"


class FeatureStage(enum.Enum):
    ALPHA = "alpha"       # off by default
    BETA = "beta"         # on by default
    GA = "ga"             # always on


KNOWN_FEATURES: Dict[str, FeatureStage] = {
    "SemanticCache": FeatureStage.ALPHA,
    "PIIDetection": FeatureStage.ALPHA,
    "KVAwareRouting": FeatureStage.BETA,
}


class FeatureGates:
    def __init__(self, spec: Optional[str] = None):
        self._enabled: Dict[str, bool] = {
            name: stage != FeatureStage.ALPHA
            for name, stage in KNOWN_FEATURES.items()}
        spec = spec if spec is not None else os.environ.get(ENV_VAR, "")
        for item in (spec or "").split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(
                    f"feature gate {item!r} must be Name=true|false")
            name, value = item.split("=", 1)
            name = name.strip()
            if name not in KNOWN_FEATURES:
                raise ValueError(f"unknown feature gate {name!r}; known: "
                                 f"{sorted(KNOWN_FEATURES)}")
            if KNOWN_FEATURES[name] == FeatureStage.GA and \
                    value.lower() == "false":
                raise ValueError(f"GA feature {name} cannot be disabled")
            self._enabled[name] = value.strip().lower() == "true"
        for name, on in sorted(self._enabled.items()):
            if on:
                logger.info("feature gate %s enabled (%s)", name,
                            KNOWN_FEATURES[name].value)

    def enabled(self, name: str) -> bool:
        return self._enabled.get(name, False)

    def as_dict(self) -> Dict[str, bool]:
        return dict(self._enabled)
