"""Router-side Prometheus gauges, refreshed from the stats plane on scrape.

Gauge names match the reference's router metrics surface (reference:
src/vllm_router/services/metrics_service/prometheus_gauge.py —
vllm:current_qps, vllm:avg_latency, vllm:avg_itl, vllm:num_prefill_requests,
vllm:num_decoding_requests, vllm:num_requests_running,
vllm:healthy_pods_total) so existing Grafana dashboards keep working.
"""

from prometheus_client import CollectorRegistry, Gauge, generate_latest


class RouterMetrics:
    def __init__(self):
        self.registry = CollectorRegistry()

        def gauge(name, doc):
            return Gauge(name, doc, ["server"], registry=self.registry)

        self.current_qps = gauge("vllm:current_qps",
                                 "Router-observed QPS per engine")
        self.avg_latency = gauge("vllm:avg_latency",
                                 "Mean e2e latency (window)")
        self.avg_ttft = gauge("vllm:avg_ttft", "Mean TTFT (window)")
        self.avg_itl = gauge("vllm:avg_itl", "Mean inter-token latency")
        self.num_prefill = gauge("vllm:num_prefill_requests",
                                 "Requests awaiting first byte")
        self.num_decoding = gauge("vllm:num_decoding_requests",
                                  "Requests streaming")
        self.num_running = gauge("vllm:num_requests_running",
                                 "In-flight requests via router")
        self.healthy_pods = Gauge("vllm:healthy_pods_total",
                                  "Routable engine endpoints",
                                  registry=self.registry)
        # semantic-cache surface (reference:
        # semantic_cache_integration.py:25-44 gauge names)
        def plain(name, doc):
            return Gauge(name, doc, registry=self.registry)
        self.semantic_hits = plain("vllm:semantic_cache_hits",
                                   "Semantic cache hits")
        self.semantic_misses = plain("vllm:semantic_cache_misses",
                                     "Semantic cache misses")
        self.semantic_hit_ratio = plain("vllm:semantic_cache_hit_ratio",
                                        "Semantic cache hit ratio")
        self.semantic_size = plain("vllm:semantic_cache_size",
                                   "Semantic cache entries")
        self.semantic_latency = plain("vllm:semantic_cache_latency",
                                      "Last semantic cache lookup seconds")
        # PII surface (reference: pii/middleware.py:20-39 counters)
        self.pii_scanned = plain("vllm:pii_requests_scanned",
                                 "Requests scanned for PII")
        self.pii_blocked = plain("vllm:pii_requests_blocked",
                                 "Requests blocked for PII")
        self.pii_redacted = plain("vllm:pii_requests_redacted",
                                  "Requests redacted for PII")
        self._seen_servers = set()

    def refresh(self, request_stats: dict, num_endpoints: int) -> None:
        # drop label series for engines that left the fleet so /metrics
        # never exports frozen stats for dead pods
        for url in self._seen_servers - set(request_stats):
            for g in (self.current_qps, self.avg_latency, self.avg_ttft,
                      self.avg_itl, self.num_prefill, self.num_decoding,
                      self.num_running):
                try:
                    g.remove(url)
                except KeyError:
                    pass
        self._seen_servers = set(request_stats)
        for url, st in request_stats.items():
            self.current_qps.labels(server=url).set(st.qps)
            self.avg_latency.labels(server=url).set(st.latency)
            self.avg_ttft.labels(server=url).set(st.ttft)
            self.avg_itl.labels(server=url).set(st.itl)
            self.num_prefill.labels(server=url).set(st.in_prefill)
            self.num_decoding.labels(server=url).set(st.in_decoding)
            self.num_running.labels(server=url).set(st.in_flight)
        self.healthy_pods.set(num_endpoints)

    def refresh_semantic_cache(self, cache) -> None:
        self.semantic_hits.set(cache.hits)
        self.semantic_misses.set(cache.misses)
        self.semantic_hit_ratio.set(cache.hit_ratio)
        self.semantic_size.set(len(cache))
        self.semantic_latency.set(cache.last_lookup_s)

    def refresh_pii(self, middleware) -> None:
        self.pii_scanned.set(middleware.scanned)
        self.pii_blocked.set(middleware.blocked)
        self.pii_redacted.set(middleware.redacted)

    def render(self) -> bytes:
        return generate_latest(self.registry)
