"""Router-side Prometheus gauges, refreshed from the stats plane on scrape.

Gauge names match the reference's router metrics surface (reference:
src/vllm_router/services/metrics_service/prometheus_gauge.py —
vllm:current_qps, vllm:avg_latency, vllm:avg_itl, vllm:num_prefill_requests,
vllm:num_decoding_requests, vllm:num_requests_running,
vllm:healthy_pods_total) so existing Grafana dashboards keep working.
"""

from prometheus_client import CollectorRegistry, Gauge, generate_latest

from production_stack_tpu.tracing import (PhaseHistogramCollector,
                                          PhaseHistograms)


class RouterMetrics:
    def __init__(self):
        self.registry = CollectorRegistry()
        # phase-latency attribution (tracing.py): one histogram series
        # per (phase, server). Router-local phases (admission, routing,
        # backoff, prefill_dispatch) carry server=""; backend-attributed
        # phases (backend_ttfb, relay) carry the endpoint URL — and are
        # EVICTED with the endpoint (evict_phase_servers) so a dynamic-
        # config swap never leaves frozen per-endpoint series behind
        # (the r8 refresh_resilience precedent). Fed at trace seal time
        # (proxy.py), rendered at scrape by the custom collector —
        # never a prometheus object on the relay hot loop.
        self.request_phases = PhaseHistograms(("phase", "server"))
        self.registry.register(PhaseHistogramCollector(
            "tpu:request_phase_seconds",
            "Router-side request phase durations (docs/observability.md "
            "'Tracing' phase glossary)", self.request_phases))

        def gauge(name, doc):
            return Gauge(name, doc, ["server"], registry=self.registry)

        self.current_qps = gauge("vllm:current_qps",
                                 "Router-observed QPS per engine")
        self.avg_latency = gauge("vllm:avg_latency",
                                 "Mean e2e latency (window)")
        self.avg_ttft = gauge("vllm:avg_ttft", "Mean TTFT (window)")
        self.avg_itl = gauge("vllm:avg_itl", "Mean inter-token latency")
        self.num_prefill = gauge("vllm:num_prefill_requests",
                                 "Requests awaiting first byte")
        self.num_decoding = gauge("vllm:num_decoding_requests",
                                  "Requests streaming")
        self.num_running = gauge("vllm:num_requests_running",
                                 "In-flight requests via router")
        self.healthy_pods = Gauge("vllm:healthy_pods_total",
                                  "Healthy (breaker-closed, non-"
                                  "draining) engine endpoints",
                                  registry=self.registry)
        # resilience surface: per-endpoint upstream failure/retry
        # outcomes (previously invisible — a relayed backend 5xx looked
        # identical to a healthy response in every exported series) and
        # breaker state
        self.upstream_failures = Gauge(
            "vllm:upstream_failures_total",
            "Upstream failures observed per endpoint by kind "
            "(connect, timeout, http_5xx, mid_stream, probe)",
            ["server", "kind"], registry=self.registry)
        self.upstream_retries = Gauge(
            "vllm:upstream_retries_total",
            "Pre-stream failovers routed away from this endpoint",
            ["server"], registry=self.registry)
        self.relayed_5xx = Gauge(
            "vllm:relayed_5xx_total",
            "Backend 5xx responses relayed to clients (retries "
            "exhausted)", ["server"], registry=self.registry)
        self.breaker_state = Gauge(
            "vllm:breaker_state",
            "Circuit state per endpoint (0 closed, 1 half-open, 2 open)",
            ["server"], registry=self.registry)
        self.breaker_opens = Gauge(
            "vllm:breaker_opens_total",
            "Circuit-breaker open transitions", registry=self.registry)
        # overload-protection surface: requests the ROUTER itself shed
        # ("admission" = --max-inflight gate -> 429; "endpoint_cap" =
        # every candidate at its concurrency cap -> 503). Upstream
        # sheds the router observed live in
        # vllm:upstream_failures_total{kind="shed"|"deadline"}.
        self.router_sheds = Gauge(
            "vllm:router_sheds_total",
            "Requests shed by the router by scope",
            ["scope"], registry=self.registry)
        # semantic-cache surface (reference:
        # semantic_cache_integration.py:25-44 gauge names)
        def plain(name, doc):
            return Gauge(name, doc, registry=self.registry)
        self.semantic_hits = plain("vllm:semantic_cache_hits",
                                   "Semantic cache hits")
        self.semantic_misses = plain("vllm:semantic_cache_misses",
                                     "Semantic cache misses")
        self.semantic_hit_ratio = plain("vllm:semantic_cache_hit_ratio",
                                        "Semantic cache hit ratio")
        self.semantic_size = plain("vllm:semantic_cache_size",
                                   "Semantic cache entries")
        self.semantic_latency = plain("vllm:semantic_cache_latency",
                                      "Last semantic cache lookup seconds")
        # cache-aware prefix routing surface (routing.PrefixAwareRouter):
        # how often scoring found a warm endpoint vs fell back to hash
        # affinity on a cold prefix. Real counters (exposition name
        # gains the _total suffix) fed by delta-sync in refresh_routing
        # so a dynamic-config router swap never reads as an unflagged
        # gauge reset.
        from prometheus_client import Counter
        self.prefix_warm_routes = Counter(
            "tpu:router_prefix_warm_routes",
            "Routing decisions scored onto a warm endpoint "
            "(expected prefix-hit bytes > 0)", registry=self.registry)
        self.prefix_cold_routes = Counter(
            "tpu:router_prefix_cold_routes",
            "Routing decisions that fell back to hash affinity "
            "(cold prefix)", registry=self.registry)
        self._prefix_last = {"warm": 0, "cold": 0}
        # multi-router control plane (router/shared_state.py + qos.py):
        # peer liveness, per-tier QoS sheds/preemptions, and affinity
        # moves. Counters are delta-synced (the r12 disagg convention)
        # so a dynamic-config router swap — which resets the policy
        # object's affinity totals — never reads as a counter reset.
        self.router_peers = Gauge(
            "tpu:router_peers",
            "Peer router replicas by gossip liveness state "
            "(live, stale, unreachable)",
            ["state"], registry=self.registry)
        self.qos_sheds = Counter(
            "tpu:router_qos_sheds",
            "Requests shed by the QoS admission layer per priority "
            "tier (graduated pressure gate, token bucket, preemption)",
            ["tier"], registry=self.registry)
        self.qos_preemptions = Counter(
            "tpu:router_qos_preemptions",
            "In-flight background dispatches preempted by higher-"
            "priority arrivals, per victim tier",
            ["tier"], registry=self.registry)
        self.qos_inflight = Gauge(
            "tpu:router_qos_inflight",
            "Currently proxied requests per priority tier",
            ["tier"], registry=self.registry)
        # named-pools surface (router/pools.py): per-pool routed
        # requests / endpoint counts / in-place swaps, unknown-model
        # 404s, and per-(tenant, tier) sheds from the nested tenant
        # buckets (qos.py). Counters delta-sync off the PoolManager's /
        # QosPolicy's plain-int totals, which survive pool swaps by
        # construction; the tenant label set is bounded by the policy's
        # LRU (max_tenants).
        self.pool_requests = Counter(
            "tpu:router_pool_requests",
            "Requests routed per named pool (model -> pool resolution)",
            ["pool"], registry=self.registry)
        self.pool_endpoints = Gauge(
            "tpu:router_pool_endpoints",
            "Configured endpoints per named pool",
            ["pool"], registry=self.registry)
        self.pool_swaps = Counter(
            "tpu:router_pool_swaps",
            "In-place pool spec swaps applied (membership or policy)",
            ["pool"], registry=self.registry)
        self.pool_unknown_models = Counter(
            "tpu:router_pool_unknown_models",
            "Requests 404ed because no pool serves the named model",
            registry=self.registry)
        self.tenant_sheds = Counter(
            "tpu:router_tenant_sheds",
            "Requests shed by a per-tenant token bucket nested in a "
            "QoS tier (noisy-neighbor containment, "
            "docs/multitenancy.md)",
            ["tenant", "tier"], registry=self.registry)
        self._pool_req_last: dict = {}
        self._pool_swap_last: dict = {}
        self._pool_unknown_last = 0
        self._tenant_shed_last: dict = {}
        self._seen_pools = set()
        self.affinity_moves = Counter(
            "tpu:router_affinity_moves",
            "Session/prefix keys routed away from their previous home "
            "endpoint, by reason (endpoint_lost = home unroutable/"
            "removed; rebalance = policy drift — across N routers, "
            "the split-brain signal)",
            ["reason"], registry=self.registry)
        self._qos_shed_last: dict = {}
        self._qos_preempt_last: dict = {}
        self._affinity_last: dict = {}
        # disaggregated prefill surface (router/disagg.py): prefill
        # dispatches/failures, per-reason fallbacks to aggregated
        # serving, breaker opens, and decode-selection outcomes. Real
        # counters, delta-synced in refresh_disagg so a dynamic-config
        # pool swap (which may replace the orchestrator) never reads as
        # an unflagged counter reset.
        self.disagg_prefills = Counter(
            "tpu:router_disagg_prefills",
            "Prefill passes dispatched to the prefill pool",
            registry=self.registry)
        self.disagg_prefill_errors = Counter(
            "tpu:router_disagg_prefill_errors",
            "Prefill passes that failed (decode recomputed)",
            registry=self.registry)
        self.disagg_fallbacks = Counter(
            "tpu:router_disagg_fallbacks",
            "Requests degraded to aggregated serving by reason "
            "(no_pool, breaker_open, shed, http_error, timeout, "
            "connect)", ["reason"], registry=self.registry)
        self.disagg_breaker_opens = Counter(
            "tpu:router_disagg_breaker_opens",
            "Prefill-backend circuit-breaker open transitions",
            registry=self.registry)
        self.disagg_headstart_elapsed = Counter(
            "tpu:router_disagg_headstart_elapsed",
            "Decode routed while the prefill pass was still running",
            registry=self.registry)
        self.disagg_decode_cost_routes = Counter(
            "tpu:router_disagg_decode_cost_routes",
            "Decode selections made by the transfer-cost model",
            registry=self.registry)
        self.disagg_decode_abstains = Counter(
            "tpu:router_disagg_decode_abstains",
            "Decode selections deferred to the routing policy "
            "(cold prefix)", registry=self.registry)
        self._disagg_last: dict = {}
        # SLO surface (production_stack_tpu/slo.py): burn rates per
        # (slo, window) — the series the generated Prometheus rules in
        # observability/alert-rules.yaml alert over, so in-process and
        # cluster alerting read the same accounting — plus the alert
        # state machine and firing transitions (delta-synced real
        # counter). Refreshed at scrape (refresh_slo), like every
        # other family.
        self.slo_burn = Gauge(
            "tpu:slo_burn_rate",
            "Error-budget burn rate per SLO and window (bad fraction "
            "over the window / error budget; docs/observability.md "
            "'SLOs and alerting')",
            ["slo", "window"], registry=self.registry)
        self.slo_events = Gauge(
            "tpu:slo_window_events",
            "Good+bad events per SLO window — the volume floor the "
            "generated alert rules gate on, mirroring the in-process "
            "min_events gate",
            ["slo", "window"], registry=self.registry)
        self.alert_state = Gauge(
            "tpu:alert_state",
            "Burn-rate alert state (0 inactive/resolved, 1 pending, "
            "2 firing; diagnosis steps in docs/runbooks.md)",
            ["alert"], registry=self.registry)
        self.alerts_fired = Counter(
            "tpu:alerts_fired",
            "Alert firing transitions (pending -> firing)",
            ["alert"], registry=self.registry)
        self._alerts_fired_last: dict = {}
        # PII surface (reference: pii/middleware.py:20-39 counters)
        self.pii_scanned = plain("vllm:pii_requests_scanned",
                                 "Requests scanned for PII")
        self.pii_blocked = plain("vllm:pii_requests_blocked",
                                 "Requests blocked for PII")
        self.pii_redacted = plain("vllm:pii_requests_redacted",
                                  "Requests redacted for PII")
        self._seen_servers = set()
        self._seen_failures = set()       # (url, kind) label pairs
        self._seen_retry_servers = set()
        self._seen_relayed_servers = set()
        self._seen_breaker_servers = set()

    def refresh(self, request_stats: dict, num_healthy: int) -> None:
        # drop label series for engines that left the fleet so /metrics
        # never exports frozen stats for dead pods
        for url in self._seen_servers - set(request_stats):
            for g in (self.current_qps, self.avg_latency, self.avg_ttft,
                      self.avg_itl, self.num_prefill, self.num_decoding,
                      self.num_running):
                try:
                    g.remove(url)
                except KeyError:
                    pass
        self._seen_servers = set(request_stats)
        for url, st in request_stats.items():
            self.current_qps.labels(server=url).set(st.qps)
            self.avg_latency.labels(server=url).set(st.latency)
            self.avg_ttft.labels(server=url).set(st.ttft)
            self.avg_itl.labels(server=url).set(st.itl)
            self.num_prefill.labels(server=url).set(st.in_prefill)
            self.num_decoding.labels(server=url).set(st.in_decoding)
            self.num_running.labels(server=url).set(st.in_flight)
        # healthy = breaker-closed and not draining (callers compute it
        # from the HealthTracker), NOT raw discovery membership
        self.healthy_pods.set(num_healthy)

    def refresh_resilience(self, tracker) -> None:
        """Export the HealthTracker's counters + breaker states,
        dropping label series for endpoints the tracker evicted so
        departed pods never export frozen resilience series."""
        def sync(gauge, seen, current, setter):
            for labels in seen - set(current):
                try:
                    gauge.remove(*labels)
                except KeyError:
                    pass
            for labels in current:
                setter(labels)
            return set(current)

        state_code = {"closed": 0, "half_open": 1, "open": 2}
        self._seen_failures = sync(
            self.upstream_failures, self._seen_failures,
            tracker.failures,
            lambda k: self.upstream_failures.labels(
                server=k[0], kind=k[1]).set(tracker.failures[k]))
        self._seen_retry_servers = sync(
            self.upstream_retries, self._seen_retry_servers,
            {(u,) for u in tracker.retries},
            lambda k: self.upstream_retries.labels(server=k[0]).set(
                tracker.retries[k[0]]))
        self._seen_relayed_servers = sync(
            self.relayed_5xx, self._seen_relayed_servers,
            {(u,) for u in tracker.relayed_5xx},
            lambda k: self.relayed_5xx.labels(server=k[0]).set(
                tracker.relayed_5xx[k[0]]))
        snap = tracker.snapshot()
        self._seen_breaker_servers = sync(
            self.breaker_state, self._seen_breaker_servers,
            {(u,) for u in snap},
            lambda k: self.breaker_state.labels(server=k[0]).set(
                state_code.get(snap[k[0]]["state"], 0)))
        self.breaker_opens.set(tracker.breaker_opens)

    def refresh_overload(self, shed_counts: dict) -> None:
        for scope, count in shed_counts.items():
            self.router_sheds.labels(scope=scope).set(count)

    def refresh_routing(self, router) -> None:
        """Export cache-aware routing + affinity-move counters when
        the active policy carries them. Delta-synced: a dynamic-config
        swap resets the router object's totals, so fresh totals below
        the last sync are treated as new increments."""
        moves = getattr(router, "affinity_moves", None)
        if moves is not None:
            for reason, total in moves.items():
                delta = total - self._affinity_last.get(reason, 0)
                if delta < 0:     # router swapped: totals restarted
                    delta = total
                if delta > 0:
                    self.affinity_moves.labels(reason=reason).inc(delta)
                self._affinity_last[reason] = total
        warm = getattr(router, "warm_routes", None)
        if warm is None:
            return
        cold = router.cold_routes
        for key, total, counter in (
                ("warm", warm, self.prefix_warm_routes),
                ("cold", cold, self.prefix_cold_routes)):
            delta = total - self._prefix_last[key]
            if delta < 0:         # router swapped: totals restarted
                delta = total
            if delta > 0:
                counter.inc(delta)
            self._prefix_last[key] = total

    def refresh_peers(self, peers) -> None:
        """Export peer-router liveness (shared_state.RouterPeers).
        The state label set is fixed, so nothing to evict."""
        for state, count in peers.state_counts().items():
            self.router_peers.labels(state=state).set(count)

    def refresh_qos(self, qos) -> None:
        """Export per-tier QoS accounting (qos.QosPolicy). The tier
        label set is fixed by the CLI spec for the process lifetime;
        sheds/preemptions are delta-synced real counters."""
        for tier, total in qos.shed_totals().items():
            delta = total - self._qos_shed_last.get(tier, 0)
            if delta > 0:
                self.qos_sheds.labels(tier=tier).inc(delta)
            self._qos_shed_last[tier] = total
        for t in qos.tiers:
            total = qos.preemptions[t.index]
            delta = total - self._qos_preempt_last.get(t.name, 0)
            if delta > 0:
                self.qos_preemptions.labels(tier=t.name).inc(delta)
            self._qos_preempt_last[t.name] = total
            self.qos_inflight.labels(tier=t.name).set(
                qos.inflight[t.index])
        # per-(tenant, tier) sheds from the nested tenant buckets; the
        # policy's LRU evicts (tenant, tier) keys with their buckets,
        # so the baseline dict is pruned with it — an evicted tenant
        # that returns restarts its totals, which delta-sync treats as
        # fresh increments (never negative)
        tenant_sheds = getattr(qos, "tenant_sheds", None)
        if tenant_sheds is not None:
            for key in [k for k in self._tenant_shed_last
                        if k not in tenant_sheds]:
                del self._tenant_shed_last[key]
            for (tenant, tier), total in tenant_sheds.items():
                delta = total - self._tenant_shed_last.get(
                    (tenant, tier), 0)
                if delta > 0:
                    self.tenant_sheds.labels(
                        tenant=tenant, tier=tier).inc(delta)
                self._tenant_shed_last[(tenant, tier)] = total

    def refresh_pools(self, pools) -> None:
        """Export the PoolManager's accounting. Requests/swaps are
        delta-synced real counters off manager totals that survive
        pool-object swaps; the endpoint gauge drops label series for
        pools no longer in the table (dropped pools keep their counter
        totals — counters are monotonic — but must not export a frozen
        endpoint count)."""
        snap = pools.snapshot()
        for name in self._seen_pools - set(snap):
            try:
                self.pool_endpoints.remove(name)
            except KeyError:
                pass
        self._seen_pools = set(snap)
        for name, info in snap.items():
            self.pool_endpoints.labels(pool=name).set(
                len(info["backends"]))
        for name, total in pools.routed.items():
            delta = total - self._pool_req_last.get(name, 0)
            if delta > 0:
                self.pool_requests.labels(pool=name).inc(delta)
            self._pool_req_last[name] = total
        for name, total in pools.swaps.items():
            delta = total - self._pool_swap_last.get(name, 0)
            if delta > 0:
                self.pool_swaps.labels(pool=name).inc(delta)
            self._pool_swap_last[name] = total
        delta = pools.unknown_models - self._pool_unknown_last
        if delta > 0:
            self.pool_unknown_models.inc(delta)
        self._pool_unknown_last = pools.unknown_models

    def refresh_disagg(self, orch) -> None:
        """Export the disagg orchestrator's counters. Delta-synced like
        refresh_routing: a dynamic-config swap may replace the
        orchestrator (totals restart), so fresh totals below the last
        sync are treated as new increments."""
        def bump(key, total, counter):
            delta = total - self._disagg_last.get(key, 0)
            if delta < 0:             # orchestrator swapped: restarted
                delta = total
            if delta > 0:
                counter.inc(delta)
            self._disagg_last[key] = total

        bump("prefills", orch.prefills, self.disagg_prefills)
        bump("errors", orch.prefill_errors, self.disagg_prefill_errors)
        bump("breaker_opens", orch.breaker_opens,
             self.disagg_breaker_opens)
        bump("headstart", orch.headstart_elapsed,
             self.disagg_headstart_elapsed)
        for reason, total in orch.fallbacks.items():
            bump(f"fb:{reason}", total,
                 self.disagg_fallbacks.labels(reason=reason))
        sel = orch.selector
        if sel is not None:
            bump("cost_routes", sel.cost_routes,
                 self.disagg_decode_cost_routes)
            bump("abstains", sel.abstains, self.disagg_decode_abstains)

    def evict_phase_servers(self, live_urls) -> int:
        """Drop per-endpoint phase-histogram series for endpoints no
        longer configured (called from the /metrics handler next to the
        stats/breaker evictions). Router-local series (server="") are
        untouched."""
        return self.request_phases.evict_except(live_urls,
                                                label_index=1)

    def reset_disagg_baseline(self) -> None:
        """Called after a final refresh_disagg fold when the
        orchestrator is removed (dynamic-config disable): the next
        orchestrator starts its totals from zero, and a stale baseline
        would swallow its first increments whenever they happen to
        pass the old totals between scrapes."""
        self._disagg_last = {}

    def refresh_slo(self, slo_engine) -> None:
        """Export the SLO engine's burn rates and alert states (a
        scrape re-evaluates unless the eval task's last pass is under
        half a second old — states cannot move faster). Fired counts are
        delta-synced real counters; the (slo, window) and (alert)
        label sets are fixed by the SLO config, so there is nothing to
        evict."""
        from production_stack_tpu.slo import STATE_CODE
        slo_engine.evaluate(max_age_s=0.5)
        for slo_name, windows in slo_engine.burns.items():
            for window, value in windows.items():
                self.slo_burn.labels(slo=slo_name, window=window).set(
                    value)
        for slo_name, windows in slo_engine.volumes.items():
            for window, value in windows.items():
                self.slo_events.labels(slo=slo_name, window=window).set(
                    value)
        for name, alert in slo_engine.alerts.items():
            self.alert_state.labels(alert=name).set(
                STATE_CODE[alert.state])
            delta = alert.fired_total - \
                self._alerts_fired_last.get(name, 0)
            if delta > 0:
                self.alerts_fired.labels(alert=name).inc(delta)
            self._alerts_fired_last[name] = alert.fired_total

    def refresh_semantic_cache(self, cache) -> None:
        self.semantic_hits.set(cache.hits)
        self.semantic_misses.set(cache.misses)
        self.semantic_hit_ratio.set(cache.hit_ratio)
        self.semantic_size.set(len(cache))
        self.semantic_latency.set(cache.last_lookup_s)

    def refresh_pii(self, middleware) -> None:
        self.pii_scanned.set(middleware.scanned)
        self.pii_blocked.set(middleware.blocked)
        self.pii_redacted.set(middleware.redacted)

    def render(self) -> bytes:
        return generate_latest(self.registry)
