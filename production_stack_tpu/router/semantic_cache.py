"""Semantic cache: serve repeated-meaning chat requests without an engine.

Capability parity with reference src/vllm_router/experimental/semantic_cache/
(semantic_cache.py:1-353 + db_adapters/faiss_adapter.py:30-134 +
semantic_cache_integration.py:25-306): embed the request messages, nearest-
neighbor against cached request embeddings, and short-circuit the router
when similarity clears a threshold; store non-streaming completions after
they finish. Differences by design:

  * the vector index is this repo's native C++ flat-IP index
    (native/vecindex.cpp) with a numpy fallback — not a FAISS wheel;
  * the default embedder is a dependency-free feature-hashing embedder
    (deterministic across replicas), with sentence-transformers as an
    optional drop-in when the wheel exists — the reference hard-requires it;
  * persistence is the index's own binary format + a JSON metadata file,
    not pickles (no code execution on load).

Gated behind the ``SemanticCache`` feature gate (ALPHA, off by default),
like the reference (feature_gates.py).
"""

import ctypes
import itertools
import json
import math
import os
import threading
import time
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Tuple

import numpy as np

from production_stack_tpu.kvcache import _native
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

DEFAULT_SIMILARITY_THRESHOLD = 0.95
DEFAULT_DIM = 384

# canonical home is proxy.py (keeps this numpy-heavy module out of the
# hot path's imports); re-exported here because the knobs are consumed
# by SemanticCache.cacheable/check
from production_stack_tpu.router.proxy import CACHE_CONTROL_FIELDS  # noqa: E402,F401


# ---------------------------------------------------------------- embedders

class Embedder(ABC):
    dim: int

    @abstractmethod
    def embed(self, text: str) -> np.ndarray:
        """-> L2-normalized float32 [dim]."""


class HashingEmbedder(Embedder):
    """Deterministic feature-hashing embedder (no model download).

    Words and character trigrams are hashed into `dim` buckets with a
    +/- sign (the classic hashing trick), tf-weighted, L2-normalized.
    Inner product then behaves like an n-gram cosine similarity: near-1.0
    for same meaning-ish strings with small edits, low for unrelated text.
    Deterministic across processes/replicas (blake2b, not PYTHONHASHSEED).
    """

    def __init__(self, dim: int = DEFAULT_DIM):
        self.dim = dim

    def _features(self, text: str):
        text = " ".join(text.lower().split())
        for word in text.split(" "):
            yield "w:" + word
        padded = f"  {text} "
        for i in range(len(padded) - 2):
            yield "c:" + padded[i:i + 3]

    def embed(self, text: str) -> np.ndarray:
        import hashlib
        vec = np.zeros(self.dim, np.float32)
        for feat in self._features(text):
            h = int.from_bytes(
                hashlib.blake2b(feat.encode(), digest_size=8).digest(),
                "little")
            idx = (h >> 1) % self.dim
            sign = 1.0 if h & 1 else -1.0
            vec[idx] += sign
        norm = float(np.linalg.norm(vec))
        return vec / norm if norm > 0 else vec


class SentenceTransformerEmbedder(Embedder):
    """Optional wrapper over sentence-transformers (reference default
    all-MiniLM-L6-v2, semantic_cache.py); only usable when the wheel and
    model weights are present."""

    def __init__(self, model_name: str = "all-MiniLM-L6-v2"):
        from sentence_transformers import SentenceTransformer  # lazy
        self._model = SentenceTransformer(model_name)
        self.dim = self._model.get_sentence_embedding_dimension()

    def embed(self, text: str) -> np.ndarray:
        vec = np.asarray(self._model.encode([text])[0], np.float32)
        norm = float(np.linalg.norm(vec))
        return vec / norm if norm > 0 else vec


class EngineEmbedder(Embedder):
    """Embed via a serving engine's /v1/embeddings endpoint — the REAL
    model path (models/encoder.py behind engine/server.py), so the
    router process stays model-free and the encoder runs where the
    accelerator is. Mirrors the reference's real-model embedder
    (semantic_cache.py sentence-transformers) without pulling torch
    into the router.

    Spec form: ``engine:http://host:port`` or
    ``engine:http://host:port#model-name``. Synchronous HTTP with a
    bounded timeout — check()/store() already run on executor threads,
    never on the event loop. The embedding dim is discovered by a probe
    at construction, retried over ~15s to ride out the router-before-
    engine startup race; if the endpoint never answers, construction
    RAISES and the router fails fast (k8s restarts it until the engine
    is up) — silently downgrading an explicitly configured real-model
    embedder to hashing geometry would flip hit/miss behavior with one
    log line as the only trace. Runtime failures are bounded by the
    cache's breaker (SemanticCache._embed_guarded)."""

    def __init__(self, url: str, model: Optional[str] = None,
                 timeout_s: float = 3.0, probe_retries: int = 5,
                 probe_delay_s: float = 3.0):
        self.url = url.rstrip("/") + "/v1/embeddings"
        self.model = model
        self.timeout_s = timeout_s
        last_err = None
        for attempt in range(probe_retries):
            try:
                self.dim = len(self._fetch("dimension probe"))
                return
            except Exception as e:      # noqa: BLE001 — urllib raises
                last_err = e            # URLError/OSError/HTTPError/...
                if attempt + 1 < probe_retries:
                    logger.info(
                        "engine embedder probe %d/%d failed (%s); "
                        "retrying in %.0fs", attempt + 1, probe_retries,
                        e, probe_delay_s)
                    time.sleep(probe_delay_s)
        raise RuntimeError(
            f"engine embedder endpoint {self.url} unreachable after "
            f"{probe_retries} probes: {last_err}")

    def _fetch(self, text: str) -> np.ndarray:
        import urllib.request
        payload = {"input": [text]}
        if self.model:
            payload["model"] = self.model
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            data = json.loads(resp.read())
        return np.asarray(data["data"][0]["embedding"], np.float32)

    def embed(self, text: str) -> np.ndarray:
        vec = self._fetch(text)
        norm = float(np.linalg.norm(vec))
        return vec / norm if norm > 0 else vec


def make_embedder(spec: str = "hashing", dim: int = DEFAULT_DIM) -> Embedder:
    if spec == "hashing":
        return HashingEmbedder(dim)
    if spec.startswith("engine:"):
        # no hashing fallback here, deliberately: the operator asked for
        # real-model embeddings; a dead endpoint fails router startup
        # (EngineEmbedder docstring) rather than silently serving a
        # different similarity geometry
        rest = spec[len("engine:"):]
        url, _, model = rest.partition("#")
        return EngineEmbedder(url, model or None)
    if spec.startswith("sentence-transformers/") or spec == "minilm":
        name = spec.split("/", 1)[1] if "/" in spec else "all-MiniLM-L6-v2"
        try:
            return SentenceTransformerEmbedder(name)
        except Exception as e:
            logger.warning("sentence-transformers unavailable (%s); "
                           "falling back to hashing embedder", e)
            return HashingEmbedder(dim)
    raise ValueError(f"unknown embedder {spec!r}")


# ---------------------------------------------------------------- index

class VectorIndex(ABC):
    """FlatIP semantics: add/replace by id, top-k search, swap-remove."""

    @abstractmethod
    def add(self, vec: np.ndarray, vid: int) -> None: ...

    @abstractmethod
    def remove(self, vid: int) -> bool: ...

    @abstractmethod
    def search(self, vec: np.ndarray, k: int) -> \
        Tuple[List[float], List[int]]: ...

    @abstractmethod
    def __len__(self) -> int: ...

    @abstractmethod
    def save(self, path: str) -> None: ...


class NativeVectorIndex(VectorIndex):
    """ctypes binding of native/vecindex.cpp (psvi_*)."""

    def __init__(self, dim: int, _handle=None):
        self._lib = _native.load()
        if self._lib is None:
            raise RuntimeError("libpskv.so unavailable")
        self.dim = dim
        self._h = _handle if _handle is not None else \
            self._lib.psvi_new(dim)

    @classmethod
    def load(cls, path: str) -> Optional["NativeVectorIndex"]:
        lib = _native.load()
        if lib is None:
            return None
        h = lib.psvi_load(path.encode())
        if not h:
            return None
        return cls(lib.psvi_dim(h), _handle=h)

    def _as_fp(self, vec: np.ndarray):
        vec = np.ascontiguousarray(vec, np.float32)
        return vec, vec.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    def add(self, vec: np.ndarray, vid: int) -> None:
        keepalive, ptr = self._as_fp(vec)
        self._lib.psvi_add(self._h, ptr, vid)

    def remove(self, vid: int) -> bool:
        return bool(self._lib.psvi_remove(self._h, vid))

    def search(self, vec: np.ndarray, k: int):
        keepalive, ptr = self._as_fp(vec)
        scores = np.empty(k, np.float32)
        ids = np.empty(k, np.int64)
        n = self._lib.psvi_search(
            self._h, ptr, k,
            scores.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return scores[:n].tolist(), ids[:n].tolist()

    def __len__(self) -> int:
        return int(self._lib.psvi_size(self._h))

    def save(self, path: str) -> None:
        if self._lib.psvi_save(self._h, path.encode()) != 0:
            raise OSError(f"failed to save vector index to {path}")

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.psvi_free(h)
            self._h = None


class NumpyVectorIndex(VectorIndex):
    """Pure-numpy fallback with identical semantics + file format."""

    _MAGIC, _VERSION = 0x50535649, 1

    def __init__(self, dim: int):
        self.dim = dim
        # contiguous row matrix grown by doubling: search is a single
        # matvec, no per-query stack/copy (mirrors the native buffer)
        self._data = np.empty((16, dim), np.float32)
        self._n = 0
        self._ids: List[int] = []
        self._pos: Dict[int, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def load(cls, path: str) -> Optional["NumpyVectorIndex"]:
        try:
            with open(path, "rb") as f:
                raw = f.read(12)
                if len(raw) < 12:  # truncated header
                    return None
                hdr = np.frombuffer(raw, np.uint32)
                if hdr[0] != cls._MAGIC or hdr[1] != cls._VERSION:
                    return None
                dim = int(hdr[2])
                (n,) = np.frombuffer(f.read(8), np.uint64)
                n = int(n)
                ids = np.frombuffer(f.read(8 * n), np.int64)
                data = np.frombuffer(f.read(4 * n * dim),
                                     np.float32).reshape(n, dim)
            ix = cls(dim)
            for i in range(n):
                ix.add(data[i], int(ids[i]))
            return ix
        except (OSError, ValueError, MemoryError, OverflowError):
            # ValueError: truncated payload; MemoryError/OverflowError:
            # absurd on-disk count from a corrupt header
            return None

    def add(self, vec: np.ndarray, vid: int) -> None:
        vec = np.ascontiguousarray(vec, np.float32)
        with self._lock:
            row = self._pos.get(vid)
            if row is not None:
                self._data[row] = vec
                return
            if self._n == len(self._data):
                grown = np.empty((2 * len(self._data), self.dim),
                                 np.float32)
                grown[:self._n] = self._data[:self._n]
                self._data = grown
            self._data[self._n] = vec
            self._pos[vid] = self._n
            self._ids.append(vid)
            self._n += 1

    def remove(self, vid: int) -> bool:
        with self._lock:
            row = self._pos.pop(vid, None)
            if row is None:
                return False
            last = self._n - 1
            if row != last:
                self._data[row] = self._data[last]
                self._ids[row] = self._ids[last]
                self._pos[self._ids[row]] = row
            self._ids.pop()
            self._n = last
            return True

    def search(self, vec: np.ndarray, k: int):
        with self._lock:
            if not self._n:
                return [], []
            scores = self._data[:self._n] @ np.asarray(vec, np.float32)
            order = np.argsort(-scores)[:k]
            return scores[order].tolist(), [self._ids[i] for i in order]

    def __len__(self) -> int:
        with self._lock:
            return self._n

    def save(self, path: str) -> None:
        with self._lock:
            n = self._n
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(np.asarray([self._MAGIC, self._VERSION, self.dim],
                                   np.uint32).tobytes())
                f.write(np.asarray([n], np.uint64).tobytes())
                f.write(np.asarray(self._ids, np.int64).tobytes())
                if n:
                    f.write(self._data[:n].tobytes())
            os.replace(tmp, path)


def make_index(dim: int) -> VectorIndex:
    if _native.load() is not None:
        return NativeVectorIndex(dim)
    return NumpyVectorIndex(dim)


def load_index(path: str) -> Optional[VectorIndex]:
    ix = NativeVectorIndex.load(path) if _native.load() is not None else None
    return ix if ix is not None else NumpyVectorIndex.load(path)


# ---------------------------------------------------------------- cache

class SemanticCache:
    """check() before routing, store() after completion (non-streaming).

    Request text = concatenated chat messages (role + content), matching
    the reference's extraction (semantic_cache.py). Per-request knobs in
    the body mirror the reference integration: ``skip_cache: true``
    bypasses, ``cache_similarity_threshold`` overrides the default.
    """

    INDEX_FILE = "semantic_index.bin"
    META_FILE = "semantic_meta.json"

    # embedder circuit breaker: after this many CONSECUTIVE embed
    # failures the cache disables itself for the cooldown (requests
    # route straight to engines — a sick embedding endpoint must never
    # queue the whole router behind its timeout), then lets one request
    # probe again (half-open)
    EMBED_BREAKER_THRESHOLD = 3
    EMBED_BREAKER_COOLDOWN_S = 30.0

    def __init__(self, embedder: Optional[Embedder] = None,
                 threshold: float = DEFAULT_SIMILARITY_THRESHOLD,
                 max_entries: int = 4096,
                 persist_dir: Optional[str] = None):
        self.embedder = embedder or HashingEmbedder()
        self.threshold = threshold
        self.max_entries = max_entries
        self.persist_dir = persist_dir
        self.hits = 0
        self.misses = 0
        self.last_lookup_s = 0.0
        self._embed_failures = 0
        self._embed_retry_at = 0.0
        self._lock = threading.Lock()
        self._meta: Dict[int, dict] = {}
        self._order: List[int] = []          # insertion order for eviction
        self._ids = itertools.count()
        self.index: VectorIndex = make_index(self.embedder.dim)
        if persist_dir:
            self._load_persisted()

    # -- request plumbing ------------------------------------------------

    @staticmethod
    def request_text(body: dict) -> Optional[str]:
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            return None
        parts = []
        for m in messages:
            if not isinstance(m, dict):
                return None
            content = m.get("content", "")
            if isinstance(content, list):  # multimodal: text parts only
                content = " ".join(p.get("text", "") for p in content
                                   if isinstance(p, dict))
            parts.append(f"{m.get('role', '')}: {content}")
        return "\n".join(parts)

    @staticmethod
    def cacheable(body: dict) -> bool:
        """Only plain single-choice text completions are cacheable: a
        cached answer can't honor tools / response_format / n>1 /
        logprobs, so requests carrying them must always reach an engine."""
        return not (body.get("stream") or body.get("skip_cache")
                    or body.get("tools") or body.get("tool_choice")
                    or body.get("response_format") or body.get("logprobs")
                    or body.get("n", 1) != 1)

    # -- core ------------------------------------------------------------

    def _embed_guarded(self, text: str) -> Optional[np.ndarray]:
        """embed() behind the consecutive-failure breaker: None = the
        cache is sitting out this request (open circuit or a fresh
        failure); the caller treats it as 'no cache', never an error —
        an embedding outage must cost one log line, not requests."""
        now = time.monotonic()
        with self._lock:
            if (self._embed_failures >= self.EMBED_BREAKER_THRESHOLD
                    and now < self._embed_retry_at):
                return None
        try:
            vec = self.embedder.embed(text)
        except Exception as e:   # noqa: BLE001 — any transport failure
            with self._lock:
                self._embed_failures += 1
                self._embed_retry_at = (time.monotonic()
                                        + self.EMBED_BREAKER_COOLDOWN_S)
                tripped = (self._embed_failures
                           == self.EMBED_BREAKER_THRESHOLD)
            (logger.warning if tripped else logger.info)(
                "semantic-cache embed failed (%s)%s", e,
                f"; breaker OPEN for {self.EMBED_BREAKER_COOLDOWN_S:.0f}s"
                if tripped else "")
            return None
        with self._lock:
            self._embed_failures = 0
        return vec

    def check(self, body: dict) -> Optional[dict]:
        """Cached response for a semantically-equivalent request, or None."""
        if not self.cacheable(body):
            return None
        text = self.request_text(body)
        if text is None:
            return None
        threshold = float(body.get("cache_similarity_threshold",
                                   self.threshold))
        t0 = time.monotonic()
        vec = self._embed_guarded(text)
        if vec is None:
            return None
        # k > 1: in multi-model deployments the global nearest neighbor may
        # belong to another model; take the best same-model hit instead
        scores, ids = self.index.search(vec, 8)
        # check() runs on executor threads: counter read-modify-writes
        # must hold the lock or concurrent lookups lose increments
        with self._lock:
            self.last_lookup_s = time.monotonic() - t0
        for score, vid in zip(scores, ids):
            if score < threshold:
                break
            with self._lock:
                meta = self._meta.get(vid)
            if meta is not None and meta.get("model") == body.get("model"):
                with self._lock:
                    self.hits += 1
                response = dict(meta["response"])
                response["cached"] = True
                return response
        with self._lock:
            self.misses += 1
        return None

    def store(self, body: dict, response: dict) -> bool:
        if not self.cacheable(body):
            return False
        text = self.request_text(body)
        if text is None:
            return False
        vec = self._embed_guarded(text)
        if vec is None:
            return False
        with self._lock:
            vid = next(self._ids)
        # the vector must be in the index BEFORE vid is registered in
        # _order: a concurrent store() may evict vid the moment it is
        # registered, and index.remove of a not-yet-added vid would no-op,
        # orphaning the vector forever
        self.index.add(vec, vid)
        with self._lock:
            self._meta[vid] = {"model": body.get("model"),
                               "response": response}
            self._order.append(vid)
            evict = []
            while len(self._order) > self.max_entries:
                old = self._order.pop(0)
                self._meta.pop(old, None)
                evict.append(old)
        for old in evict:
            self.index.remove(old)
        return True

    # -- persistence -----------------------------------------------------

    def persist(self) -> None:
        if not self.persist_dir:
            return
        os.makedirs(self.persist_dir, exist_ok=True)
        self.index.save(os.path.join(self.persist_dir, self.INDEX_FILE))
        tmp = os.path.join(self.persist_dir, self.META_FILE + ".tmp")
        with self._lock:
            payload = {"next_id": next(self._ids),
                       "order": self._order,
                       "meta": {str(k): v for k, v in self._meta.items()}}
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, os.path.join(self.persist_dir, self.META_FILE))

    def _load_persisted(self) -> None:
        index_path = os.path.join(self.persist_dir, self.INDEX_FILE)
        meta_path = os.path.join(self.persist_dir, self.META_FILE)
        if not (os.path.exists(index_path) and os.path.exists(meta_path)):
            return
        try:
            with open(meta_path) as f:
                payload = json.load(f)
            loaded = load_index(index_path)
            if loaded is None:
                return
            if loaded.dim != self.embedder.dim:
                logger.warning(
                    "semantic cache restore skipped: persisted index dim "
                    "%d != embedder dim %d (embedder changed?)",
                    loaded.dim, self.embedder.dim)
                return
            self.index = loaded
            self._meta = {int(k): v for k, v in payload["meta"].items()}
            self._order = list(payload["order"])
            self._ids = itertools.count(int(payload["next_id"]))
            logger.info("semantic cache restored: %d entries",
                        len(self._meta))
        except (OSError, ValueError, KeyError) as e:
            logger.warning("semantic cache restore failed: %s", e)

    # -- metrics ---------------------------------------------------------

    @property
    def hit_ratio(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def __len__(self) -> int:
        with self._lock:
            return len(self._meta)
