"""Multi-router shared state: peer gossip, convergence, cap splitting.

One router process binds at ~1,650 req/s (BASELINE.md Round 7); a
fleet serving millions of users runs N of them behind a dumb L4
split. Everything the data plane learned used to be process-local —
session/prefix rings, breaker state, drain flags, in-flight caps — so
N routers would disagree about affinity and health. This module is
the control plane that makes N routers behave like one:

- **Deterministic affinity without synchronized rings.** The
  session/prefix policies already route by consistent hashing over
  the endpoint set (routing.HashRing): two routers with the SAME
  healthy-endpoint view map the same key to the same engine without
  exchanging a byte of ring state. What actually diverges is the
  *view* — a breaker tripped on one replica, a drain issued through
  one replica's /admin/drain. So the gossip exchanges exactly those
  facts and nothing else.
- **Breaker/drain convergence.** Each router serves its shareable
  health facts on ``GET /peers`` (``HealthTracker.peer_view()``:
  per-endpoint breaker state + drain flag, stamped with transition
  *ages* — no shared clock needed). ``RouterPeers`` polls every peer
  on a short interval and merges by last-writer-wins on age
  (``HealthTracker.adopt_peer_view``), so an engine death observed by
  one router opens everyone's breaker within a gossip interval, and
  the probe-driven close propagates the same way.
- **Apportioned in-flight caps.** The per-endpoint concurrency cap
  (engine-advertised capacity or ``--endpoint-inflight-cap``) is a
  FLEET-wide bound; each router enforces ``cap × cap_share()`` where
  the share is 1/(live routers), so N routers together still respect
  the engine's advertised capacity instead of N-times it.
- **Peer liveness.** Peers answer → ``live``; stop answering →
  ``stale`` after ``stale_after_s`` then counted dead. Surfaced on
  ``/health``, ``tpu:router_peers{state}``, and as a signal SLO
  (``router_peer_lost`` — docs/runbooks.md#router_peer_lost_page).

The closed loop is ``python -m production_stack_tpu.loadgen
multirouter`` (docs/benchmarks.md "Multi-router"): ≥2 real router
processes behind an L4 splitter must match a single-router control's
affinity hit rate, converge breaker state across replicas, survive a
router SIGKILL with only the in-flight blip, and degrade by QoS tier
(router/qos.py) rather than uniformly — committed as
``MULTIROUTER_r16.json``.
"""

import asyncio
import time
from typing import Callable, Dict, List, Optional

import aiohttp

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

PEERS_PATH = "/peers"

LIVE, STALE, UNREACHABLE = "live", "stale", "unreachable"


class _Peer:
    __slots__ = ("url", "router_id", "last_seen", "last_attempt",
                 "failures", "ever_seen")

    def __init__(self, url: str):
        self.url = url
        self.router_id: Optional[str] = None
        self.last_seen: Optional[float] = None
        self.last_attempt: Optional[float] = None
        self.failures = 0
        self.ever_seen = False


class _PeerSignal:
    """One peer's freshness sample for the SLO engine's signal path
    (``SLOEngine.ingest_engine_loads`` reads ``peer_age_s`` by
    attribute name and dedups on ``scraped_at``)."""

    __slots__ = ("peer_age_s", "scraped_at")

    def __init__(self, peer_age_s: float, scraped_at: float):
        self.peer_age_s = peer_age_s
        self.scraped_at = scraped_at


def derive_router_id(host: str, port: int) -> str:
    """Default ``--router-id``: host:port the process listens on —
    stable across restarts of the same replica, unique across a
    fleet launched by the orchestrator (distinct ports/hosts)."""
    import socket
    h = host
    if h in ("0.0.0.0", "::", ""):
        h = socket.gethostname()
    return f"{h}:{port}"


class RouterPeers:
    """Gossip client + merge loop for one router process.

    ``health`` is the process's HealthTracker (merge target);
    ``known_urls`` returns the configured engine fleet so a peer with
    a stale config cannot plant state for endpoints we dropped.
    """

    def __init__(self, router_id: str,
                 peer_urls: List[str],
                 health,
                 known_urls: Callable[[], List[str]],
                 interval_s: float = 1.0,
                 stale_after_s: Optional[float] = None,
                 timeout_s: float = 2.0,
                 now_fn: Callable[[], float] = time.monotonic):
        self.router_id = router_id
        self.interval_s = interval_s
        # a peer is stale once it has missed ~3 gossip rounds
        self.stale_after_s = stale_after_s if stale_after_s is not None \
            else max(3.0 * interval_s, 2.0)
        self.health = health
        self.known_urls = known_urls
        self._now = now_fn
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self._peers: Dict[str, _Peer] = {
            u.rstrip("/"): _Peer(u.rstrip("/")) for u in peer_urls
            if u.strip()}
        self._task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        # telemetry
        self.gossip_rounds = 0
        self.merge_errors = 0

    # -- lifecycle ------------------------------------------------------

    async def start(self, session: aiohttp.ClientSession) -> None:
        self._session = session
        self._task = asyncio.create_task(self._loop(), name="peer-gossip")

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def healthy(self) -> bool:
        return self._task is None or not self._task.done()

    async def _loop(self) -> None:
        while True:
            try:
                await self.gossip_now()
            except asyncio.CancelledError:
                raise
            except Exception:
                self.merge_errors += 1
                logger.exception("peer gossip round failed")
            await asyncio.sleep(self.interval_s)

    # -- gossip ---------------------------------------------------------

    async def gossip_now(self) -> None:
        """One concurrent poll-and-merge pass over every peer."""
        if not self._peers:
            return
        await asyncio.gather(*(self._poll_one(p)
                               for p in self._peers.values()))
        self.gossip_rounds += 1

    async def _poll_one(self, peer: _Peer) -> None:
        peer.last_attempt = self._now()
        try:
            async with self._session.get(f"{peer.url}{PEERS_PATH}",
                                         timeout=self._timeout) as r:
                if r.status != 200:
                    peer.failures += 1
                    return
                body = await r.json()
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError, ValueError):
            peer.failures += 1
            return
        rid = body.get("router_id")
        if rid == self.router_id:
            # an L4 splitter (or a copy-pasted config) pointed us at
            # ourselves; merging our own echo is harmless but the
            # liveness count would read one router as two
            logger.warning("peer %s answers with our own router_id %s; "
                           "ignoring it", peer.url, rid)
            peer.failures += 1
            return
        peer.router_id = rid
        peer.last_seen = self._now()
        peer.failures = 0
        peer.ever_seen = True
        view = body.get("breakers") or {}
        if isinstance(view, dict):
            self.health.adopt_peer_view(view, self.known_urls())

    # -- reads ----------------------------------------------------------

    def _state(self, peer: _Peer) -> str:
        if peer.last_seen is None:
            return UNREACHABLE
        if self._now() - peer.last_seen > self.stale_after_s:
            return STALE
        return LIVE

    def peers(self) -> Dict[str, Dict]:
        """Per-peer liveness for /health and the stat log."""
        out = {}
        for url, p in self._peers.items():
            age = None if p.last_seen is None \
                else round(self._now() - p.last_seen, 3)
            out[url] = {"router_id": p.router_id,
                        "state": self._state(p),
                        "last_seen_age_s": age,
                        "failures": p.failures}
        return out

    def state_counts(self) -> Dict[str, int]:
        counts = {LIVE: 0, STALE: 0, UNREACHABLE: 0}
        for p in self._peers.values():
            counts[self._state(p)] += 1
        return counts

    def live_router_count(self) -> int:
        """Routers currently sharing the fleet's caps: self + live
        peers. A peer that stopped answering stops counting — its
        share of every endpoint cap flows back to the survivors
        within ``stale_after_s`` (exactly what a router SIGKILL under
        load needs)."""
        return 1 + sum(1 for p in self._peers.values()
                       if self._state(p) == LIVE)

    def cap_share(self) -> float:
        """Fraction of each fleet-wide per-endpoint cap THIS router
        may use."""
        return 1.0 / max(1, self.live_router_count())

    def signal_records(self) -> Dict[str, _PeerSignal]:
        """Peer freshness as SLO signal samples (``router_peer_lost``).

        A peer we have EVER seen answers with its silence: its age
        grows past the SLO bound and burns. A peer we have never
        reached is indistinguishable from a replica that hasn't
        started yet — startup must not page — so it contributes no
        sample until first contact.
        """
        now = self._now()
        out = {}
        for url, p in self._peers.items():
            if not p.ever_seen:
                continue
            # `is None` checks throughout: 0.0 is a timestamp (the
            # stats-plane convention), not "never"
            age = max(0.0, now - p.last_seen) \
                if p.last_seen is not None else 0.0
            # scraped_at moves every attempt so the engine's per-
            # (url, scrape) dedup admits one sample per gossip round
            # even while the peer is dark
            out[url] = _PeerSignal(
                peer_age_s=age,
                scraped_at=p.last_attempt
                if p.last_attempt is not None else now)
        return out

    def snapshot(self) -> Dict:
        return {
            "router_id": self.router_id,
            "interval_s": self.interval_s,
            "gossip_rounds": self.gossip_rounds,
            "live_routers": self.live_router_count(),
            "cap_share": round(self.cap_share(), 4),
            "peers": self.peers(),
            "adopted_opens": self.health.peer_adopted_opens,
            "adopted_closes": self.health.peer_adopted_closes,
        }


def peers_payload(router_id: str, health) -> Dict:
    """The ``GET /peers`` body this router serves to its peers."""
    return {"router_id": router_id,
            "breakers": health.peer_view()}
