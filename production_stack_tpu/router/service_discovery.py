"""Engine membership: who can serve which model, kept fresh.

Two implementations behind one interface (capability parity with
reference src/vllm_router/service_discovery.py:36-239, re-designed):

- StaticServiceDiscovery: fixed URL/model lists from flags; optionally
  confirms each backend's model list by probing /v1/models.
- K8sServiceDiscovery: watches pod events through the Kubernetes REST
  API directly (aiohttp + the pod's serviceaccount token — no kubernetes
  client dependency). A pod becomes routable only when it is Ready AND
  answers /v1/models (same readiness gate as the reference :201-239).

All implementations are asyncio tasks on the app's event loop — no
threads, no locks; state mutations happen on the loop.
"""

import asyncio
import json
import os
import ssl
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


@dataclass
class EndpointInfo:
    url: str                      # e.g. http://10.0.0.3:8100
    model: str                    # served model name
    added_at: float = field(default_factory=time.time)
    pod_name: Optional[str] = None
    model_aliases: List[str] = field(default_factory=list)
    # named pool membership (disaggregated serving, router/disagg.py):
    # discovery-managed endpoints are the "decode" pool; the prefill
    # orchestrator's endpoints carry "prefill"
    pool: str = "decode"

    def serves(self, model: str) -> bool:
        return model == self.model or model in self.model_aliases


class ServiceDiscovery(ABC):
    @abstractmethod
    def get_endpoints(self) -> List[EndpointInfo]:
        ...

    def all_endpoints(self) -> List[EndpointInfo]:
        """The full configured membership, INCLUDING endpoints
        temporarily withheld from routing (e.g. probe-marked
        unroutable). State eviction keys off this so a transient
        outage doesn't wipe an endpoint's stats/breaker/drain state."""
        return self.get_endpoints()

    async def start(self) -> None:
        pass

    async def close(self) -> None:
        pass

    def healthy(self) -> bool:
        return True


def engine_auth_headers() -> Dict[str, str]:
    """Bearer header for engine pods when they enforce an API key.

    Reads ENGINE_API_KEY — the same secret the chart delivers to engine
    pods (reference parity: the stack's discovery queries pods with
    VLLM_API_KEY, src/vllm_router/service_discovery.py:145-147).
    """
    key = os.environ.get("ENGINE_API_KEY", "")
    return {"Authorization": f"Bearer {key}"} if key else {}


async def probe_model_name(session: aiohttp.ClientSession,
                           url: str) -> Optional[List[str]]:
    """GET <url>/v1/models -> list of model ids, or None if unreachable."""
    try:
        async with session.get(f"{url}/v1/models",
                               headers=engine_auth_headers(),
                               timeout=aiohttp.ClientTimeout(total=5)) as r:
            if r.status != 200:
                return None
            data = await r.json()
            return [card["id"] for card in data.get("data", [])]
    except (aiohttp.ClientError, asyncio.TimeoutError, json.JSONDecodeError,
            KeyError):
        return None


class StaticServiceDiscovery(ServiceDiscovery):
    """Fixed endpoint list, optionally liveness-checked.

    With ``probe=True``, each backend's ``/v1/models`` is re-probed on
    an interval: extra served models become routable aliases, and —
    since a static list has no other liveness signal — an endpoint
    failing ``probe_failure_threshold`` consecutive probes is marked
    unroutable (dropped from ``get_endpoints``) until a probe succeeds
    again. Probe outcomes are also fed to the router's
    ``HealthTracker`` (when wired) so the breaker, ``/metrics``, and
    discovery agree on who is healthy.
    """

    def __init__(self, urls: List[str], models: List[str],
                 aliases: Optional[Dict[str, str]] = None,
                 probe: bool = False, probe_interval: float = 30.0,
                 probe_failure_threshold: int = 3,
                 health_tracker=None):
        if len(urls) != len(models):
            raise ValueError(
                f"{len(urls)} backends but {len(models)} model names")
        alias_map: Dict[str, List[str]] = {}
        for alias, target in (aliases or {}).items():
            alias_map.setdefault(target, []).append(alias)
        self._endpoints = [
            EndpointInfo(url=u.rstrip("/"), model=m,
                         model_aliases=alias_map.get(m, []))
            for u, m in zip(urls, models)]
        self._probe = probe
        self._probe_interval = probe_interval
        self._probe_failure_threshold = probe_failure_threshold
        self._probe_failures: Dict[str, int] = {}
        self._unroutable: set = set()
        self._health = health_tracker
        self._probe_task: Optional[asyncio.Task] = None

    def get_endpoints(self) -> List[EndpointInfo]:
        if not self._unroutable:
            return list(self._endpoints)
        return [ep for ep in self._endpoints
                if ep.url not in self._unroutable]

    def all_endpoints(self) -> List[EndpointInfo]:
        return list(self._endpoints)

    async def start(self) -> None:
        if not self._probe:
            return
        # one immediate pass (routers usually start after engines), then
        # keep re-probing: an engine that is still warming up at router
        # start would otherwise never contribute its extra served models
        # (e.g. LoRA adapters) as routable aliases
        await self._probe_once()
        self._probe_task = asyncio.create_task(self._probe_loop(),
                                               name="static-probe")

    async def close(self) -> None:
        if self._probe_task:
            self._probe_task.cancel()
            self._probe_task = None

    async def _probe_loop(self) -> None:
        while True:
            await asyncio.sleep(self._probe_interval)
            try:
                await self._probe_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("static backend probe failed")

    async def _probe_once(self) -> None:
        async with aiohttp.ClientSession() as session:
            for ep in self._endpoints:
                models = await probe_model_name(session, ep.url)
                if not models:
                    n = self._probe_failures.get(ep.url, 0) + 1
                    self._probe_failures[ep.url] = n
                    if n >= self._probe_failure_threshold and \
                            ep.url not in self._unroutable:
                        # stale aliases must not keep a dead endpoint
                        # routable forever
                        logger.warning(
                            "backend %s unroutable: %d consecutive "
                            "/v1/models probe failures", ep.url, n)
                        self._unroutable.add(ep.url)
                    if self._health is not None:
                        self._health.record_probe_result(ep.url, False)
                    continue
                if ep.url in self._unroutable:
                    logger.info("backend %s recovered (probe ok); "
                                "routable again", ep.url)
                    self._unroutable.discard(ep.url)
                self._probe_failures[ep.url] = 0
                if self._health is not None:
                    self._health.record_probe_result(ep.url, True)
                if ep.model not in models:
                    logger.warning(
                        "backend %s reports models %s, flag says %s",
                        ep.url, models, ep.model)
                extra = [m for m in models
                         if m != ep.model and m not in ep.model_aliases]
                if extra:
                    # adapters/aliases the engine serves beyond the flag
                    # (e.g. LoRA adapters as model ids) become routable
                    logger.info("backend %s also serves %s", ep.url, extra)
                    ep.model_aliases = ep.model_aliases + extra


class K8sServiceDiscovery(ServiceDiscovery):
    """Watch pods matching a label selector; track ready engine pods.

    Reconnects the watch on expiry/failure with the last resourceVersion
    (falling back to a fresh list on 410 Gone).
    """

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, namespace: str, label_selector: str,
                 engine_port: int = 8100,
                 api_server: Optional[str] = None,
                 token_path: Optional[str] = None,
                 ca_path: Optional[str] = None):
        self.namespace = namespace
        self.label_selector = label_selector
        self.engine_port = engine_port
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.api_server = api_server or f"https://{host}:{port}"
        self.token_path = token_path or f"{self.SA_DIR}/token"
        self.ca_path = ca_path or f"{self.SA_DIR}/ca.crt"
        self._endpoints: Dict[str, EndpointInfo] = {}   # pod name -> info
        self._task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._last_event = 0.0

    # -- interface ------------------------------------------------------

    def get_endpoints(self) -> List[EndpointInfo]:
        return list(self._endpoints.values())

    def healthy(self) -> bool:
        return self._task is not None and not self._task.done()

    async def start(self) -> None:
        ssl_ctx: Optional[ssl.SSLContext] = None
        if os.path.exists(self.ca_path):
            ssl_ctx = ssl.create_default_context(cafile=self.ca_path)
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(ssl=ssl_ctx))
        self._task = asyncio.create_task(self._watch_loop(),
                                         name="k8s-watch")

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._session:
            await self._session.close()
            self._session = None

    # -- internals ------------------------------------------------------

    def _headers(self) -> Dict[str, str]:
        headers = {}
        if os.path.exists(self.token_path):
            with open(self.token_path) as f:
                headers["Authorization"] = f"Bearer {f.read().strip()}"
        return headers

    async def _watch_loop(self) -> None:
        resource_version = ""
        while True:
            try:
                resource_version = await self._list_pods()
                await self._watch(resource_version)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning("k8s watch error (%s); retrying in 2s", e)
                await asyncio.sleep(2)

    async def _list_pods(self) -> str:
        url = (f"{self.api_server}/api/v1/namespaces/{self.namespace}/pods"
               f"?labelSelector={self.label_selector}")
        async with self._session.get(url, headers=self._headers()) as r:
            r.raise_for_status()
            data = await r.json()
        seen = set()
        for pod in data.get("items", []):
            name = await self._handle_pod(pod)
            if name:
                seen.add(name)
        for gone in set(self._endpoints) - seen:
            logger.info("engine pod %s gone", gone)
            del self._endpoints[gone]
        return data.get("metadata", {}).get("resourceVersion", "")

    async def _watch(self, resource_version: str) -> None:
        url = (f"{self.api_server}/api/v1/namespaces/{self.namespace}/pods"
               f"?watch=true&labelSelector={self.label_selector}"
               f"&resourceVersion={resource_version}"
               f"&timeoutSeconds=300")
        async with self._session.get(
                url, headers=self._headers(),
                timeout=aiohttp.ClientTimeout(total=None, sock_read=330)
        ) as resp:
            resp.raise_for_status()
            async for line in resp.content:
                if not line.strip():
                    continue
                event = json.loads(line)
                self._last_event = time.time()
                etype = event.get("type")
                pod = event.get("object", {})
                if etype in ("ADDED", "MODIFIED"):
                    await self._handle_pod(pod)
                elif etype == "DELETED":
                    name = pod.get("metadata", {}).get("name")
                    if name in self._endpoints:
                        logger.info("engine pod %s deleted", name)
                        del self._endpoints[name]

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        statuses = pod.get("status", {}).get("containerStatuses", [])
        return bool(statuses) and all(s.get("ready") for s in statuses)

    async def _handle_pod(self, pod: dict) -> Optional[str]:
        meta = pod.get("metadata", {})
        name = meta.get("name")
        ip = pod.get("status", {}).get("podIP")
        if not name:
            return None
        if not ip or not self._pod_ready(pod) or pod.get("metadata", {}).get(
                "deletionTimestamp"):
            if name in self._endpoints:
                logger.info("engine pod %s not ready; removing", name)
                del self._endpoints[name]
            return None
        url = f"http://{ip}:{self.engine_port}"
        existing = self._endpoints.get(name)
        if existing is not None:
            if existing.url == url:
                return name
            # same pod name, new IP (recreated pod, missed DELETED event):
            # fall through and re-probe at the new address
            logger.info("engine pod %s moved %s -> %s; re-probing", name,
                        existing.url, url)
            del self._endpoints[name]
        models = await probe_model_name(self._session, url)
        if not models:
            return None   # not answering yet; next MODIFIED event retries
        self._endpoints[name] = EndpointInfo(url=url, model=models[0],
                                             pod_name=name,
                                             model_aliases=models[1:])
        logger.info("engine pod %s at %s serving %s", name, url, models)
        return name
