"""Hot-reload router configuration from a JSON file (operator contract).

Capability parity with reference src/vllm_router/dynamic_config.py
(DynamicRouterConfig :20-76 + 10s file-poll watcher :95-209): the C++
operator reconciles a StaticRoute CR into a ConfigMap mounted at
--dynamic-config-json; this watcher (an asyncio task) detects content
changes and swaps service discovery / routing policy in place. The
current config is surfaced in /health (parity with main_router.py:150-158).
"""

import asyncio
import json
import os
from dataclasses import dataclass, field
from typing import List, Optional

from production_stack_tpu.router.routing import make_router
from production_stack_tpu.router.service_discovery import (
    StaticServiceDiscovery)
from production_stack_tpu.utils import init_logger, parse_comma_separated

logger = init_logger(__name__)


@dataclass
class DynamicRouterConfig:
    service_discovery: str = "static"
    routing_logic: str = "roundrobin"
    static_backends: List[str] = field(default_factory=list)
    static_models: List[str] = field(default_factory=list)
    session_key: str = "x-user-id"

    @staticmethod
    def from_json(data: dict) -> "DynamicRouterConfig":
        def listify(v):
            return parse_comma_separated(v) if isinstance(v, str) else (
                v or [])
        return DynamicRouterConfig(
            service_discovery=data.get("service_discovery", "static"),
            routing_logic=data.get("routing_logic", "roundrobin"),
            static_backends=listify(data.get("static_backends")),
            static_models=listify(data.get("static_models")),
            session_key=data.get("session_key", "x-user-id"),
        )

    def to_json(self) -> dict:
        return {
            "service_discovery": self.service_discovery,
            "routing_logic": self.routing_logic,
            "static_backends": self.static_backends,
            "static_models": self.static_models,
            "session_key": self.session_key,
        }


class DynamicConfigWatcher:
    def __init__(self, app_state: dict, path: str, interval_s: float = 10.0):
        self.state = app_state
        self.path = path
        self.interval = interval_s
        self._last_content: Optional[str] = None
        self._task: Optional[asyncio.Task] = None
        self.current: Optional[DynamicRouterConfig] = None

    async def start(self) -> None:
        await self._check_once()   # apply initial config before serving
        self._task = asyncio.create_task(self._loop(), name="config-watch")

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    def healthy(self) -> bool:
        return self._task is None or not self._task.done()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self._check_once()
            except Exception:
                logger.exception("dynamic config reload failed")

    async def _check_once(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path) as f:
            content = f.read()
        if content == self._last_content:
            return
        self._last_content = content
        cfg = DynamicRouterConfig.from_json(json.loads(content))
        await self._apply(cfg)

    async def _apply(self, cfg: DynamicRouterConfig) -> None:
        logger.info("applying dynamic config: %s", cfg.to_json())
        if cfg.service_discovery == "static" and cfg.static_backends:
            old = self.state.get("discovery")
            new = StaticServiceDiscovery(
                cfg.static_backends, cfg.static_models,
                health_tracker=self.state.get("health"))
            await new.start()
            self.state["discovery"] = new
            if old is not None:
                await old.close()
        # rebuild the router ONLY when its own fields changed: the
        # autoscaler rewrites this file on every scale event, and a
        # gratuitous rebuild would wipe the policy's learned state —
        # the prefix router's warm-endpoint ring, least-loaded's
        # slow-start ramps — exactly when the fleet is in motion
        old_router = self.state.get("router")
        unchanged = (
            old_router is not None
            and old_router.name == cfg.routing_logic
            and getattr(old_router, "session_key",
                        cfg.session_key) == cfg.session_key)
        if not unchanged:
            metrics = self.state.get("metrics")
            if metrics is not None and old_router is not None:
                # fold the outgoing router's routing counters into the
                # exposition before its totals vanish with it
                metrics.refresh_routing(old_router)
            self.state["router"] = make_router(
                cfg.routing_logic, cfg.session_key,
                **self.state.get("router_kwargs", {}))
            scraper = self.state.get("scraper")
            if scraper is not None and \
                    hasattr(self.state["router"], "attach_scraper"):
                self.state["router"].attach_scraper(scraper.get)
        self.current = cfg
