"""Hot-reload router configuration from a JSON file (operator contract).

Capability parity with reference src/vllm_router/dynamic_config.py
(DynamicRouterConfig :20-76 + 10s file-poll watcher :95-209): the C++
operator reconciles a StaticRoute CR into a ConfigMap mounted at
--dynamic-config-json; this watcher (an asyncio task) detects content
changes and swaps service discovery / routing policy in place. The
current config is surfaced in /health (parity with main_router.py:150-158).
"""

import asyncio
import json
import os
from dataclasses import dataclass, field
from typing import List, Optional

from production_stack_tpu.router.routing import make_router
from production_stack_tpu.router.service_discovery import (
    StaticServiceDiscovery)
from production_stack_tpu.utils import init_logger, parse_comma_separated

logger = init_logger(__name__)


@dataclass
class DynamicRouterConfig:
    service_discovery: str = "static"
    routing_logic: str = "roundrobin"
    static_backends: List[str] = field(default_factory=list)
    static_models: List[str] = field(default_factory=list)
    session_key: str = "x-user-id"
    # disaggregated-prefill pool (router/disagg.py). Tri-state: None =
    # key absent from the config file, leave the running pool alone
    # (an autoscaler managing only the decode pool must not wipe the
    # prefill pool on every scale event); [] = explicitly disable
    # disaggregation; non-empty = swap the pool in place.
    prefill_backends: Optional[List[str]] = None
    prefill_models: Optional[List[str]] = None
    # named pools (router/pools.py). Same tri-state: None = key absent,
    # leave the running pools table alone (an autoscaler actuating one
    # pool writes only that pool's entry merged by its shared config
    # writer — but an operator pushing an unrelated key must not wipe
    # the table); {} = disable pooling (single-pool routing resumes
    # from static_backends); non-empty = diff-and-swap pool by pool,
    # preserving untouched pools' policy state.
    pools: Optional[dict] = None

    @staticmethod
    def from_json(data: dict) -> "DynamicRouterConfig":
        def listify(v):
            return parse_comma_separated(v) if isinstance(v, str) else (
                v or [])
        return DynamicRouterConfig(
            service_discovery=data.get("service_discovery", "static"),
            routing_logic=data.get("routing_logic", "roundrobin"),
            static_backends=listify(data.get("static_backends")),
            static_models=listify(data.get("static_models")),
            session_key=data.get("session_key", "x-user-id"),
            prefill_backends=(listify(data["prefill_backends"])
                              if "prefill_backends" in data else None),
            prefill_models=(listify(data["prefill_models"])
                            if "prefill_models" in data else None),
            pools=(dict(data["pools"] or {}) if "pools" in data
                   else None),
        )

    def to_json(self) -> dict:
        out = {
            "service_discovery": self.service_discovery,
            "routing_logic": self.routing_logic,
            "static_backends": self.static_backends,
            "static_models": self.static_models,
            "session_key": self.session_key,
        }
        # each key echoed only as provided: synthesizing prefill_models
        # [] next to non-empty backends would render a length-mismatched
        # document (which _apply_prefill_pool rejects) as if it were
        # the live config on /health
        if self.prefill_backends is not None:
            out["prefill_backends"] = self.prefill_backends
        if self.prefill_models is not None:
            out["prefill_models"] = self.prefill_models
        if self.pools is not None:
            out["pools"] = self.pools
        return out


class DynamicConfigWatcher:
    def __init__(self, app_state: dict, path: str, interval_s: float = 10.0):
        self.state = app_state
        self.path = path
        self.interval = interval_s
        self._last_content: Optional[str] = None
        self._task: Optional[asyncio.Task] = None
        self.current: Optional[DynamicRouterConfig] = None

    async def start(self) -> None:
        await self._check_once()   # apply initial config before serving
        self._task = asyncio.create_task(self._loop(), name="config-watch")

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    def healthy(self) -> bool:
        return self._task is None or not self._task.done()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self._check_once()
            except Exception:
                logger.exception("dynamic config reload failed")

    async def _check_once(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path) as f:
            content = f.read()
        if content == self._last_content:
            return
        self._last_content = content
        cfg = DynamicRouterConfig.from_json(json.loads(content))
        await self._apply(cfg)

    async def _apply(self, cfg: DynamicRouterConfig) -> None:
        logger.info("applying dynamic config: %s", cfg.to_json())
        if cfg.service_discovery == "static" and cfg.static_backends:
            old = self.state.get("discovery")
            new = StaticServiceDiscovery(
                cfg.static_backends, cfg.static_models,
                health_tracker=self.state.get("health"))
            await new.start()
            self.state["discovery"] = new
            if old is not None:
                await old.close()
        # rebuild the router ONLY when its own fields changed: the
        # autoscaler rewrites this file on every scale event, and a
        # gratuitous rebuild would wipe the policy's learned state —
        # the prefix router's warm-endpoint ring, least-loaded's
        # slow-start ramps — exactly when the fleet is in motion
        old_router = self.state.get("router")
        unchanged = (
            old_router is not None
            and old_router.name == cfg.routing_logic
            and getattr(old_router, "session_key",
                        cfg.session_key) == cfg.session_key)
        if not unchanged:
            metrics = self.state.get("metrics")
            if metrics is not None and old_router is not None:
                # fold the outgoing router's routing counters into the
                # exposition before its totals vanish with it
                metrics.refresh_routing(old_router)
            self.state["router"] = make_router(
                cfg.routing_logic, cfg.session_key,
                **self.state.get("router_kwargs", {}))
            scraper = self.state.get("scraper")
            if scraper is not None and \
                    hasattr(self.state["router"], "attach_scraper"):
                self.state["router"].attach_scraper(scraper.get)
        await self._apply_pools(cfg)
        self._apply_prefill_pool(cfg)
        # decode-fleet membership may have changed above (static swap)
        # even when the prefill key was absent — the decode-only-
        # autoscaler case. Locality evidence for departed decode
        # engines must go either way: a later scale-up reusing the URL
        # starts a COLD process the ring would otherwise score warm.
        disagg = self.state.get("disagg")
        if disagg is not None and disagg.selector is not None:
            discovery = self.state.get("discovery")
            if discovery is not None:
                disagg.selector.evict_except(
                    ep.url for ep in discovery.all_endpoints())
        self.current = cfg

    async def _apply_pools(self, cfg: DynamicRouterConfig) -> None:
        """Create/swap/disable the named-pools table (router/pools.py).
        The running PoolManager is mutated IN PLACE pool by pool, so a
        swap that touches pool A never resets pool B's router-policy
        state, and the manager's routed/unknown counters survive every
        swap (the r11/r12 state-survival contract at the pool layer).
        When pools are active the manager IS the service discovery —
        every fleet-wide consumer reads the union of pools."""
        if cfg.pools is None:
            return                     # key absent: leave pools alone
        manager = self.state.get("pools")
        metrics = self.state.get("metrics")
        if not cfg.pools:
            # {} -> disable pooling. Discovery falls back to whatever
            # the static swap above installed (an operator disabling
            # pools ships static_backends in the same document); with
            # no static list the fleet is legitimately empty.
            if manager is not None and manager.active:
                if metrics is not None:
                    metrics.refresh_pools(manager)
                manager.apply({})
                logger.info("dynamic config: pools disabled")
                if self.state.get("discovery") is manager and \
                        not cfg.static_backends:
                    logger.warning(
                        "dynamic config: pools disabled with no "
                        "static_backends — zero routable endpoints")
            return
        from production_stack_tpu.router.pools import (PoolManager,
                                                       parse_pool_spec)
        try:
            spec = parse_pool_spec(cfg.pools)
        except (ValueError, TypeError) as e:
            # a malformed pools document must not kill the watcher or
            # leave the apply half-done: keep the running table
            logger.error("dynamic config: bad pools spec (%s) — pools "
                         "left unchanged", e)
            return
        if manager is None:
            manager = PoolManager(self.state.get("router_kwargs"))
            scraper = self.state.get("scraper")
            if scraper is not None:
                manager.attach_scraper(scraper.get)
            self.state["pools"] = manager
        elif metrics is not None:
            # fold counters before any pool drops out of the table
            metrics.refresh_pools(manager)
        manager.apply(spec)
        old = self.state.get("discovery")
        if old is not manager:
            self.state["discovery"] = manager
            if old is not None:
                await old.close()

    def _apply_prefill_pool(self, cfg: DynamicRouterConfig) -> None:
        """Swap/create/disable the disagg prefill pool. The running
        orchestrator is mutated IN PLACE (set_pool) so breaker and
        rotation state survive for pool members present on both sides
        of the swap — replacing the object would amnesty a sick prefill
        backend exactly when the fleet is in motion (the bug class r11
        fixed for prefix rings)."""
        if cfg.prefill_backends is None:
            return                    # key absent: leave the pool alone
        disagg = self.state.get("disagg")
        if not cfg.prefill_backends:
            if disagg is not None:
                # fold the outgoing orchestrator's counters into the
                # exposition before its totals vanish with it, then
                # reset the delta baseline: a later enable starts a
                # fresh orchestrator from zero
                metrics = self.state.get("metrics")
                if metrics is not None:
                    metrics.refresh_disagg(disagg)
                    metrics.reset_disagg_baseline()
                del self.state["disagg"]
                logger.info("dynamic config: disaggregated prefill "
                            "disabled")
            return
        models = cfg.prefill_models or []
        if len(models) != len(cfg.prefill_backends):
            # an operator (or an actuator extra_config) shipping a
            # mismatched pool must not kill the watcher — or router
            # startup, where _check_once runs unwrapped — nor leave
            # the apply half-done: log loudly, keep the running pool
            logger.error(
                "dynamic config: %d prefill_backends but %d "
                "prefill_models — prefill pool left unchanged",
                len(cfg.prefill_backends), len(models))
            return
        if disagg is None:
            from production_stack_tpu.router.disagg import (
                build_orchestrator)
            self.state["disagg"] = build_orchestrator(
                cfg.prefill_backends, models,
                self.state.get("disagg_kwargs"))
            logger.info("dynamic config: disaggregated prefill enabled "
                        "(%d backends)", len(cfg.prefill_backends))
        else:
            # (_apply evicts departed decode engines from the selector
            # locality ring after this, for every config shape)
            disagg.set_pool(cfg.prefill_backends, models)
