"""Router stats plane: per-engine request stats + scraped engine stats.

Capability parity with reference src/vllm_router/stats/ (request_stats.py
sliding-window QPS/TTFT/latency monitor :20-282; engine_stats.py
Prometheus scraper :27-186), re-designed: one dataclass per concern, the
scraper is an asyncio task (not a thread), and histograms are simple
ring-deques trimmed on read.
"""

import asyncio
import collections
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional, Tuple

import aiohttp
from prometheus_client.parser import text_string_to_metric_families

from production_stack_tpu.signals import LoadPoller, parse_load_report
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class _Window:
    """Sliding window of (timestamp, value) pairs.

    A running sum makes ``mean`` O(popped), not O(len): under load a
    30 s arrival window holds tens of thousands of entries, and the
    stats plane reads every window on each snapshot refresh.

    ``now`` is compared with ``is None`` throughout — an explicit 0.0
    (epoch zero, which deterministic tests use as a time origin) is a
    timestamp, not "not provided".
    """

    def __init__(self, horizon_s: float):
        self.horizon = horizon_s
        self._items: Deque[Tuple[float, float]] = collections.deque()
        self._sum = 0.0

    def add(self, value: float, now: Optional[float] = None) -> None:
        if now is None:
            now = time.time()
        self._items.append((now, value))
        self._sum += value

    def _trim(self, now: float) -> None:
        cutoff = now - self.horizon
        while self._items and self._items[0][0] < cutoff:
            _, value = self._items.popleft()
            self._sum -= value
        if not self._items:
            self._sum = 0.0        # shed accumulated float drift

    def count(self, now: Optional[float] = None) -> int:
        self._trim(time.time() if now is None else now)
        return len(self._items)

    def mean(self, now: Optional[float] = None) -> float:
        self._trim(time.time() if now is None else now)
        if not self._items:
            return 0.0
        return self._sum / len(self._items)

    def rate(self, now: Optional[float] = None) -> float:
        if now is None:
            now = time.time()
        self._trim(now)
        return len(self._items) / self.horizon


@dataclass
class RequestStats:
    """Router-observed stats for one engine URL."""

    qps: float = 0.0
    ttft: float = 0.0              # mean seconds in window
    latency: float = 0.0           # mean end-to-end seconds in window
    itl: float = 0.0               # mean inter-token latency proxy
    in_flight: int = 0             # currently proxied requests
    in_prefill: int = 0            # accepted, no first byte yet
    in_decoding: int = 0           # streaming
    finished: int = 0              # total completed


class ActiveRequest:
    """Mutable per-request record handed out by ``on_new_request``.

    The proxy's streaming hot loop does a bare ``rec.tokens += 1`` per
    chunk — no dict lookup by (url, request_id) tuple key — and every
    piece of window math is deferred to ``on_request_complete``.
    """

    __slots__ = ("url", "start", "first_byte", "tokens")

    def __init__(self, url: str, start: float):
        self.url = url
        self.start = start
        self.first_byte: Optional[float] = None
        self.tokens = 0


class RequestStatsMonitor:
    """Lifecycle hooks called by the proxy; windows per engine URL.

    ``snapshot()`` is the routing-decision read: the full sliding-window
    aggregate is recomputed at most every ``snapshot_ttl_s`` seconds
    (50 ms default — far inside any horizon's resolution) while the
    in-flight counters are always read live. ``get()`` always computes
    fresh (metrics scrapes, stat logging, tests).
    """

    def __init__(self, horizon_s: float = 30.0,
                 snapshot_ttl_s: float = 0.05):
        self.horizon = horizon_s
        self.snapshot_ttl_s = snapshot_ttl_s
        self._arrivals: Dict[str, _Window] = {}
        self._ttft: Dict[str, _Window] = {}
        self._latency: Dict[str, _Window] = {}
        self._itl: Dict[str, _Window] = {}
        self._in_prefill: Dict[str, int] = collections.defaultdict(int)
        self._in_decoding: Dict[str, int] = collections.defaultdict(int)
        self._finished: Dict[str, int] = collections.defaultdict(int)
        self._snapshot: Dict[str, RequestStats] = {}
        self._snapshot_at: float = float("-inf")

    def _window(self, store: Dict[str, _Window], url: str) -> _Window:
        if url not in store:
            store[url] = _Window(self.horizon)
        return store[url]

    # lifecycle ---------------------------------------------------------

    def on_new_request(self, url: str,
                       now: Optional[float] = None) -> ActiveRequest:
        if now is None:
            now = time.time()
        self._window(self._arrivals, url).add(1.0, now)
        self._in_prefill[url] += 1
        return ActiveRequest(url, now)

    def on_first_token(self, rec: ActiveRequest,
                       now: Optional[float] = None) -> None:
        if rec.first_byte is not None:
            return
        rec.first_byte = time.time() if now is None else now
        url = rec.url
        self._in_prefill[url] = max(0, self._in_prefill[url] - 1)
        self._in_decoding[url] += 1

    def on_request_complete(self, rec: ActiveRequest,
                            now: Optional[float] = None) -> None:
        if now is None:
            now = time.time()
        url = rec.url
        first = rec.first_byte
        if first is None:
            self._in_prefill[url] = max(0, self._in_prefill[url] - 1)
        else:
            self._in_decoding[url] = max(0, self._in_decoding[url] - 1)
            # window math deferred from the hot loop; stamped with the
            # completion time (like latency/ITL) — timestamps must stay
            # monotonic within the deque or the front-trim stops early
            # and expired samples linger in the mean
            self._window(self._ttft, url).add(first - rec.start, now)
            if rec.tokens > 1:
                self._window(self._itl, url).add(
                    (now - first) / (rec.tokens - 1), now)
        self._window(self._latency, url).add(now - rec.start, now)
        self._finished[url] += 1

    def evict_except(self, live_urls) -> None:
        """Drop windows/counters for engines no longer in discovery."""
        live = set(live_urls)
        for store in (self._arrivals, self._ttft, self._latency, self._itl,
                      self._in_prefill, self._in_decoding, self._finished):
            for url in [u for u in store if u not in live]:
                del store[url]
        self._snapshot_at = float("-inf")   # force a fresh snapshot

    # reads -------------------------------------------------------------

    def get(self, now: Optional[float] = None) -> Dict[str, RequestStats]:
        if now is None:
            now = time.time()
        urls = set(self._arrivals) | set(self._in_prefill) | set(
            self._in_decoding)
        out = {}
        for url in urls:
            out[url] = RequestStats(
                qps=self._window(self._arrivals, url).rate(now),
                ttft=self._window(self._ttft, url).mean(now),
                latency=self._window(self._latency, url).mean(now),
                itl=self._window(self._itl, url).mean(now),
                in_flight=self._in_prefill[url] + self._in_decoding[url],
                in_prefill=self._in_prefill[url],
                in_decoding=self._in_decoding[url],
                finished=self._finished[url],
            )
        return out

    def snapshot(self) -> Dict[str, RequestStats]:
        """Cached window aggregates + live in-flight counters: what a
        routing decision reads. With ``snapshot_ttl_s <= 0`` this is
        exactly ``get()``."""
        now = time.time()
        if self.snapshot_ttl_s <= 0 or \
                now - self._snapshot_at >= self.snapshot_ttl_s:
            self._snapshot = self.get(now)
            self._snapshot_at = now
            return self._snapshot
        for url, st in self._snapshot.items():
            st.in_prefill = self._in_prefill.get(url, 0)
            st.in_decoding = self._in_decoding.get(url, 0)
            st.in_flight = st.in_prefill + st.in_decoding
        # an engine whose FIRST request arrived inside the TTL is not in
        # the cached dict yet — surface it with live counters (and zero
        # window aggregates) or least-loaded routing would read it as
        # idle and dogpile it until the next refresh
        for url in [u for u in set(self._in_prefill)
                    | set(self._in_decoding) if u not in self._snapshot]:
            pre = self._in_prefill.get(url, 0)
            dec = self._in_decoding.get(url, 0)
            if pre or dec:
                self._snapshot[url] = RequestStats(
                    in_prefill=pre, in_decoding=dec, in_flight=pre + dec)
        return self._snapshot


@dataclass
class EngineStats:
    """Parsed from an engine's /metrics exposition."""

    num_running: float = 0.0
    num_waiting: float = 0.0
    kv_usage: float = 0.0          # vllm:gpu_cache_usage_perc | tpu:hbm_kv
    prefix_hit_rate: float = 0.0
    # overload-protection signals (engine/metrics.py): the total
    # in-flight the engine accepts before shedding (0 = unbounded
    # admission — no per-endpoint concurrency cap derivable) and its
    # own queue-delay estimate
    capacity: float = 0.0
    est_queue_delay_ms: float = 0.0
    # tier-share signals (/load "kv_cache" block; zero for engines
    # without KV tiering): the cache-aware prefix router breaks scoring
    # ties on kv_hit_rate (routing.PrefixAwareRouter)
    kv_hit_rate: float = 0.0
    kv_hit_tokens: float = 0.0
    kv_foreign_hit_tokens: float = 0.0
    # disagg role from /load ("kv_producer"/"kv_consumer"/"kv_both";
    # "" = no KV tiering): surfaced in the stat log so a mis-wired
    # pool (a producer in the decode set) is visible at a glance
    kv_role: str = ""
    # engine-efficiency signals (/load "perf" block; zeros for
    # foreign/legacy engines): the hardware-level view next to the
    # load view — a replica at high utilization but low MBU/live
    # fraction is busy doing dead work, and compile_in_flight > 0
    # explains a latency spike without a /debug round trip
    mbu_perc: float = 0.0
    live_fraction: float = 0.0
    decode_tokens_per_s: float = 0.0
    compiles_total: float = 0.0
    compile_in_flight: float = 0.0
    # the engine's live model catalog from /load ("models": base first,
    # then every currently-loaded LoRA adapter): feeds /v1/models
    # aggregation and the pool-resolution scrape fallback — a
    # just-loaded adapter becomes routable one scrape later with no
    # config push (router/pools.py)
    served_models: Tuple[str, ...] = ()
    scraped_at: float = field(default_factory=time.time)


_WANTED_GAUGES = ("vllm:num_requests_running", "vllm:num_requests_waiting",
                  "vllm:gpu_cache_usage_perc", "tpu:hbm_kv_usage_perc",
                  "vllm:gpu_prefix_cache_hit_rate",
                  "tpu:engine_capacity_seqs", "tpu:est_queue_delay_ms")


def parse_engine_metrics(text: str) -> EngineStats:
    values: Dict[str, float] = {}
    for family in text_string_to_metric_families(text):
        if family.name in _WANTED_GAUGES:
            for sample in family.samples:
                values[family.name] = float(sample.value)
    # vllm's gauge name wins when both KV-usage spellings are exposed
    kv = values.get("vllm:gpu_cache_usage_perc",
                    values.get("tpu:hbm_kv_usage_perc", 0.0))
    return EngineStats(
        num_running=values.get("vllm:num_requests_running", 0.0),
        num_waiting=values.get("vllm:num_requests_waiting", 0.0),
        kv_usage=kv,
        prefix_hit_rate=values.get("vllm:gpu_prefix_cache_hit_rate", 0.0),
        capacity=values.get("tpu:engine_capacity_seqs", 0.0),
        est_queue_delay_ms=values.get("tpu:est_queue_delay_ms", 0.0),
        # foreign backends (no /load): the exported prefix hit rate is
        # the closest available proxy for tier-hit likelihood
        kv_hit_rate=values.get("vllm:gpu_prefix_cache_hit_rate", 0.0),
    )


class EngineStatsScraper(LoadPoller):
    """Polls every engine's /load on an interval (asyncio task).

    Built on the shared ``signals.LoadPoller`` so the endpoint-cap
    derivation (proxy._endpoint_cap), the stat logger, and an embedded
    autoscaler all read ONE scrape per engine per interval. The /load
    report is a purpose-built JSON answer — far cheaper on both sides
    than parsing a full Prometheus exposition — and carries everything
    EngineStats needs; engines that do not serve /load (a foreign
    vLLM pod behind the same router) fall back to the /metrics parse.
    """

    def __init__(self, get_endpoints, interval_s: float = 10.0):
        super().__init__(
            lambda: [ep.url for ep in get_endpoints()],
            interval_s=interval_s)
        self._stats: Dict[str, EngineStats] = {}

    def _build(self, data: dict) -> EngineStats:
        load = parse_load_report(data)
        return EngineStats(
            num_running=load.running,
            num_waiting=load.queue_depth,
            kv_usage=load.kv_usage,
            # EngineStats keeps 0.0 as its unbounded-admission sentinel
            # (pre-/load consumers pin it: see proxy._endpoint_cap)
            capacity=load.capacity if load.capacity is not None else 0.0,
            est_queue_delay_ms=load.est_queue_delay_ms,
            kv_hit_rate=load.kv_hit_rate,
            kv_hit_tokens=load.kv_hit_tokens,
            kv_foreign_hit_tokens=load.kv_foreign_hit_tokens,
            kv_role=load.kv_role,
            mbu_perc=load.mbu_perc,
            live_fraction=load.live_fraction,
            decode_tokens_per_s=load.decode_tokens_per_s,
            compiles_total=load.compiles_total,
            compile_in_flight=load.compile_in_flight,
            served_models=load.models,
        )

    async def _fetch_fallback(self, url: str) -> Optional[EngineStats]:
        try:
            async with self._session.get(f"{url}/metrics",
                                         timeout=self._timeout) as r:
                if r.status == 200:
                    return parse_engine_metrics(await r.text())
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass
        return None

    def get(self) -> Dict[str, EngineStats]:
        return dict(self._stats)


class StatLogger:
    """Periodic stat dump: one log line per engine every `interval_s`
    with request-window and scraped-engine numbers, plus a gauge refresh.

    The reference ships the same capability as a thread
    (src/vllm_router/stats/log_stats.py — whose spawn-site bug meant it
    died on first use, SURVEY.md §2.1); here it is an asyncio task owned
    by the app lifecycle.
    """

    def __init__(self, get_endpoints, monitor: "RequestStatsMonitor",
                 scraper: "EngineStatsScraper", metrics=None,
                 interval_s: float = 30.0, health_tracker=None):
        self.get_endpoints = get_endpoints
        self.monitor = monitor
        self.scraper = scraper
        self.metrics = metrics
        self.interval_s = interval_s
        self.health_tracker = health_tracker
        self._task = None

    async def start(self) -> None:
        import asyncio
        self._task = asyncio.create_task(self._loop(), name="stat-logger")

    async def close(self) -> None:
        import asyncio
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        import asyncio
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                self.log_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("stat logging failed")

    def log_once(self) -> None:
        request_stats = self.monitor.get()
        engine_stats = self.scraper.get()
        urls = sorted({ep.url for ep in self.get_endpoints()}
                      | set(request_stats) | set(engine_stats))
        if not urls:
            logger.info("stats: no engines")
        for url in urls:
            rs = request_stats.get(url)
            es = engine_stats.get(url)
            parts = [f"engine {url}"]
            if rs is not None:
                parts.append(
                    f"qps={rs.qps:.2f} ttft={rs.ttft:.3f}s "
                    f"itl={rs.itl:.4f}s latency={rs.latency:.3f}s "
                    f"in_prefill={rs.in_prefill} "
                    f"in_decoding={rs.in_decoding} "
                    f"finished={rs.finished}")
            if es is not None:
                parts.append(
                    f"running={es.num_running:.0f} "
                    f"waiting={es.num_waiting:.0f} "
                    f"kv_usage={es.kv_usage:.1%}")
                # compile_in_flight gates too: a cold engine stalled
                # on its FIRST build has zero mbu/live fraction — the
                # one moment this line exists to explain
                if es.mbu_perc or es.live_fraction \
                        or es.compile_in_flight or es.compiles_total:
                    parts.append(
                        f"mbu={es.mbu_perc:.2f}% "
                        f"live={es.live_fraction:.2f} "
                        f"compiling={es.compile_in_flight:.0f}")
            logger.info("stats: %s", " | ".join(parts))
        if self.metrics is not None:
            eps = list(self.get_endpoints())
            tracker = self.health_tracker
            healthy = len([ep for ep in eps if tracker is None
                           or tracker.is_routable(ep.url)])
            self.metrics.refresh(request_stats, healthy)
            if tracker is not None:
                self.metrics.refresh_resilience(tracker)
