"""The hot path: proxy an OpenAI request to a chosen engine, streaming.

Capability parity with reference src/vllm_router/services/request_service/
request.py:44-196 (body parse -> model filter -> route -> stream relay ->
stats hooks -> response), re-designed on one shared aiohttp
ClientSession around a zero-rework fast path:

- the body is parsed ONCE and the client's raw bytes are forwarded
  untouched unless a rewriter / cache knob / disagg hook actually
  mutated them (byte-identical passthrough is pinned by
  tests/test_router_fastpath.py);
- the static forward-header overlay (the router's engine Bearer) and
  the client timeout object are built at app startup, not per request;
- the streaming loop does one bare attribute increment per chunk on an
  ActiveRequest record (stats.py) — all window math runs at
  on_request_complete;
- routing reads RequestStatsMonitor.snapshot() (window aggregates
  cached ~50 ms, in-flight counters live) instead of recomputing every
  engine's sliding windows per request;
- small non-streaming backend responses (Content-Length present, no
  event-stream) are relayed as ONE buffered write instead of a
  prepare/chunk/eof sequence.

The committed A/B for all of this is
``python -m production_stack_tpu.loadgen overhead``
(BASELINE.md Round 7; docs/benchmarks.md "Router performance").
"""

import asyncio
import json
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.router.rewriter import NoopRequestRewriter
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

HOP_HEADERS = {"host", "content-length", "transfer-encoding", "connection",
               "keep-alive", "te", "upgrade",
               # aiohttp's client auto-decompresses, so encoding headers
               # must not leak through in either direction
               "accept-encoding", "content-encoding"}

# Router-level cache knobs (consumed by semantic_cache.SemanticCache,
# which imports this tuple): stripped from forwarded bodies because they
# are not OpenAI fields and strict backends reject unknown params.
# Defined here, not in semantic_cache, so the hot proxy path never pulls
# in numpy/kvcache when the cache gate is off.
CACHE_CONTROL_FIELDS = ("skip_cache", "cache_similarity_threshold")

# buffered-relay cap: a non-streaming backend response up to this size
# is read whole and written in one shot; anything bigger (or chunked,
# or an event stream) goes through the chunk relay loop
BUFFERED_RESPONSE_MAX = 4 * 1024 * 1024


def _copy_backend_headers(resp: web.StreamResponse,
                          backend: aiohttp.ClientResponse) -> None:
    for k, v in backend.headers.items():
        if k.lower() not in HOP_HEADERS:
            resp.headers[k] = v


def _log_store_failure(fut) -> None:
    e = fut.exception()
    if e is not None:
        logger.warning("semantic cache store failed: %s", e)


def _store_cached_response(semantic_cache, body: dict,
                           payload: bytes) -> None:
    """Fire-and-forget semantic-cache store: the sync CPU embed +
    index insert must never sit between the handler and the client."""
    try:
        response_body = json.loads(payload)
    except Exception as e:
        logger.warning("semantic cache store failed: %s", e)
        return
    fut = asyncio.get_running_loop().run_in_executor(
        None, semantic_cache.store, body, response_body)
    fut.add_done_callback(_log_store_failure)


def _forward_headers(request: web.Request, auth_overlay: dict) -> dict:
    headers = {k: v for k, v in request.headers.items()
               if k.lower() not in HOP_HEADERS}
    # membership test on the CIMultiDict (case-insensitive): a lowercase
    # 'authorization' must suppress injection too, or the upstream
    # request would carry both the client's and the router's Bearer.
    # engines enforcing ENGINE_API_KEY (engine/server.py) accept the
    # router's own key for clients trusted at the router boundary; a
    # client-provided Bearer always passes through untouched
    if auth_overlay and "Authorization" not in request.headers:
        headers.update(auth_overlay)
    return headers


async def route_general_request(request: web.Request,
                                endpoint_path: str) -> web.StreamResponse:
    """Proxy `request` to an engine chosen by the app's routing policy."""
    app = request.app
    state = app["state"]
    t_route0 = time.monotonic()

    # the PII middleware may have redacted the body (read-once request;
    # the sanitized copy is stashed on the request object)
    raw = request.get("pii_sanitized_raw") or await request.read()
    try:
        body = json.loads(raw) if raw else {}
    except (json.JSONDecodeError, UnicodeDecodeError):
        return web.json_response(
            {"error": {"message": "request body is not valid JSON",
                       "type": "invalid_request_error"}}, status=400)
    model = body.get("model")
    if not model:
        return web.json_response(
            {"error": {"message": "missing 'model' field",
                       "type": "invalid_request_error"}}, status=400)

    # optional pluggable rewrite hook (the exact noop default is
    # skipped so the fast path stays allocation-free; a SUBCLASS of the
    # noop must still be invoked)
    rewriter = state.get("rewriter")
    if rewriter is not None and \
            type(rewriter) is not NoopRequestRewriter:
        body, raw = rewriter.rewrite(endpoint_path, body, raw)

    # semantic cache short-circuit (gated; chat completions only) —
    # reference hooks the same spot (main_router.py:44-51 checks before
    # routing, request.py:113-117 stores after completion)
    semantic_cache = state.get("semantic_cache")
    check_cache = (semantic_cache is not None
                   and endpoint_path == "/v1/chat/completions")
    if check_cache:
        try:
            # embed + index search are sync CPU work — keep them off the
            # event loop so concurrent streams never stall behind them
            cached = await asyncio.get_running_loop().run_in_executor(
                None, semantic_cache.check, body)
        except Exception as e:
            logger.warning("semantic cache check failed: %s", e)
            cached = None
        if cached is not None:
            return web.json_response(cached)

    # router-level cache knobs are not OpenAI fields: strip them from the
    # forwarded bytes (strict backends reject unknown params) while the
    # local `body` keeps them for the store/capture decision below
    if any(k in body for k in CACHE_CONTROL_FIELDS):
        raw = json.dumps({k: v for k, v in body.items()
                          if k not in CACHE_CONTROL_FIELDS}).encode()

    endpoints = [ep for ep in state["discovery"].get_endpoints()
                 if ep.serves(model)]
    if not endpoints:
        return web.json_response(
            {"error": {"message": f"no backend serves model {model!r}",
                       "type": "invalid_request_error"}}, status=400)

    # routing reads the TTL-cached snapshot: window aggregates at most
    # snapshot_ttl_s stale, in-flight counters live
    request_stats = state["request_stats"].snapshot()
    url = state["router"].route(endpoints, request_stats,
                                request.headers, body)

    # disaggregated prefill: the prefill pool computes the prompt KV into
    # the shared tier (publishing chunk-by-chunk as it goes) while decode
    # routing proceeds after a bounded head-start; failures (or an open
    # breaker) degrade to a normal full prefill on the decode engine
    disagg = state.get("disagg")
    if disagg is not None:
        request_id = request.headers.get("x-request-id") or \
            uuid.uuid4().hex
        prefill_headers = {"x-request-id": request_id}
        if "Authorization" in request.headers:
            prefill_headers["Authorization"] = \
                request.headers["Authorization"]
        else:
            prefill_headers.update(state["auth_overlay"])
        await disagg.run_with_headstart(state["client"], endpoint_path,
                                        model, body,
                                        headers=prefill_headers)
    logger.debug("routed %s %s -> %s (%.2fms)", endpoint_path, model, url,
                 1e3 * (time.monotonic() - t_route0))

    monitor = state["request_stats"]
    session: aiohttp.ClientSession = state["client"]
    rec = monitor.on_new_request(url)
    resp: Optional[web.StreamResponse] = None
    try:
        async with session.post(
                f"{url}{endpoint_path}", data=raw,
                headers=_forward_headers(request, state["auth_overlay"]),
                timeout=state["client_timeout"],
        ) as backend:
            # capture the body for the semantic cache only when this
            # response is storable (non-streaming 200 on the chat path)
            capture = (check_cache and backend.status == 200
                       and semantic_cache.cacheable(body))

            length = backend.headers.get("Content-Length", "")
            if length.isdigit() and int(length) <= BUFFERED_RESPONSE_MAX \
                    and "text/event-stream" not in \
                    backend.headers.get("Content-Type", ""):
                # buffered fast path: whole body in one write (no
                # chunked framing on the client leg); first byte and
                # completion coincide
                payload = await backend.read()
                monitor.on_first_token(rec)
                rec.tokens += 1
                resp = web.Response(status=backend.status, body=payload)
                _copy_backend_headers(resp, backend)
                if capture:
                    _store_cached_response(semantic_cache, body, payload)
                return resp

            resp = web.StreamResponse(status=backend.status)
            _copy_backend_headers(resp, backend)
            await resp.prepare(request)
            captured = bytearray() if capture else None
            async for chunk in backend.content.iter_any():
                if rec.first_byte is None:
                    monitor.on_first_token(rec)
                rec.tokens += 1
                if captured is not None:
                    captured.extend(chunk)
                await resp.write(chunk)
            await resp.write_eof()
            if captured is not None:
                _store_cached_response(semantic_cache, body,
                                       bytes(captured))
            return resp
    except asyncio.TimeoutError:
        # the configured --request-timeout fired: a structured 504, not
        # an escaped-exception 500 (aiohttp's total timeout raises bare
        # asyncio.TimeoutError, which is not a ClientError)
        logger.warning("backend %s timed out after %gs", url,
                       state["request_timeout"])
        if resp is not None and resp.prepared:
            resp.force_close()
            return resp
        return web.json_response(
            {"error": {"message": f"backend timed out after "
                                  f"{state['request_timeout']:g}s",
                       "type": "timeout_error"}}, status=504)
    except (aiohttp.ClientError, ConnectionError) as e:
        logger.warning("backend %s failed: %s", url, e)
        if resp is not None and resp.prepared:
            # headers already sent — a 502 body can't be delivered; drop
            # the connection so the client sees a truncated stream, not a
            # corrupted second response on the same exchange
            resp.force_close()
            return resp
        return web.json_response(
            {"error": {"message": f"backend error: {e}",
                       "type": "server_error"}}, status=502)
    finally:
        monitor.on_request_complete(rec)
