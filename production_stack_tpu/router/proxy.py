"""The hot path: proxy an OpenAI request to a chosen engine, streaming.

Capability parity with reference src/vllm_router/services/request_service/
request.py:44-196 (body parse -> model filter -> route -> stream relay ->
stats hooks -> response), re-designed on one shared aiohttp
ClientSession around a zero-rework fast path:

- the body is parsed ONCE and the client's raw bytes are forwarded
  untouched unless a rewriter / cache knob / disagg hook actually
  mutated them (byte-identical passthrough is pinned by
  tests/test_router_fastpath.py);
- the static forward-header overlay (the router's engine Bearer) and
  the client timeout object are built at app startup, not per request;
- the streaming loop does one bare attribute increment per chunk on an
  ActiveRequest record (stats.py) — all window math runs at
  on_request_complete;
- routing reads RequestStatsMonitor.snapshot() (window aggregates
  cached ~50 ms, in-flight counters live) instead of recomputing every
  engine's sliding windows per request;
- small non-streaming backend responses (Content-Length present, no
  event-stream) are relayed as ONE buffered write instead of a
  prepare/chunk/eof sequence.

The committed A/B for all of this is
``python -m production_stack_tpu.loadgen overhead``
(BASELINE.md Round 7; docs/benchmarks.md "Router performance").

Resilience (resilience.py, BASELINE.md Round 8): candidates are
filtered to breaker-closed/non-draining endpoints before routing, and
failures occurring *before any byte reaches the client* (connect
error, refusal, timeout, backend 5xx) mark the endpoint in the health
tracker and fail over to the remaining candidates — bounded by
``--failover-attempts``, a global retry budget, and jittered backoff.
Mid-stream failures still truncate: relayed bytes cannot be replayed.
The closed loop is ``python -m production_stack_tpu.loadgen chaos``.
"""

import asyncio
import json
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.router.resilience import backoff_s
from production_stack_tpu.router.rewriter import NoopRequestRewriter
from production_stack_tpu.slo import CLASS_HEADER, classify_request
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

HOP_HEADERS = {"host", "content-length", "transfer-encoding", "connection",
               "keep-alive", "te", "upgrade",
               # aiohttp's client auto-decompresses, so encoding headers
               # must not leak through in either direction
               "accept-encoding", "content-encoding"}

# Router-level cache knobs (consumed by semantic_cache.SemanticCache,
# which imports this tuple): stripped from forwarded bodies because they
# are not OpenAI fields and strict backends reject unknown params.
# Defined here, not in semantic_cache, so the hot proxy path never pulls
# in numpy/kvcache when the cache gate is off.
CACHE_CONTROL_FIELDS = ("skip_cache", "cache_similarity_threshold")

# buffered-relay cap: a non-streaming backend response up to this size
# is read whole and written in one shot; anything bigger (or chunked,
# or an event stream) goes through the chunk relay loop
BUFFERED_RESPONSE_MAX = 4 * 1024 * 1024

# overload-protection wire signals (mirrored in engine/server.py; the
# router must not import the engine package)
DEADLINE_HEADER = "x-request-deadline-ms"
DEADLINE_MARKER = "x-deadline-expired"


def _copy_backend_headers(resp: web.StreamResponse,
                          backend: aiohttp.ClientResponse) -> None:
    for k, v in backend.headers.items():
        if k.lower() not in HOP_HEADERS:
            resp.headers[k] = v


def _log_store_failure(fut) -> None:
    e = fut.exception()
    if e is not None:
        logger.warning("semantic cache store failed: %s", e)


def _store_cached_response(semantic_cache, body: dict,
                           payload: bytes) -> None:
    """Fire-and-forget semantic-cache store: the sync CPU embed +
    index insert must never sit between the handler and the client."""
    try:
        response_body = json.loads(payload)
    except Exception as e:
        logger.warning("semantic cache store failed: %s", e)
        return
    fut = asyncio.get_running_loop().run_in_executor(
        None, semantic_cache.store, body, response_body)
    fut.add_done_callback(_log_store_failure)


class _ClientDisconnect(Exception):
    """The CLIENT side of the relay died (reset/broken pipe writing to
    it). Distinct from backend failures: it must produce no health
    signal against the engine and no retry — nobody is listening."""


class _Preempted(Exception):
    """A higher-priority request took this one's admission slot
    (router/qos.py). Raised only while the backend dispatch is in
    flight and no byte has reached the client; the handler answers a
    structured 503 + Retry-After."""


class _PreemptableRequest:
    """Races a backend dispatch against a preemption event. Wraps the
    aiohttp request context manager ONLY for preemptable-tier requests
    — the untiered/tier-0 hot path never allocates any of this."""

    __slots__ = ("_ctx", "_event")

    def __init__(self, ctx, event: asyncio.Event):
        self._ctx = ctx
        self._event = event

    async def __aenter__(self):
        req_task = asyncio.ensure_future(self._ctx.__aenter__())
        waiter = asyncio.ensure_future(self._event.wait())
        try:
            await asyncio.wait({req_task, waiter},
                               return_when=asyncio.FIRST_COMPLETED)
        except asyncio.CancelledError:
            # the HANDLER was cancelled (client disconnect): reap the
            # in-flight dispatch too — asyncio.wait never cancels its
            # pending futures, and a detached request task would pin
            # its pooled connection until GC
            req_task.cancel()
            try:
                await req_task
            except (asyncio.CancelledError, Exception):
                pass
            raise
        finally:
            waiter.cancel()
        if req_task.done():
            return req_task.result()
        # preempted mid-dispatch: cancelling the request coroutine
        # closes the backend connection, so the engine sees the abort
        req_task.cancel()
        try:
            await req_task
        except (asyncio.CancelledError, Exception):
            pass
        raise _Preempted()

    async def __aexit__(self, *exc):
        return await self._ctx.__aexit__(*exc)


# client-leg transport failures (raised by resp.prepare/write/write_eof)
_CLIENT_LEG_ERRORS = (OSError, RuntimeError, aiohttp.ClientError)


def _can_retry(attempt: int, max_attempts: int, tried: set,
               candidates, budget) -> bool:
    """Pre-stream failover gate: attempts left, an untried candidate
    left, and a retry-budget token available."""
    return (attempt < max_attempts
            and len(tried) + 1 < len(candidates)
            and (budget is None or budget.try_spend()))


def _forward_headers(request: web.Request, auth_overlay: dict,
                     deadline_overlay: Optional[dict] = None) -> dict:
    headers = {k: v for k, v in request.headers.items()
               if k.lower() not in HOP_HEADERS}
    # membership test on the CIMultiDict (case-insensitive): a lowercase
    # 'authorization' must suppress injection too, or the upstream
    # request would carry both the client's and the router's Bearer.
    # engines enforcing ENGINE_API_KEY (engine/server.py) accept the
    # router's own key for clients trusted at the router boundary; a
    # client-provided Bearer always passes through untouched
    if auth_overlay and "Authorization" not in request.headers:
        headers.update(auth_overlay)
    # deadline propagation: the client's x-request-deadline-ms passes
    # through untouched (it is not hop-by-hop); when the client sent
    # none, the router's own --request-timeout becomes the downstream
    # deadline so the engine can drop the request from its queue the
    # moment the router would have given up on it anyway
    if deadline_overlay and DEADLINE_HEADER not in request.headers:
        headers.update(deadline_overlay)
    return headers


def _endpoint_cap(state, url: str, scraper_stats=None) -> float:
    """Concurrency cap for one endpoint: the static override
    (--endpoint-inflight-cap) when set, else the capacity the engine
    advertises on /metrics (tpu:engine_capacity_seqs, scraped by
    EngineStatsScraper; 0 = unbounded admission -> no cap).
    ``scraper_stats`` lets the failover loop snapshot the scraper once
    per routing pass instead of once per candidate.

    Both caps are FLEET-wide bounds: with peer routers configured
    (shared_state.RouterPeers) each router enforces only its share —
    cap / live-router-count, floored at 1 — so N routers together
    still respect the engine's advertised capacity instead of
    N-times it, and a dead peer's share flows back to the survivors
    once gossip marks it stale."""
    peers = state.get("peers")
    share = peers.cap_share() if peers is not None else 1.0
    static = state.get("endpoint_cap") or 0
    if static > 0:
        return max(1.0, float(static) * share)
    if scraper_stats is None:
        scraper = state.get("scraper")
        if scraper is None:
            return float("inf")
        scraper_stats = scraper.get()
    es = scraper_stats.get(url)
    if es is None or es.capacity <= 0:
        return float("inf")
    return max(1.0, es.capacity * share)


def _under_cap(state, ep, request_stats, scraper_stats) -> bool:
    """Endpoint below its concurrency cap (or uncapped / never seen).
    Shared by the routing loop's under-cap filter and the disagg
    saturation pre-check — the pre-check exists to predict the loop's
    shed decision, so the two must never diverge."""
    rs = request_stats.get(ep.url)
    return rs is None or \
        rs.in_flight < _endpoint_cap(state, ep.url, scraper_stats)


def _shed_response(status: int, message: str,
                   retry_after_s: float = 1.0) -> web.Response:
    resp = web.json_response(
        {"error": {"message": message, "type": "overloaded_error"}},
        status=status)
    resp.headers["Retry-After"] = str(max(1, int(retry_after_s)))
    return resp


def _preempted_response(tier) -> web.Response:
    """Structured answer for a preempted background request: the same
    shed wire shape (503 + Retry-After), so clients back off and the
    SLO engine classifies it as intentional backpressure, never an
    availability burn."""
    return _shed_response(
        503, f"preempted: tier {tier.name} admission slot taken by "
             f"higher-priority traffic; retry later")


# response header carrying the request's trace id (stamped on EVERY
# response, sheds and errors included) so a client-side harness can
# join client-observed latency to the server-side span chain
# (docs/observability.md "Tracing"; the closed loop is
# ``python -m production_stack_tpu.loadgen trace``)
TRACE_ID_HEADER = "x-trace-id"


def _slo_observe(state, endpoint_path: str, request: web.Request,
                 resp: Optional[web.StreamResponse], trace,
                 final_status: str = "ok", tier=None) -> None:
    """Feed the SLO engine one finished request — a handful of bucket
    increments, taken from state already at hand (the response and the
    trace's phase spans). Client disconnects are skipped entirely: the
    caller vanished, so neither availability nor latency was observed
    by anyone. A QoS tier becomes the request's SLO class (unless the
    client named one explicitly) so per-tier objectives — e.g. the
    default tier0_shed_rate — see per-tier traffic."""
    slo = state.get("slo")
    if slo is None or resp is None or final_status == "client_disconnect":
        return
    cls = None
    if tier is not None:
        from production_stack_tpu.slo import CLASS_HEADER
        if CLASS_HEADER not in request.headers:
            cls = tier.name
    t0 = trace.t0
    ttft = None
    for name, kind, start, dur, _status, _attrs in trace.spans:
        if kind == "phase" and name == "backend_ttfb" \
                and start is not None:
            ttft = start + dur - t0
            break
    slo.observe_response(endpoint_path, request.headers, resp.status,
                         resp.headers, ttft_s=ttft,
                         e2e_s=time.monotonic() - t0,
                         truncated=(final_status == "truncated"),
                         cls=cls)


def _finish_trace(state, trace, status: str) -> None:
    """Seal the request trace into the ring and fold its phase spans
    into the tpu:request_phase_seconds histograms — ONE pass at request
    end, so the relay hot loop never touches histogram state. Event
    spans (abandoned failover attempts, decode-selection detail) ride
    in the trace only: phases must sum to at most the request's wall
    time or unattributed-time accounting goes negative."""
    state["tracer"].finish(trace, status)
    phases = state["metrics"].request_phases
    for name, kind, _start, dur, _status, attrs in trace.spans:
        if kind == "phase":
            phases.observe(name, (attrs or {}).get("server", ""), dur)


async def route_general_request(request: web.Request,
                                endpoint_path: str) -> web.StreamResponse:
    """Proxy `request` to an engine chosen by the app's routing policy.

    Router-wide admission gate first (--max-inflight): past the bound,
    shed with 429 + Retry-After BEFORE parsing the body — protecting
    the router's own event loop is the last line of defense when every
    engine-side bound has already been blown through."""
    state = request.app["state"]
    trace = state["tracer"].begin(request.headers.get("traceparent"),
                                  name=endpoint_path)
    # the request's SLO class rides on the trace so a cross-process
    # reader (the obsplane stitcher) can bucket fleet percentiles per
    # class without re-deriving header semantics; a QoS tier overrides
    # it below exactly the way _slo_observe's classification does
    trace.attrs["class"] = classify_request(endpoint_path,
                                            request.headers)
    max_inflight = state.get("max_inflight") or 0
    qos = state.get("qos")
    tier = None
    if qos is not None:
        # graduated, low-tier-first admission (router/qos.py): each
        # tier hits its own fraction of the --max-inflight gate, its
        # optional token bucket applies pressure or not, and a top-tier
        # arrival at the full gate may preempt a background dispatch
        # instead of shedding
        tier = qos.resolve(request.headers)
        if CLASS_HEADER not in request.headers:
            trace.attrs["class"] = tier.name
        tenant = qos.resolve_tenant(request.headers)
        verdict, _victim = qos.admit(tier, state["proxied_inflight"],
                                     max_inflight, tenant=tenant)
        if verdict == "shed":
            state["shed_counts"]["admission"] += 1
            resp = _shed_response(
                429, f"router overloaded: priority tier {tier.name} "
                     f"is past its admission bound "
                     f"({state['proxied_inflight']} in flight, "
                     f"--max-inflight {max_inflight}); retry later"
                if tenant is None else
                f"tenant {tenant} is over its per-tenant rate in "
                f"tier {tier.name}, or the tier is past its admission "
                f"bound; retry later")
            resp.headers[TRACE_ID_HEADER] = trace.trace_id
            _slo_observe(state, endpoint_path, request, resp, trace,
                         tier=tier)
            _finish_trace(state, trace, "shed")
            return resp
    elif max_inflight and state["proxied_inflight"] >= max_inflight:
        state["shed_counts"]["admission"] += 1
        resp = _shed_response(
            429, f"router overloaded: {state['proxied_inflight']} "
                 f"requests already in flight (--max-inflight "
                 f"{max_inflight}); retry later")
        resp.headers[TRACE_ID_HEADER] = trace.trace_id
        _slo_observe(state, endpoint_path, request, resp, trace)
        _finish_trace(state, trace, "shed")
        return resp
    state["proxied_inflight"] += 1
    if qos is not None:
        qos.on_start(tier)
    try:
        resp = await _proxy_request(request, endpoint_path, trace, tier)
    except BaseException as e:
        if not isinstance(e, asyncio.CancelledError):
            # an escaped handler exception becomes aiohttp's own 500 —
            # client-visible, so it must burn availability like any
            # other 5xx (a router-side bug 500ing every request is
            # exactly the outage class the SLO engine exists to catch);
            # cancellation is the client disconnecting, observed by
            # nobody
            slo = state.get("slo")
            if slo is not None:
                slo.observe_response(endpoint_path, request.headers,
                                     500, None)
        _finish_trace(state, trace, "exception")
        raise
    finally:
        state["proxied_inflight"] -= 1
        if qos is not None:
            qos.on_complete(tier)
    if resp is not None and not resp.prepared:
        # prepared (streaming / relayed) responses were stamped before
        # resp.prepare inside the relay; everything else — error JSON,
        # sheds, cache hits — is stamped here
        resp.headers[TRACE_ID_HEADER] = trace.trace_id
    status = trace.attrs.get("final_status", "ok")
    if status == "ok" and resp is not None and resp.status >= 400:
        status = f"http_{resp.status}"
    _slo_observe(state, endpoint_path, request, resp, trace, status,
                 tier=tier)
    _finish_trace(state, trace, status)
    return resp


async def _proxy_request(request: web.Request,
                         endpoint_path: str,
                         trace, tier=None) -> web.StreamResponse:
    app = request.app
    state = app["state"]
    t_route0 = time.monotonic()

    # the PII middleware may have redacted the body (read-once request;
    # the sanitized copy is stashed on the request object)
    raw = request.get("pii_sanitized_raw") or await request.read()
    try:
        body = json.loads(raw) if raw else {}
    except (json.JSONDecodeError, UnicodeDecodeError):
        return web.json_response(
            {"error": {"message": "request body is not valid JSON",
                       "type": "invalid_request_error"}}, status=400)
    model = body.get("model")
    if not model:
        return web.json_response(
            {"error": {"message": "missing 'model' field",
                       "type": "invalid_request_error"}}, status=400)

    # optional pluggable rewrite hook (the exact noop default is
    # skipped so the fast path stays allocation-free; a SUBCLASS of the
    # noop must still be invoked)
    rewriter = state.get("rewriter")
    if rewriter is not None and \
            type(rewriter) is not NoopRequestRewriter:
        body, raw = rewriter.rewrite(endpoint_path, body, raw)

    # semantic cache short-circuit (gated; chat completions only) —
    # reference hooks the same spot (main_router.py:44-51 checks before
    # routing, request.py:113-117 stores after completion)
    semantic_cache = state.get("semantic_cache")
    check_cache = (semantic_cache is not None
                   and endpoint_path == "/v1/chat/completions")
    if check_cache:
        try:
            # embed + index search are sync CPU work — keep them off the
            # event loop so concurrent streams never stall behind them
            cached = await asyncio.get_running_loop().run_in_executor(
                None, semantic_cache.check, body)
        except Exception as e:
            logger.warning("semantic cache check failed: %s", e)
            cached = None
        if cached is not None:
            trace.add_phase("admission", t_route0, time.monotonic(),
                            attrs={"semantic_cache": "hit"})
            return web.json_response(cached)

    # router-level cache knobs are not OpenAI fields: strip them from the
    # forwarded bytes (strict backends reject unknown params) while the
    # local `body` keeps them for the store/capture decision below
    if any(k in body for k in CACHE_CONTROL_FIELDS):
        raw = json.dumps({k: v for k, v in body.items()
                          if k not in CACHE_CONTROL_FIELDS}).encode()

    # named pools (router/pools.py): the model picks its pool — the
    # pool's endpoints AND its own routing-policy instance. A model no
    # pool serves is an authoritative 404 (the pools table is the
    # fleet's model catalog), not the legacy 400. Without pools the
    # single-pool path below is byte-identical to before (r7 band).
    pools = state.get("pools")
    pool_router = None
    if pools is not None and pools.active:
        model_pool = pools.resolve(model)
        if model_pool is None:
            pools.note_unknown_model()
            return web.json_response(
                {"error": {"message": f"model {model!r} is not served "
                                      f"by any pool",
                           "type": "not_found_error",
                           "code": "model_not_found"}}, status=404)
        pools.note_routed(model_pool.name)
        pool_router = model_pool.router
        candidates = list(model_pool.endpoints)
    else:
        candidates = [ep for ep in state["discovery"].get_endpoints()
                      if ep.serves(model)]
    if not candidates:
        return web.json_response(
            {"error": {"message": f"no backend serves model {model!r}",
                       "type": "invalid_request_error"}}, status=400)

    # health-aware admission: every policy sees only breaker-closed,
    # non-draining endpoints (fail-open to the full set when nothing is
    # routable — see HealthTracker.healthy_endpoints)
    health = state.get("health")
    if health is not None:
        candidates = health.healthy_endpoints(candidates)

    # admission phase: body parse, rewrite, cache check, candidate
    # discovery + health filtering — everything before the disagg
    # overlap / routing decision starts
    trace.add_phase("admission", t_route0, time.monotonic())

    # disaggregated prefill: the prefill pool computes the prompt KV into
    # the shared tier (publishing chunk-by-chunk as it goes) while decode
    # routing proceeds after a bounded head-start; failures (or an open
    # breaker) degrade to a normal full prefill on the decode engine.
    # Decode selection then goes through the orchestrator's NetKV-style
    # transfer-cost scoring (disagg.DecodeSelector) for the FIRST
    # attempt; failover re-routing stays with the normal policy.
    disagg = state.get("disagg")
    disagg_active = disagg is not None and \
        disagg.should_run(endpoint_path, body)
    disagg_digests = None
    if disagg_active:
        # decode-side saturation pre-check: with EVERY candidate at its
        # concurrency cap the routing loop below sheds 503 — dispatching
        # the prefill first would burn a producer pass on a request
        # that is never served AND delay that shed by the head-start
        # (defeating Retry-After's fast-backoff intent)
        _stats0 = state["request_stats"].snapshot()
        _scraper0 = state.get("scraper")
        _sstats0 = _scraper0.get() if _scraper0 is not None else {}
        if not any(_under_cap(state, ep, _stats0, _sstats0)
                   for ep in candidates):
            disagg_active = False
    if disagg_active:
        request_id = request.headers.get("x-request-id") or \
            uuid.uuid4().hex
        # the producer's engine-side spans must join the same trace as
        # the decode engine's (router->prefill->decode chain)
        prefill_headers = {"x-request-id": request_id,
                           "traceparent": trace.child_traceparent()}
        if "Authorization" in request.headers:
            prefill_headers["Authorization"] = \
                request.headers["Authorization"]
        else:
            prefill_headers.update(state["auth_overlay"])
        # hash the prompt once; the same digest list feeds the prefill
        # dispatch, decode selection, and the locality-ring record
        disagg_digests = disagg.digests(body)
        t_pf0 = time.monotonic()
        await disagg.run_with_headstart(state["client"], endpoint_path,
                                        model, body,
                                        headers=prefill_headers,
                                        digests=disagg_digests,
                                        trace=trace)
        # the serialization the CLIENT pays before decode routing: the
        # bounded head-start wait (the prefill pass itself, which may
        # keep running in the background, is the "prefill" event span
        # the orchestrator records)
        trace.add_phase("prefill_dispatch", t_pf0, time.monotonic())

    monitor = state["request_stats"]
    session: aiohttp.ClientSession = state["client"]
    # tiered deadline budgets: the overlay injected when the client
    # sent no deadline shrinks with the tier's admit fraction, so under
    # queue buildup the engine's expiry sweep drops background work
    # first (router/qos.py "deadline budgets, low-tier-first")
    deadline_overlay = state.get("deadline_overlay")
    if tier is not None:
        overlays = state.get("qos_deadline_overlays")
        if overlays is not None:
            deadline_overlay = overlays[tier.index]
    fwd_headers = _forward_headers(request, state["auth_overlay"],
                                   deadline_overlay)
    # the engine parents its spans onto the ROUTER's span (a client-
    # supplied traceparent became this trace's parent in begin(), so
    # the client's own context is replaced, not forwarded verbatim)
    fwd_headers["traceparent"] = trace.child_traceparent()
    budget = state.get("retry_budget")
    if budget is not None:
        budget.on_request()
    max_attempts = state.get("failover_attempts", 1)
    tried: set = set()
    attempt = 0
    last_failure = ""      # human-readable cause of the final attempt
    timed_out = False      # 504 vs 502 on exhaustion
    shed_rerouted = False  # one re-route per request on upstream shed
    prefer_least_loaded = False
    last_was_shed = False  # exhaustion after a shed relays 503, not 502

    # preemption surface (router/qos.py): background-tier requests
    # register while their backend dispatch is in flight; a top-tier
    # arrival at the full admission gate may take the slot
    qos = state.get("qos")
    preempt_event: Optional[asyncio.Event] = None
    preempt_slot = None
    if qos is not None and tier is not None \
            and tier.index >= qos.preempt_from:
        preempt_event = asyncio.Event()
        preempt_slot = qos.register_preemptable(tier, preempt_event)

    # bounded pre-stream failover loop: a connect error, refusal,
    # timeout, or backend 5xx *before any byte reached the client* marks
    # the endpoint in the health tracker and re-routes among the
    # remaining candidates (jittered backoff, global retry budget).
    # Once bytes have been relayed the stream can only truncate — bytes
    # cannot be replayed.
    try:
      while True:
        if preempt_event is not None and preempt_event.is_set():
            # preempted between attempts: the slot is already gone
            trace.attrs["final_status"] = "preempted"
            return _preempted_response(tier)
        # re-read the CONFIGURED fleet each attempt: a dynamic-config
        # apply that removed an endpoint mid-failover must not see it
        # resurrected from this loop's captured candidate list
        # (pinned by tests/test_router_resilience.py)
        live = {ep.url
                for ep in state["discovery"].all_endpoints()}
        pool = [ep for ep in candidates
                if ep.url not in tried and ep.url in live]
        if not pool:
            break
        if attempt > 0:
            # de-synchronize concurrent failovers off a dying endpoint
            t_bo = time.monotonic()
            await asyncio.sleep(backoff_s(attempt))
            trace.add_phase("backoff", t_bo, time.monotonic())
        t_route = time.monotonic()
        # routing reads the TTL-cached snapshot: window aggregates at
        # most snapshot_ttl_s stale, in-flight counters live
        request_stats = state["request_stats"].snapshot()
        # per-endpoint concurrency cap (advertised engine capacity or
        # --endpoint-inflight-cap): endpoints already at their cap are
        # invisible to routing; with EVERY candidate at its cap the
        # router sheds here instead of piling more onto engines that
        # would only shed it themselves one hop later
        scraper = state.get("scraper")
        scraper_stats = scraper.get() if scraper is not None else {}
        under_cap = [ep for ep in pool
                     if _under_cap(state, ep, request_stats,
                                   scraper_stats)]
        if under_cap:
            pool = under_cap
        elif attempt == 0:
            state["shed_counts"]["endpoint_cap"] += 1
            return _shed_response(
                503, "all backends at their concurrency cap; retry "
                     "after the indicated delay")
        else:
            break      # mid-failover: relay the recorded failure
        if prefer_least_loaded:
            # post-shed re-route: go straight to the least-loaded
            # healthy endpoint (the policy's pick — e.g. a sticky
            # session's home — is the one that just shed); the ring
            # itself is untouched, so the session is NOT rehomed
            prefer_least_loaded = False
            url = min(pool, key=lambda ep:
                      request_stats[ep.url].in_flight
                      if ep.url in request_stats else 0).url
        else:
            url = None
            if disagg_active and attempt == 0:
                # two-stage decode selection: expected KV transfer
                # bytes vs scraped load; None (cold prefix / selection
                # disabled) falls through to the routing policy
                explain: dict = {}
                url = disagg.select_decode(body, pool, request_stats,
                                           scraper_stats,
                                           digests=disagg_digests,
                                           explain=explain)
                if explain:
                    # per-candidate transfer-cost inputs, in the trace
                    # only (event): the "why this decode engine" record
                    trace.add_event("decode_select", t_route,
                                    time.monotonic() - t_route,
                                    status=("cost" if url is not None
                                            else "abstain"),
                                    attrs=explain)
            if url is None:
                # the pool's own policy instance when pools are active
                # (its ring/ramp state is pool-scoped); the app-wide
                # router otherwise
                router = pool_router if pool_router is not None \
                    else state["router"]
                url = router.route(pool, request_stats,
                                   request.headers, body)
        if disagg_active:
            # the chosen decode engine will fetch-or-compute the
            # prompt chunks and hold them locally afterwards. Recorded
            # on EVERY attempt — failover re-routes and post-shed
            # least-loaded picks included (like the prefix ring, which
            # records inside route()) — and taken back out by
            # on_decode_failed when the attempt dies before a byte
            # reaches the client: only the engine that actually pulled
            # the KV stays credited
            disagg.on_decode_routed(body, url, digests=disagg_digests)
        attempt += 1
        t_attempt = time.monotonic()
        # routing phase: snapshot read + cap filter + policy/cost pick
        # (one span per attempt; histogram counts therefore tally
        # routing PASSES, not requests, under failover)
        trace.add_phase("routing", t_route, t_attempt,
                        attrs={"server": ""})
        if attempt == 1:
            logger.debug("routed %s %s -> %s (%.2fms)", endpoint_path,
                         model, url,
                         1e3 * (time.monotonic() - t_route0))
        rec = monitor.on_new_request(url)
        resp: Optional[web.StreamResponse] = None
        retry_cause: Optional[str] = None
        t_hdrs: Optional[float] = None   # backend headers received at
        decode_failed = False   # pre-stream failure: un-credit locality
        try:
            post_cm = session.post(
                f"{url}{endpoint_path}", data=raw,
                headers=fwd_headers,
                timeout=state["client_timeout"])
            if preempt_event is not None:
                # background tier: the dispatch races the preemption
                # event (the hot path takes the bare context manager)
                post_cm = _PreemptableRequest(post_cm, preempt_event)
            async with post_cm as backend:
                t_hdrs = time.monotonic()
                if preempt_slot is not None:
                    # the engine answered: preempting past this point
                    # saves almost nothing, so leave the registry now
                    # and close the picked-but-already-streaming race
                    qos.unregister_preemptable(preempt_slot)
                    preempt_slot = None
                    preempt_event = None
                shed = (backend.status in (429, 503)
                        and "Retry-After" in backend.headers)
                if shed:
                    # overload shed: the engine is healthy but full.
                    # NEVER a breaker signal (resilience.record_shed);
                    # re-route ONCE to the least-loaded healthy
                    # endpoint, then relay the 503/Retry-After so the
                    # client backs off instead of the router amplifying
                    # the overload with retries
                    if health is not None:
                        health.record_shed(url)
                    last_failure = f"backend shed (HTTP {backend.status})"
                    last_was_shed = True
                    decode_failed = True
                    if not shed_rerouted and _can_retry(
                            attempt, max_attempts, tried, candidates,
                            budget):
                        shed_rerouted = True
                        prefer_least_loaded = True
                        retry_cause = "shed"
                        continue
                elif (backend.status == 504
                        and DEADLINE_MARKER in backend.headers):
                    # the CLIENT's deadline expired in the engine's
                    # queue: relay verbatim — re-trying a request whose
                    # budget is spent helps nobody, and the engine did
                    # nothing wrong (no breaker signal)
                    if health is not None:
                        health.record_deadline_relay(url)
                elif backend.status >= 500:
                    # upstream failure that never reached the client:
                    # breaker signal, then either fail over or (when
                    # retries are exhausted) relay the backend's answer
                    if health is not None:
                        health.record_failure(url, "http_5xx")
                    last_failure = f"backend HTTP {backend.status}"
                    last_was_shed = False
                    decode_failed = True
                    if _can_retry(attempt, max_attempts, tried,
                                  candidates, budget):
                        retry_cause = last_failure
                        continue
                    if health is not None:
                        health.note_relayed_5xx(url)
                elif health is not None:
                    health.record_success(url)

                # capture the body for the semantic cache only when this
                # response is storable (non-streaming 200 on the chat
                # path)
                capture = (check_cache and backend.status == 200
                           and semantic_cache.cacheable(body))

                length = backend.headers.get("Content-Length", "")
                if length.isdigit() and \
                        int(length) <= BUFFERED_RESPONSE_MAX \
                        and "text/event-stream" not in \
                        backend.headers.get("Content-Type", ""):
                    # buffered fast path: whole body in one write (no
                    # chunked framing on the client leg); first byte and
                    # completion coincide
                    payload = await backend.read()
                    monitor.on_first_token(rec)
                    rec.tokens += 1
                    resp = web.Response(status=backend.status,
                                        body=payload)
                    _copy_backend_headers(resp, backend)
                    resp.headers[TRACE_ID_HEADER] = trace.trace_id
                    trace.add_phase("backend_ttfb", t_attempt, t_hdrs,
                                    attrs={"server": url})
                    trace.add_phase("relay", t_hdrs, time.monotonic(),
                                    attrs={"server": url})
                    if capture:
                        _store_cached_response(semantic_cache, body,
                                               payload)
                    return resp

                resp = web.StreamResponse(status=backend.status)
                _copy_backend_headers(resp, backend)
                resp.headers[TRACE_ID_HEADER] = trace.trace_id
                trace.add_phase("backend_ttfb", t_attempt, t_hdrs,
                                attrs={"server": url})
                try:
                    await resp.prepare(request)
                except _CLIENT_LEG_ERRORS as e:
                    raise _ClientDisconnect() from e
                captured = bytearray() if capture else None
                async for chunk in backend.content.iter_any():
                    if rec.first_byte is None:
                        monitor.on_first_token(rec)
                    rec.tokens += 1
                    if captured is not None:
                        captured.extend(chunk)
                    # inline (not a helper coroutine): this is the
                    # per-chunk hot loop
                    try:
                        await resp.write(chunk)
                    except _CLIENT_LEG_ERRORS as e:
                        raise _ClientDisconnect() from e
                try:
                    await resp.write_eof()
                except _CLIENT_LEG_ERRORS as e:
                    raise _ClientDisconnect() from e
                trace.add_phase("relay", t_hdrs, time.monotonic(),
                                attrs={"server": url})
                if captured is not None:
                    _store_cached_response(semantic_cache, body,
                                           bytes(captured))
                return resp
        except _Preempted:
            # a higher-priority request took this slot mid-dispatch:
            # structured 503 + Retry-After (the engine saw the abort;
            # no health signal — nothing is wrong with it)
            decode_failed = True
            trace.attrs["final_status"] = "preempted"
            return _preempted_response(tier)
        except _ClientDisconnect:
            # the client vanished mid-relay; the backend did nothing
            # wrong (a few users hitting stop must not trip a healthy
            # engine's breaker)
            logger.debug("client disconnected during relay from %s",
                         url)
            if t_hdrs is not None:
                trace.add_phase("relay", t_hdrs, time.monotonic(),
                                status="client_disconnect",
                                attrs={"server": url})
            trace.attrs["final_status"] = "client_disconnect"
            if resp is not None and resp.prepared:
                resp.force_close()
            return resp
        except asyncio.TimeoutError:
            # the configured --request-timeout fired: a structured 504,
            # not an escaped-exception 500 (aiohttp's total timeout
            # raises bare asyncio.TimeoutError, not a ClientError)
            logger.warning("backend %s timed out after %gs", url,
                           state["request_timeout"])
            if resp is not None and resp.prepared:
                if health is not None:
                    health.record_failure(url, "mid_stream")
                if t_hdrs is not None:
                    trace.add_phase("relay", t_hdrs, time.monotonic(),
                                    status="truncated",
                                    attrs={"server": url})
                trace.attrs["final_status"] = "truncated"
                resp.force_close()
                return resp
            if health is not None:
                health.record_failure(url, "timeout")
            last_failure = (f"backend timed out after "
                            f"{state['request_timeout']:g}s")
            timed_out = True
            decode_failed = True
            last_was_shed = False
            if _can_retry(attempt, max_attempts, tried, candidates,
                          budget):
                retry_cause = "timeout"
                continue
        except (aiohttp.ClientError, ConnectionError) as e:
            logger.warning("backend %s failed: %s", url, e)
            if resp is not None and resp.prepared:
                # headers already sent — a 502 body can't be delivered;
                # drop the connection so the client sees a truncated
                # stream, not a corrupted second response on the same
                # exchange
                if health is not None:
                    health.record_failure(url, "mid_stream")
                if t_hdrs is not None:
                    trace.add_phase("relay", t_hdrs, time.monotonic(),
                                    status="truncated",
                                    attrs={"server": url})
                trace.attrs["final_status"] = "truncated"
                resp.force_close()
                return resp
            if health is not None:
                health.record_failure(url, "connect")
            last_failure = f"backend error: {e}"
            timed_out = False
            decode_failed = True
            last_was_shed = False
            if _can_retry(attempt, max_attempts, tried, candidates,
                          budget):
                retry_cause = str(e)
                continue
        finally:
            monitor.on_request_complete(rec)
            if decode_failed and disagg_active:
                # a shed/failed pick never pulled the KV: take the
                # route-time credit back out, or its low in-flight
                # keeps winning the load tiebreak at phantom-zero
                # transfer cost for the prefixes it keeps refusing
                disagg.on_decode_failed(body, url,
                                        digests=disagg_digests)
            if retry_cause is not None:
                # abandoned attempt: an EVENT span, never a phase — its
                # wall time must not double-count against the winning
                # attempt's backend_ttfb in the histograms (the trace
                # still shows exactly where the failover time went)
                trace.add_event("backend_attempt", t_attempt,
                                time.monotonic() - t_attempt,
                                status="abandoned",
                                attrs={"server": url,
                                       "cause": retry_cause})
                tried.add(url)
                if health is not None:
                    health.note_retry(url)
                logger.info("failing over from %s after %s "
                            "(attempt %d/%d)", url, retry_cause,
                            attempt, max_attempts)
        break
    finally:
        if qos is not None:
            qos.unregister_preemptable(preempt_slot)

    # all attempts exhausted before a byte reached the client
    if timed_out:
        return web.json_response(
            {"error": {"message": last_failure or "backend timed out",
                       "type": "timeout_error"}}, status=504)
    if last_was_shed:
        # the final word was an overload shed (e.g. shed -> re-route ->
        # every remaining candidate at its cap): the client must see
        # the back-off signal, not a sick-fleet 502
        return _shed_response(503, last_failure or "backend shed")
    return web.json_response(
        {"error": {"message": last_failure or "no routable backend",
                   "type": "server_error"}}, status=502)
