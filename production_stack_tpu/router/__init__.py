"""OpenAI-compatible L7 request router.

Capability parity with the reference router (reference: src/vllm_router/,
SURVEY.md §2.1) — service discovery (static + Kubernetes watch), routing
policies (round-robin, session consistent-hash, least-loaded, prefix
KV-affinity), engine/request stats planes, dynamic config hot-reload,
feature gates, files/batches APIs — re-designed as a single-event-loop
asyncio application (the reference mixes daemon threads with asyncio;
here every background activity is a cancellable asyncio task) on aiohttp
instead of FastAPI.
"""
