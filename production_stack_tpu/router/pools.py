"""Named pools: model -> endpoints -> routing policy (heterogeneous fleet).

One router fronting a heterogeneous fleet — different base models,
different LoRA adapter sets, prefill vs decode roles — needs a layer
between "the request named model X" and "run routing policy P over
endpoint set E". That layer is the pool:

- A **pool spec** maps a pool name to its backends, the models every
  backend in the pool serves (first = base, rest = adapters/aliases),
  and its routing policy::

      {"pool-a": {"backends": ["http://10.0.0.3:8100"],
                  "models": ["llama-3-8b", "sql-adapter"],
                  "routing_logic": "prefix",
                  "session_key": "x-user-id"},
       "pool-b": {"backends": [...], "models": ["qwen-7b"]}}

  Delivered at startup (``--pools``, inline JSON or @file) or hot via
  the dynamic-config ``pools`` key (tri-state like ``prefill_backends``:
  absent = leave the running pools alone, ``{}`` = disable pooling,
  non-empty = swap in place).

- **Model resolution** happens once per request in the proxy: the
  body's ``model`` picks the pool; its endpoints and ITS router
  instance serve the request. A model no pool serves is a structured
  404 (``model_not_found``) — distinct from 400 (malformed) and from
  the legacy single-pool "no backend serves model" 400, because with
  pools active the router authoritatively knows the fleet's model
  catalog. Adapters loaded at runtime (``/admin/lora/load``) become
  resolvable through the scraped ``/load`` ``models`` list without a
  config push — resolution falls back to the scrape on an index miss.

- **Per-pool policy state survives swaps of other pools** (the r11/r12
  state-survival contract at the pool layer): ``apply()`` diffs specs
  pool-by-pool and keeps the existing ``Pool`` object — its router
  instance, with the prefix ring / session ring / slow-start state
  inside — whenever the pool's routing fields are unchanged. Breaker
  and drain state live in the ONE HealthTracker keyed by URL, so they
  were never per-pool objects to lose; request-stats windows key by
  URL likewise. Only the pool you actually reconfigure pays.

PoolManager IS a ServiceDiscovery (duck-typed): when pools are active
it replaces ``state["discovery"]``, so every fleet-wide consumer —
the stats scraper, /health endpoint counts, /metrics eviction sweeps,
the proxy's live-set re-read — sees the union of all pools without
learning a second membership API.

Closed loop: ``python -m production_stack_tpu.loadgen multitenant``
(TENANT_r21.json; docs/multitenancy.md).
"""

import collections
import json
from typing import Dict, List, Optional

from production_stack_tpu.router.routing import make_router
from production_stack_tpu.router.service_discovery import (EndpointInfo,
                                                           ServiceDiscovery)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


def parse_pool_spec(raw) -> Dict[str, dict]:
    """Normalize a pools document: ``{name: {backends, models,
    routing_logic?, session_key?}}``. Accepts the JSON text form (CLI)
    or an already-parsed dict (dynamic config). Raises ValueError on a
    malformed spec — callers at startup fail fast, the config watcher
    logs and keeps the running pools."""
    if isinstance(raw, str):
        raw = json.loads(raw)
    if not isinstance(raw, dict):
        raise ValueError(f"pools spec must be an object, got "
                         f"{type(raw).__name__}")
    out: Dict[str, dict] = {}
    for name, spec in raw.items():
        if not isinstance(spec, dict):
            raise ValueError(f"pool {name!r}: spec must be an object")
        backends = [u.rstrip("/") for u in spec.get("backends") or []]
        models = list(spec.get("models") or [])
        if not backends:
            raise ValueError(f"pool {name!r}: no backends")
        if not models:
            raise ValueError(f"pool {name!r}: no models")
        out[name] = {
            "backends": backends,
            "models": models,
            "routing_logic": spec.get("routing_logic", "roundrobin"),
            "session_key": spec.get("session_key", "x-user-id"),
        }
    return out


class Pool:
    """One named pool: endpoints + its own routing-policy instance."""

    __slots__ = ("name", "backends", "models", "routing_logic",
                 "session_key", "router", "endpoints")

    def __init__(self, name: str, spec: dict, router):
        self.name = name
        self.router = router
        self.rebuild(spec)

    def rebuild(self, spec: dict) -> None:
        self.backends = list(spec["backends"])
        self.models = list(spec["models"])
        self.routing_logic = spec["routing_logic"]
        self.session_key = spec["session_key"]
        base = self.models[0]
        aliases = self.models[1:]
        self.endpoints = [
            EndpointInfo(url=u, model=base, model_aliases=list(aliases),
                         pool=self.name)
            for u in self.backends]

    def routing_unchanged(self, spec: dict) -> bool:
        """True when the new spec keeps this pool's policy fields —
        the condition under which the router INSTANCE (and its learned
        ring/ramp state) must survive the swap."""
        return (self.routing_logic == spec["routing_logic"]
                and self.session_key == spec["session_key"])


class PoolManager(ServiceDiscovery):
    """The pools table + model->pool resolution + fleet-union discovery.

    Counters (``routed``/``unknown_models``) are plain ints keyed by
    pool NAME in the manager — not on Pool objects — so a pool swap
    never resets them (they delta-sync into ``tpu:router_pool_*`` at
    scrape, the r12 convention)."""

    def __init__(self, router_kwargs: Optional[dict] = None):
        self._pools: Dict[str, Pool] = {}
        self._index: Dict[str, Pool] = {}
        self._router_kwargs = dict(router_kwargs or {})
        self._scraper_get = None
        # telemetry: requests routed per pool, unknown-model 404s,
        # pool swap generations (survive Pool object replacement)
        self.routed: Dict[str, int] = collections.defaultdict(int)
        self.unknown_models = 0
        self.swaps: Dict[str, int] = collections.defaultdict(int)

    # -- discovery interface (the fleet union) --------------------------

    def get_endpoints(self) -> List[EndpointInfo]:
        return [ep for p in self._pools.values() for ep in p.endpoints]

    def all_endpoints(self) -> List[EndpointInfo]:
        return self.get_endpoints()

    async def start(self) -> None:
        pass

    async def close(self) -> None:
        pass

    # -- lifecycle -------------------------------------------------------

    def attach_scraper(self, get_stats) -> None:
        """Scrape fallback for resolve(): adapters loaded at runtime
        surface in each engine's /load ``models`` list one scrape
        interval later, with no config push."""
        self._scraper_get = get_stats

    def apply(self, spec: Dict[str, dict]) -> List[str]:
        """Diff-and-swap the pools table in place; returns the names of
        pools that were dropped (callers fold their metrics first if
        they need to — the manager's own counters persist regardless).

        Per pool: unchanged routing fields keep the existing Pool and
        router instance (state survival); changed routing fields build
        a fresh router; new pools are created; absent pools dropped."""
        dropped = [n for n in self._pools if n not in spec]
        for name in dropped:
            logger.info("pool %s dropped", name)
            del self._pools[name]
        for name, pspec in spec.items():
            pool = self._pools.get(name)
            if pool is None:
                router = self._make_router(pspec)
                self._pools[name] = Pool(name, pspec, router)
                self.swaps[name] += 1
                logger.info("pool %s created: %d backends, models %s, "
                            "routing %s", name, len(pspec["backends"]),
                            pspec["models"], pspec["routing_logic"])
            elif pool.routing_unchanged(pspec):
                # membership/model change only: the router instance —
                # and its prefix/session ring, slow-start ramps — is
                # kept; consistent hashing absorbs the member diff
                if (pool.backends != pspec["backends"]
                        or pool.models != pspec["models"]):
                    pool.rebuild(pspec)
                    self.swaps[name] += 1
                    logger.info("pool %s membership swapped in place "
                                "(%d backends)", name,
                                len(pspec["backends"]))
            else:
                pool.router = self._make_router(pspec)
                pool.rebuild(pspec)
                self.swaps[name] += 1
                logger.info("pool %s routing changed -> %s (fresh "
                            "policy state)", name, pspec["routing_logic"])
        self._index = {m: p for p in self._pools.values()
                       for m in p.models}
        return dropped

    def _make_router(self, pspec: dict):
        router = make_router(pspec["routing_logic"], pspec["session_key"],
                             **self._router_kwargs)
        if self._scraper_get is not None and \
                hasattr(router, "attach_scraper"):
            router.attach_scraper(self._scraper_get)
        return router

    # -- request path ----------------------------------------------------

    @property
    def active(self) -> bool:
        return bool(self._pools)

    def resolve(self, model: str) -> Optional[Pool]:
        """Model name -> pool, or None (the proxy answers 404).
        Static index first (the hot path: one dict get), then endpoint
        aliases (probe-promoted), then the scraped /load ``models``
        lists — the path a just-loaded adapter takes until the next
        config push."""
        pool = self._index.get(model)
        if pool is not None:
            return pool
        for p in self._pools.values():
            for ep in p.endpoints:
                if ep.serves(model):
                    return p
        if self._scraper_get is not None:
            by_url = {ep.url: p for p in self._pools.values()
                      for ep in p.endpoints}
            for url, es in self._scraper_get().items():
                p = by_url.get(url)
                if p is not None and \
                        model in getattr(es, "served_models", ()):
                    return p
        return None

    def note_routed(self, pool_name: str) -> None:
        self.routed[pool_name] += 1

    def note_unknown_model(self) -> None:
        self.unknown_models += 1

    # -- introspection ---------------------------------------------------

    def served_models(self) -> List[str]:
        """Every model the pools table names, pool order preserved."""
        seen = []
        for p in self._pools.values():
            for m in p.models:
                if m not in seen:
                    seen.append(m)
        return seen

    def snapshot(self) -> dict:
        return {name: {
            "backends": list(p.backends),
            "models": list(p.models),
            "routing_logic": p.routing_logic,
            "routed": self.routed.get(name, 0),
            "swaps": self.swaps.get(name, 0),
        } for name, p in self._pools.items()}
