"""Router resilience layer: per-endpoint health, failover policy, drain.

The router hides a fleet of mortal engine replicas behind one stable
endpoint; this module is where "mortal" is handled. Three pieces, all
consumed by proxy.py / routing-adjacent code through ``app['state']``:

- ``HealthTracker`` — per-endpoint circuit breaker fed by *passive*
  signals from the data plane (connect errors, timeouts, backend 5xx,
  mid-stream deaths, probe failures). closed → open on K consecutive
  failures or a windowed failure rate; open → half_open after a
  cooldown; half_open → closed only after an *active* ``/v1/models``
  re-probe succeeds (a dead pod must prove it is back before sessions
  return to it). Also owns graceful per-endpoint drain state: a
  draining endpoint takes no new admissions while its in-flight
  requests finish on the proxy's existing connections.
- ``RetryBudget`` — a global token bucket bounding failover retries to
  a fraction of live traffic, so a fleet-wide outage degrades to
  fast-failing requests instead of a router-amplified retry storm.
- ``backoff_s`` / ``wait_for_drain`` — jittered-backoff and
  listener-drain helpers for the proxy's failover loop and the app's
  SIGTERM path.

Everything here is event-loop-single-threaded (like the rest of the
router): no locks, mutations happen on the loop. The only network I/O
is the active re-probe task started by ``start()``.
"""

import asyncio
import collections
import random
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from production_stack_tpu.router.service_discovery import (EndpointInfo,
                                                           probe_model_name)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# passive failure kinds the data plane reports (metrics label values).
# The first five feed the breaker; the INFORMATIONAL kinds are counted
# in vllm:upstream_failures_total but NEVER enter breaker math —
# "shed" (429/503 + Retry-After: the engine is healthy but full; see
# record_shed) and "deadline" (engine 504 + x-deadline-expired: the
# CLIENT's budget elapsed, nothing is wrong with the engine).
FAILURE_KINDS = ("connect", "timeout", "http_5xx", "mid_stream", "probe")
INFORMATIONAL_KINDS = ("shed", "deadline")

# "no transition ever" sentinel age for the peer-gossip payloads
# (float('inf') is not valid JSON)
NEVER_AGE = 1e9


class _EndpointHealth:
    __slots__ = ("state", "consecutive", "outcomes", "open_until",
                 "opened_at", "probing", "opens", "transition_at")

    def __init__(self):
        self.state = CLOSED
        self.consecutive = 0
        # (timestamp, ok) ring for the windowed failure-rate trip
        self.outcomes: Deque[Tuple[float, bool]] = collections.deque()
        self.open_until = 0.0
        self.opened_at = 0.0
        self.probing = False
        self.opens = 0
        # when this endpoint last crossed open<->closed — the peer
        # gossip layer (shared_state.py) compares transition AGES so
        # two routers agree on which of them saw the newer event
        # without sharing a clock
        self.transition_at: Optional[float] = None


class HealthTracker:
    """Per-endpoint breaker + drain state + resilience counters.

    ``is_routable`` is the single question routing asks; ``record_*``
    are the passive signals the proxy feeds; ``start()`` owns the
    active half-open re-probe loop.
    """

    def __init__(self, failure_threshold: int = 3,
                 cooldown_s: float = 5.0,
                 window_s: float = 30.0,
                 failure_rate: float = 0.5,
                 min_samples: int = 20,
                 probe_interval_s: float = 1.0,
                 now_fn: Callable[[], float] = time.monotonic):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.window_s = window_s
        self.failure_rate = failure_rate
        self.min_samples = min_samples
        self.probe_interval_s = probe_interval_s
        self._now = now_fn
        self._eps: Dict[str, _EndpointHealth] = {}
        self._draining: set = set()
        # url -> (draining bool, stamped at): drain TRANSITIONS carry
        # ages through peer gossip the same way breaker transitions do
        # (an /admin/drain lands on ONE router; its peers must learn)
        self._drain_events: Dict[str, Tuple[bool, float]] = {}
        # peer-adoption telemetry (shared_state.py feeds these)
        self.peer_adopted_opens = 0
        self.peer_adopted_closes = 0
        # counters exported by RouterMetrics.refresh_resilience
        self.failures: Dict[Tuple[str, str], int] = \
            collections.defaultdict(int)
        self.retries: Dict[str, int] = collections.defaultdict(int)
        self.relayed_5xx: Dict[str, int] = collections.defaultdict(int)
        self.breaker_opens = 0
        self.recoveries = 0
        self._probe_task: Optional[asyncio.Task] = None

    # -- breaker state machine ------------------------------------------

    def _h(self, url: str) -> _EndpointHealth:
        h = self._eps.get(url)
        if h is None:
            h = self._eps[url] = _EndpointHealth()
        return h

    def _note(self, h: _EndpointHealth, ok: bool, now: float) -> None:
        h.outcomes.append((now, ok))
        cutoff = now - self.window_s
        while h.outcomes and h.outcomes[0][0] < cutoff:
            h.outcomes.popleft()

    def _rate_tripped(self, h: _EndpointHealth) -> bool:
        n = len(h.outcomes)
        if n < self.min_samples:
            return False
        fails = sum(1 for _, ok in h.outcomes if not ok)
        return fails / n >= self.failure_rate

    def _open(self, url: str, h: _EndpointHealth, why: str) -> None:
        now = self._now()
        h.state = OPEN
        h.opened_at = now
        h.open_until = now + self.cooldown_s
        h.opens += 1
        h.probing = False
        h.transition_at = now
        self.breaker_opens += 1
        logger.warning("breaker OPEN for %s (%s; cooldown %.1fs)",
                       url, why, self.cooldown_s)

    def _close(self, url: str, h: _EndpointHealth, why: str) -> None:
        if h.state != CLOSED:
            self.recoveries += 1
            h.transition_at = self._now()
            logger.info("breaker CLOSED for %s (%s)", url, why)
        h.state = CLOSED
        h.consecutive = 0
        h.probing = False
        h.outcomes.clear()

    def record_success(self, url: str) -> None:
        h = self._eps.get(url)
        if h is None:
            return          # endpoints start healthy; nothing to track
        h.consecutive = 0
        self._note(h, True, self._now())
        if h.state != CLOSED:
            # a real request succeeded while the breaker was open (the
            # all-unroutable fallback sent it): as good as a probe
            self._close(url, h, "request succeeded")

    def record_failure(self, url: str, kind: str) -> None:
        self.failures[(url, kind)] += 1
        h = self._h(url)
        h.consecutive += 1
        now = self._now()
        self._note(h, False, now)
        if h.state == HALF_OPEN:
            self._open(url, h, f"{kind} while half-open")
        elif h.state == CLOSED:
            if h.consecutive >= self.failure_threshold:
                self._open(url, h,
                           f"{h.consecutive} consecutive failures, "
                           f"last: {kind}")
            elif self._rate_tripped(h):
                self._open(url, h,
                           f"failure rate >= {self.failure_rate:.0%} "
                           f"over {len(h.outcomes)} samples")

    def record_probe_result(self, url: str, ok: bool) -> None:
        """Outcome of an active /v1/models probe (the tracker's own
        half-open re-probe, or service discovery's liveness probe)."""
        if ok:
            h = self._eps.get(url)
            if h is not None and h.state != CLOSED:
                self._close(url, h, "probe succeeded")
            elif h is not None:
                h.consecutive = 0
                self._note(h, True, self._now())
        else:
            self.record_failure(url, "probe")

    def reset(self, url: str) -> None:
        """Administrative breaker reset (``POST /admin/breaker``): the
        remediation loop restarts a sick engine and must not wait out
        the open-state cooldown before routing resumes — the operator
        (human or remediator) is asserting the endpoint is healthy
        again, and the next real failure will re-open it normally."""
        h = self._eps.get(url)
        if h is None:
            return          # never tracked -> already CLOSED
        self._close(url, h, "admin reset")

    def record_shed(self, url: str) -> None:
        """An upstream 429/503-with-Retry-After: the engine shed the
        request under overload protection. Shed ≠ sick — counted (for
        vllm:upstream_failures_total{kind="shed"}) but deliberately
        excluded from consecutive-failure and windowed-rate breaker
        math: a full-but-healthy engine must never trip its breaker
        open (that would dogpile its load onto the remaining fleet and
        cascade the overload)."""
        self.failures[(url, "shed")] += 1

    def record_deadline_relay(self, url: str) -> None:
        """An upstream 504 marked x-deadline-expired: the client's own
        deadline elapsed while queued. Counter-only, same rationale."""
        self.failures[(url, "deadline")] += 1

    def note_retry(self, url: str) -> None:
        self.retries[url] += 1

    def note_relayed_5xx(self, url: str) -> None:
        self.relayed_5xx[url] += 1

    # -- routing reads --------------------------------------------------

    def state_of(self, url: str) -> str:
        h = self._eps.get(url)
        return h.state if h is not None else CLOSED

    def is_routable(self, url: str) -> bool:
        if url in self._draining:
            return False
        h = self._eps.get(url)
        if h is None or h.state == CLOSED:
            return True
        # OPEN and HALF_OPEN are both unroutable: closing requires the
        # active re-probe (or a stray success) first
        return False

    def healthy_endpoints(self, endpoints: Sequence[EndpointInfo]
                          ) -> List[EndpointInfo]:
        """Filter to breaker-closed, non-draining endpoints.

        Fail-open: if EVERY candidate is unroutable, return the
        non-draining ones (and, as the last resort, all of them) — a
        fleet-wide false-open must degrade to trying, not to a
        guaranteed 502 with zero attempts.
        """
        healthy = [ep for ep in endpoints if self.is_routable(ep.url)]
        if healthy:
            return healthy
        not_draining = [ep for ep in endpoints
                        if ep.url not in self._draining]
        return not_draining or list(endpoints)

    def evict_except(self, live_urls) -> None:
        """Forget endpoints that left the configured fleet, counters
        included (RouterMetrics.refresh_resilience drops their label
        series on the next scrape). Drain flags are deliberately NOT
        evicted: a drain is operator intent, and an endpoint bouncing
        out of discovery mid-drain must come back still draining —
        only end_drain clears it."""
        live = set(live_urls)
        for url in [u for u in self._eps if u not in live]:
            del self._eps[url]
        for store in (self.retries, self.relayed_5xx):
            for url in [u for u in store if u not in live]:
                del store[url]
        for key in [k for k in self.failures if k[0] not in live]:
            del self.failures[key]

    # -- peer gossip (shared_state.py) -----------------------------------

    def peer_view(self) -> Dict[str, Dict]:
        """This router's shareable health facts, ages instead of
        timestamps (two processes share no clock; an age survives the
        hop with only gossip-interval skew). Endpoints with no
        transition yet are omitted — there is nothing to converge on."""
        now = self._now()
        out: Dict[str, Dict] = {}
        for url, h in self._eps.items():
            if h.transition_at is None:
                continue
            entry = {"state": h.state,
                     "age_s": max(0.0, now - h.transition_at)}
            if h.state != CLOSED:
                entry["cooldown_remaining_s"] = max(
                    0.0, h.open_until - now)
            out[url] = entry
        for url, (draining, at) in self._drain_events.items():
            # NEVER_AGE keeps the payload JSON-clean (inf is not JSON)
            entry = out.setdefault(url, {"state": self.state_of(url),
                                         "age_s": NEVER_AGE})
            entry["draining"] = draining
            entry["drain_age_s"] = max(0.0, now - at)
        return out

    def _transition_age(self, h: Optional[_EndpointHealth]) -> float:
        if h is None or h.transition_at is None:
            return float("inf")
        return max(0.0, self._now() - h.transition_at)

    def adopt_peer_view(self, view: Dict[str, Dict],
                        known_urls=None) -> None:
        """Merge one peer's ``peer_view()``: last-writer-wins by
        transition age. A peer that observed a NEWER open/close than we
        did wins — its age is smaller than ours — so when an engine
        dies under traffic only one router carries, every replica
        converges on OPEN within a gossip interval instead of a full
        organic trip; when the probe closes it, the close propagates
        the same way. ``known_urls`` (the configured fleet) bounds what
        a peer can make us track — a peer with a stale config must not
        plant state for endpoints we no longer serve."""
        known = set(known_urls) if known_urls is not None else None
        for url, entry in view.items():
            if known is not None and url not in known:
                continue
            self._adopt_breaker(url, entry)
            self._adopt_drain(url, entry)

    def _adopt_breaker(self, url: str, entry: Dict) -> None:
        peer_state = entry.get("state")
        peer_age = float(entry.get("age_s", NEVER_AGE))
        if peer_state not in (OPEN, HALF_OPEN, CLOSED) or \
                peer_age >= NEVER_AGE:
            return
        h = self._eps.get(url)
        if self._transition_age(h) <= peer_age:
            return            # our own observation is at least as new
        now = self._now()
        if peer_state in (OPEN, HALF_OPEN):
            if self.state_of(url) == CLOSED:
                h = self._h(url)
                h.state = OPEN
                h.opened_at = now - peer_age
                # inherit the peer's remaining cooldown so our OWN
                # re-probe takes over roughly when theirs would —
                # adopted opens still close only through a probe
                h.open_until = now + max(
                    0.0, float(entry.get("cooldown_remaining_s",
                                         self.cooldown_s)))
                h.opens += 1
                h.probing = False
                h.transition_at = now - peer_age
                self.breaker_opens += 1
                self.peer_adopted_opens += 1
                logger.warning("breaker OPEN for %s (adopted from peer, "
                               "%.1fs old)", url, peer_age)
        else:
            if h is not None and h.state != CLOSED:
                self._close(url, h, "peer observed recovery")
                h.transition_at = now - peer_age
                self.peer_adopted_closes += 1

    def _adopt_drain(self, url: str, entry: Dict) -> None:
        if "draining" not in entry:
            return
        peer_draining = bool(entry["draining"])
        peer_age = float(entry.get("drain_age_s", float("inf")))
        ours = self._drain_events.get(url)
        our_age = float("inf") if ours is None \
            else max(0.0, self._now() - ours[1])
        if our_age <= peer_age:
            return
        if peer_draining == (url in self._draining):
            # already agree; just remember the (older) stamp so a
            # third router's even-staler contradiction cannot win later
            self._drain_events[url] = (peer_draining,
                                       self._now() - peer_age)
            return
        if peer_draining:
            self.start_drain(url)
        else:
            self.end_drain(url)
        self._drain_events[url] = (peer_draining, self._now() - peer_age)

    # -- drain ----------------------------------------------------------

    def start_drain(self, url: str) -> None:
        if url not in self._draining:
            logger.info("draining %s: no new admissions; in-flight "
                        "requests continue", url)
            self._drain_events[url] = (True, self._now())
        self._draining.add(url)

    def end_drain(self, url: str) -> None:
        if url in self._draining:
            logger.info("drain ended for %s: routable again", url)
            self._drain_events[url] = (False, self._now())
        self._draining.discard(url)

    def draining(self) -> List[str]:
        return sorted(self._draining)

    # -- introspection ---------------------------------------------------

    def snapshot(self) -> Dict[str, Dict]:
        out = {}
        for url, h in self._eps.items():
            out[url] = {"state": h.state,
                        "consecutive_failures": h.consecutive,
                        "opens": h.opens,
                        "draining": url in self._draining}
        for url in self._draining - set(self._eps):
            out[url] = {"state": CLOSED, "consecutive_failures": 0,
                        "opens": 0, "draining": True}
        return out

    # -- active re-probe -------------------------------------------------

    async def start(self, session) -> None:
        self._probe_task = asyncio.create_task(self._probe_loop(session),
                                               name="health-probe")

    async def close(self) -> None:
        if self._probe_task:
            self._probe_task.cancel()
            try:
                await self._probe_task
            except asyncio.CancelledError:
                pass
            self._probe_task = None

    def healthy(self) -> bool:
        return self._probe_task is None or not self._probe_task.done()

    async def _probe_loop(self, session) -> None:
        while True:
            await asyncio.sleep(self.probe_interval_s)
            try:
                await self.probe_open_endpoints(session)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("health re-probe pass failed")

    async def probe_open_endpoints(self, session) -> None:
        """One re-probe pass: every OPEN endpoint whose cooldown has
        elapsed moves to HALF_OPEN and gets an active /v1/models probe;
        success closes the breaker, failure re-opens it for another
        cooldown."""
        now = self._now()
        due = [url for url, h in self._eps.items()
               if h.state == OPEN and now >= h.open_until and not h.probing]
        for url in due:
            h = self._eps.get(url)
            if h is None:
                continue
            h.state = HALF_OPEN
            h.probing = True
            try:
                models = await probe_model_name(session, url)
            finally:
                if url in self._eps:
                    self._eps[url].probing = False
            self.record_probe_result(url, bool(models))


class RetryBudget:
    """Token bucket bounding failover retries to a fraction of traffic.

    Each incoming request deposits ``ratio`` tokens (capped); each
    retry withdraws one. Sustained retry volume is therefore at most
    ``ratio`` × request volume, while the ``cap``-sized burst allowance
    lets a quiet router still fail over its first few requests
    instantly after an engine dies.
    """

    def __init__(self, ratio: float = 0.2, cap: float = 50.0):
        self.ratio = ratio
        self.cap = cap
        self.tokens = cap
        self.spent = 0          # granted retries (telemetry)
        self.rejected = 0       # retries denied by an empty bucket

    def on_request(self) -> None:
        self.tokens = min(self.cap, self.tokens + self.ratio)

    def try_spend(self) -> bool:
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            self.spent += 1
            return True
        self.rejected += 1
        return False


def backoff_s(attempt: int, base_s: float = 0.05, cap_s: float = 1.0,
              rng: Optional[random.Random] = None) -> float:
    """Full-jitter exponential backoff for failover attempt N (1-based):
    uniform in [0, min(cap, base * 2^(N-1))] — retries from many
    concurrent requests against a dying endpoint de-synchronize instead
    of thundering onto the next candidate together."""
    ceiling = min(cap_s, base_s * (2 ** max(0, attempt - 1)))
    r = rng.random() if rng is not None else random.random()
    return r * ceiling


async def wait_for_drain(get_inflight: Callable[[], int],
                         timeout_s: float,
                         poll_s: float = 0.1) -> bool:
    """Wait until the router has zero in-flight requests (or the bound
    expires). Returns True when fully drained."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if get_inflight() <= 0:
            return True
        await asyncio.sleep(poll_s)
    return get_inflight() <= 0
