"""OpenAI Files API: upload/retrieve/list/delete with local-disk storage.

Capability parity with reference src/vllm_router/services/files_service/
(Storage ABC + FileStorage under /tmp/vllm_files, storage.py:7-157) and
routers/files_router.py (POST /v1/files multipart, GET /v1/files/{id},
GET /v1/files/{id}/content). Re-designed: one Storage class with JSON
metadata sidecars, fully async via aiofiles.
"""

import json
import os
import time
import uuid
from dataclasses import asdict, dataclass
from typing import List, Optional

import aiofiles
import aiofiles.os
from aiohttp import web


@dataclass
class FileObject:
    id: str
    bytes: int
    created_at: int
    filename: str
    purpose: str = "batch"
    object: str = "file"


class FileStorage:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _data_path(self, file_id: str) -> str:
        return os.path.join(self.root, file_id)

    def _meta_path(self, file_id: str) -> str:
        return os.path.join(self.root, file_id + ".json")

    async def save(self, filename: str, content: bytes,
                   purpose: str = "batch") -> FileObject:
        file_id = f"file-{uuid.uuid4().hex[:24]}"
        info = FileObject(id=file_id, bytes=len(content),
                          created_at=int(time.time()), filename=filename,
                          purpose=purpose)
        async with aiofiles.open(self._data_path(file_id), "wb") as f:
            await f.write(content)
        async with aiofiles.open(self._meta_path(file_id), "w") as f:
            await f.write(json.dumps(asdict(info)))
        return info

    async def get(self, file_id: str) -> Optional[FileObject]:
        try:
            async with aiofiles.open(self._meta_path(file_id)) as f:
                return FileObject(**json.loads(await f.read()))
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    async def get_content(self, file_id: str) -> Optional[bytes]:
        try:
            async with aiofiles.open(self._data_path(file_id), "rb") as f:
                return await f.read()
        except FileNotFoundError:
            return None

    async def list(self) -> List[FileObject]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name.endswith(".json"):
                info = await self.get(name[:-5])
                if info:
                    out.append(info)
        return out

    async def delete(self, file_id: str) -> bool:
        found = False
        for path in (self._data_path(file_id), self._meta_path(file_id)):
            try:
                await aiofiles.os.remove(path)
                found = True
            except FileNotFoundError:
                pass
        return found


# ---------------------------------------------------------------- handlers

def mount_files_api(app: web.Application, storage_path: str) -> None:
    storage = FileStorage(storage_path)
    app["state"]["file_storage"] = storage

    async def upload(request: web.Request) -> web.Response:
        reader = await request.multipart()
        purpose, filename, content = "batch", "upload", None
        async for part in reader:
            if part.name == "purpose":
                purpose = (await part.read()).decode()
            elif part.name == "file":
                filename = part.filename or filename
                content = await part.read()
        if content is None:
            return web.json_response(
                {"error": {"message": "missing 'file' part"}}, status=400)
        info = await storage.save(filename, content, purpose)
        return web.json_response(asdict(info))

    async def retrieve(request: web.Request) -> web.Response:
        info = await storage.get(request.match_info["file_id"])
        if info is None:
            return web.json_response(
                {"error": {"message": "file not found"}}, status=404)
        return web.json_response(asdict(info))

    async def content(request: web.Request) -> web.Response:
        data = await storage.get_content(request.match_info["file_id"])
        if data is None:
            return web.json_response(
                {"error": {"message": "file not found"}}, status=404)
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def list_files(request: web.Request) -> web.Response:
        files = await storage.list()
        return web.json_response(
            {"object": "list", "data": [asdict(f) for f in files]})

    async def delete(request: web.Request) -> web.Response:
        ok = await storage.delete(request.match_info["file_id"])
        return web.json_response(
            {"id": request.match_info["file_id"], "object": "file",
             "deleted": ok}, status=200 if ok else 404)

    app.router.add_post("/v1/files", upload)
    app.router.add_get("/v1/files", list_files)
    app.router.add_get("/v1/files/{file_id}", retrieve)
    app.router.add_get("/v1/files/{file_id}/content", content)
    app.router.add_delete("/v1/files/{file_id}", delete)
