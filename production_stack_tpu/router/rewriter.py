"""Pluggable request-rewrite hook applied before routing/forwarding.

Capability parity with reference src/vllm_router/services/request_service/
rewriter.py:17-83 (abstract rewriter + noop + factory). Rewriters can
change the body (e.g. prompt decoration, model aliasing, parameter
clamping) — the proxy re-serializes when the body changes.
"""

import json
from abc import ABC, abstractmethod
from typing import Tuple


class RequestRewriter(ABC):
    @abstractmethod
    def rewrite(self, endpoint_path: str, body: dict,
                raw: bytes) -> Tuple[dict, bytes]:
        """Return (body, raw) — possibly modified."""


class NoopRequestRewriter(RequestRewriter):
    def rewrite(self, endpoint_path, body, raw):
        return body, raw


class ModelAliasRewriter(RequestRewriter):
    """Rewrites request 'model' through an alias map (router-level alias
    support independent of engine-reported names)."""

    def __init__(self, aliases: dict):
        self.aliases = dict(aliases)

    def rewrite(self, endpoint_path, body, raw):
        model = body.get("model")
        if model in self.aliases:
            body = dict(body)
            body["model"] = self.aliases[model]
            raw = json.dumps(body).encode()
        return body, raw


def make_rewriter(kind: str = "noop", **kwargs) -> RequestRewriter:
    if kind == "noop":
        return NoopRequestRewriter()
    if kind == "model_alias":
        return ModelAliasRewriter(kwargs.get("aliases", {}))
    raise ValueError(f"unknown rewriter {kind!r}")
