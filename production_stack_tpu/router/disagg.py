"""Disaggregated prefill/decode orchestration (ROADMAP item 2 /
BASELINE config 5; the reference only roadmaps it: README.md:56,
docs/source/tutorials/disagg.rst "Coming soon"; the --kv-transfer-config
kv_role producer/consumer knob, deployment-vllm-multi.yaml:96-97, is its
engine-side hook).

Architecture: a *prefill pool* of kv_producer engines (e.g. v5p slices —
prefill is compute-bound and loves MXU width) and a *decode pool* of
kv_consumer engines (e.g. v5e — decode is HBM-bandwidth-bound), joined
by the shared KV tier (host DRAM / disk / tpukv remote server over DCN).

Request flow: the router first sends the prompt to a prefill engine as a
1-token non-streaming completion. That engine computes the prompt KV and
its producer connector writes full chunks through the shared tier —
progressively, via ``connector.on_prefill_progress``, so chunks become
visible while later chunks still prefill. After a bounded head-start the
router routes decode; ``DecodeSelector`` picks the decode engine by
NetKV-style cost — *expected KV transfer bytes* (chunk locality in the
candidate's own tiers vs the remote server vs nowhere) weighed against
scraped decode load, not load alone. The consumer engine's connector
pulls the cached prefix, so decode-side prefill collapses to the chunk
remainder.

Failure semantics (the degradation contract, docs/disagg.md): every
prefill-stage failure — pool missing, breaker open, connect error,
timeout, backend 5xx, overload shed — degrades to aggregated serving
(the decode engine recomputes from scratch) and increments
``tpu:router_disagg_fallbacks_total{reason}``. Prefill-stage pressure
must never shed decode-bound traffic: a prefill 429/503 shed is a
fallback, not a breaker signal and not a client-visible error.
"""

import asyncio
import collections
import json
import time
from typing import Dict, List, Optional, Sequence, Tuple

import aiohttp

from production_stack_tpu.router.proxy import CACHE_CONTROL_FIELDS
from production_stack_tpu.router.routing import (prompt_chunk_digests,
                                                 record_chunk_holders)
from production_stack_tpu.router.routing import prompt_text as _prompt_text
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.utils import init_logger, parse_comma_separated

logger = init_logger(__name__)

PREFILL_PATHS = ("/v1/chat/completions", "/v1/completions")

# fallback reasons (the {reason} label set of
# tpu:router_disagg_fallbacks_total); every prefill-stage failure maps
# onto exactly one of these — a prefill failure must never vanish
FALLBACK_REASONS = ("no_pool", "breaker_open", "shed", "http_error",
                    "timeout", "connect")


class DecodeSelector:
    """NetKV-style decode-engine selection (PAPERS.md): score candidates
    by *expected KV transfer bytes*, weighed against scraped load.

    Generalizes the r11 PrefixAwareRouter expected-hit-bytes scoring
    from "deepest warm membership wins" to a per-chunk transfer-cost
    model. For each leading prompt chunk a candidate decode engine pays:

    - **0** when the chunk is warm in its own tiers (this selector
      routed the same leading prefix there before — host-RAM locality,
      no DCN transfer);
    - ``remote_fetch_cost`` × chunk bytes when the chunk was published
      to the shared remote tier (a prefill pass covered it) but is not
      local — the consumer will pull it over the network;
    - ``recompute_cost`` × chunk bytes when the chunk is known to
      neither — the consumer's tier walk stops at the first such chunk
      and everything after it is recomputed regardless of locality.

    The score blends normalized transfer cost with normalized scraped
    decode load (in-flight over advertised capacity when the engine
    advertises one, else over the busiest candidate):

        score = transfer_weight * cost_norm + load_weight * load_norm

    **Cold-prefix fallback:** when no candidate's transfer cost differs
    (nothing about the prompt is known, or everything is equally
    remote) the selector abstains (returns None) and the configured
    routing policy decides — balancing load without a network signal
    is the policy's job (least-loaded reads the same stats; session
    and hash policies keep their affinity, which converges repeated
    cold prefixes onto one replica).

    State is bounded: ``ring_entries`` chunk digests (LRU) for both the
    locality ring and the published-to-remote set.
    """

    _URLS_PER_CHUNK = 4

    def __init__(self, chunk_chars: int = 256,
                 ring_entries: int = 65536,
                 max_track_chars: int = 8192,
                 transfer_weight: float = 1.0,
                 load_weight: float = 1.0,
                 remote_fetch_cost: float = 1.0,
                 recompute_cost: float = 2.0):
        self.chunk_chars = max(1, chunk_chars)
        self.ring_entries = ring_entries
        self.max_track_chars = max_track_chars
        self.transfer_weight = transfer_weight
        self.load_weight = load_weight
        self.remote_fetch_cost = remote_fetch_cost
        self.recompute_cost = recompute_cost
        # digest -> recent decode URLs holding the chunk locally (most
        # recent last); LRU over digests
        self._chunks: "collections.OrderedDict[bytes, List[str]]" = \
            collections.OrderedDict()
        # digests a prefill pass covered -> published to the shared
        # remote tier (value unused; OrderedDict for LRU)
        self._published: "collections.OrderedDict[bytes, None]" = \
            collections.OrderedDict()
        # superset of URLs present in _chunks (an LRU'd-out URL may
        # linger until the next real eviction): lets evict_except
        # no-op without scanning the ring when nobody departed
        self._seen_urls: set = set()
        self.cost_routes = 0        # selections made by the cost model
        self.abstains = 0           # cold prefix: policy decided

    # shared with PrefixAwareRouter (routing.py): both rings must chunk
    # the SAME rendering or affinity and cost scoring diverge
    prompt_text = staticmethod(_prompt_text)

    @staticmethod
    def prompt_chars(body: dict) -> int:
        """CONTENT length only — the length gate's unit. prompt_text
        (the digest basis) serializes the whole messages array, whose
        ~40 chars/message of role/key scaffolding would let a 2-char
        chat sail past --disagg-min-prompt-chars."""
        msgs = body.get("messages")
        if isinstance(msgs, list):
            return sum(len(str(m.get("content") or ""))
                       for m in msgs if isinstance(m, dict))
        prompt = body.get("prompt", "")
        return len(prompt) if isinstance(prompt, str) else \
            len(json.dumps(prompt))

    def digests(self, body: dict) -> List[bytes]:
        return prompt_chunk_digests(self.prompt_text(body),
                                    self.chunk_chars,
                                    self.max_track_chars)

    # -- state feeds -----------------------------------------------------

    def on_prefill_dispatched(self, digests: Sequence[bytes]) -> None:
        """A prefill pass covers these chunks: the producer will publish
        them to the shared remote tier (progressively, so marking at
        dispatch time matches what a post-head-start consumer sees)."""
        for d in digests:
            self._published[d] = None
            self._published.move_to_end(d)
        while len(self._published) > self.ring_entries:
            self._published.popitem(last=False)

    def on_decode_routed(self, digests: Sequence[bytes],
                         url: str) -> None:
        """The chosen decode engine will fetch-or-compute these chunks
        and hold them in its local tiers afterwards."""
        record_chunk_holders(self._chunks, digests, url,
                             urls_per_chunk=self._URLS_PER_CHUNK,
                             max_entries=self.ring_entries)
        self._seen_urls.add(url)

    def on_decode_failed(self, digests: Sequence[bytes],
                         url: str) -> None:
        """A routed attempt failed before any byte reached the client:
        the engine never pulled the KV, so the route-time credit must
        come back out — a shedding engine's low in-flight would
        otherwise keep winning the load tiebreak at phantom-zero
        transfer cost for exactly the prefixes it keeps refusing.
        (_seen_urls deliberately keeps the URL: it is a superset.)"""
        for d in digests:
            urls = self._chunks.get(d)
            if urls and url in urls:
                urls.remove(url)
                if not urls:
                    del self._chunks[d]

    def rehome(self, from_url: str, to_url: str,
               digests: Optional[Sequence[bytes]] = None) -> int:
        """kvplane migration hand-off: chunks whose KV just moved
        replica-to-replica now live on ``to_url``, so the locality
        evidence must follow — otherwise transfer-cost scoring keeps
        steering the migrated prefixes at the replica that no longer
        holds them (recreating the very pressure the migration
        relieved). ``digests=None`` rehomes every entry naming
        ``from_url`` (whole-replica drain); a digest list restricts the
        rewrite to the migrated chunks. Returns entries rewritten."""
        if from_url == to_url:
            return 0
        keys = list(self._chunks) if digests is None else digests
        moved = 0
        for d in keys:
            urls = self._chunks.get(d)
            if not urls or from_url not in urls:
                continue
            urls.remove(from_url)
            if to_url not in urls:
                urls.append(to_url)
            moved += 1
        if moved:
            self._seen_urls.add(to_url)
        return moved

    def evict_except(self, live_urls) -> None:
        """Drop locality evidence for decode engines that left the
        fleet (dynamic-config swaps) — a departed URL must not keep
        winning cost scoring. Called on every /metrics scrape (and
        every dynamic-config apply), so the common nobody-departed
        case must not pay a full-ring scan."""
        live = set(live_urls)
        if self._seen_urls <= live:
            return
        for d in list(self._chunks):
            urls = [u for u in self._chunks[d] if u in live]
            if urls:
                self._chunks[d] = urls
            else:
                del self._chunks[d]
        self._seen_urls &= live

    # -- scoring ---------------------------------------------------------

    def transfer_cost(self, digests: Sequence[bytes], url: str) -> float:
        """Expected transfer cost for ``url``, in chunk-char units
        (absolute scale cancels in normalization)."""
        cost = 0.0
        walk_broken = False
        for d in digests:
            if walk_broken:
                cost += self.chunk_chars * self.recompute_cost
                continue
            if url in (self._chunks.get(d) or ()):
                continue                       # local: free
            if d in self._published:
                cost += self.chunk_chars * self.remote_fetch_cost
                continue
            # neither local nor remote: the consumer's tier walk stops
            # here; the rest of the prompt recomputes
            walk_broken = True
            cost += self.chunk_chars * self.recompute_cost
        return cost

    def select(self, body: dict, urls: Sequence[str],
               request_stats: Dict, engine_stats: Dict,
               digests: Optional[List[bytes]] = None,
               explain: Optional[dict] = None) -> Optional[str]:
        """Pick a decode URL, or None to abstain (cold prefix — let the
        routing policy decide). ``digests`` lets the caller hash the
        prompt once per request instead of once per hook. ``explain``
        (a caller-owned dict) is filled with the per-candidate
        transfer-cost inputs so the decision is reconstructable from a
        trace (tracing.py ``decode_select`` event span)."""
        if len(urls) <= 1:
            return None
        if digests is None:
            digests = self.digests(body)
        if not digests:
            return None
        costs = {u: self.transfer_cost(digests, u) for u in urls}
        if explain is not None:
            explain["transfer_cost"] = {u: round(c, 1)
                                        for u, c in costs.items()}
            explain["chunks"] = len(digests)
        if max(costs.values()) - min(costs.values()) < 1e-9:
            # no locality signal separates the candidates: abstain so
            # the policy's own affinity (hash ring) keeps repeated cold
            # prefixes converging
            self.abstains += 1
            return None
        # normalize by the worst possible cost; the max() guards a
        # zero cost knob (--disagg-recompute-cost 0 is expressible)
        max_cost = len(digests) * self.chunk_chars * max(
            self.recompute_cost, self.remote_fetch_cost, 1e-9)

        def in_flight(u: str) -> float:
            rs = request_stats.get(u)
            return float(rs.in_flight) if rs is not None else 0.0

        peak = max((in_flight(u) for u in urls), default=0.0)

        def capacity(u: str) -> float:
            es = engine_stats.get(u) if engine_stats else None
            cap = getattr(es, "capacity", 0.0) if es is not None else 0.0
            return float(cap) if cap and cap > 0 else 0.0

        # one normalization for the whole candidate set: utilization
        # (in-flight / advertised capacity) only when EVERY candidate
        # advertises one — mixing it with the peak-relative scale would
        # systematically favor exactly the engines whose stats are
        # missing or stale
        use_capacity = all(capacity(u) > 0 for u in urls)

        def load_norm(u: str) -> float:
            if use_capacity:
                return min(2.0, in_flight(u) / capacity(u))
            return in_flight(u) / (peak + 1.0)

        def score(u: str) -> Tuple[float, str]:
            return (self.transfer_weight * (costs[u] / max_cost)
                    + self.load_weight * load_norm(u), u)

        self.cost_routes += 1
        picked = min(urls, key=score)
        if explain is not None:
            explain["selected"] = picked
        return picked


class DisaggPrefillOrchestrator:
    """Owns the prefill pool and the decode selection for the two-stage
    request (see module docstring).

    Prefill dispatch round-robins per model over breaker-closed pool
    members. Failure handling: a per-backend circuit breaker opens after
    ``breaker_threshold`` consecutive failures and skips the backend for
    ``breaker_cooldown_s`` (decode engines can always recompute, so an
    open breaker degrades to non-disagg behavior, never to errors) —
    overload sheds (429/503 + Retry-After) are fallbacks but NEVER
    breaker signals, mirroring the r9 shed≠sick contract. Latency: the
    proxy gives prefill only a bounded ``headstart_s`` before routing
    decode (see run_with_headstart) — the producer keeps publishing KV
    chunks progressively in the background either way.

    ``set_pool`` swaps the prefill endpoint set at runtime (dynamic
    config): breaker and rotation state survive for members present on
    both sides of the swap — the same bug class r11 fixed for prefix
    rings (a fleet swap must not amnesty a sick backend or reset a
    rotation mid-cycle).
    """

    def __init__(self, backends: List[str], models: List[str],
                 timeout_s: float = 15.0, headstart_s: float = 2.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 min_prompt_chars: int = 0,
                 selector: Optional[DecodeSelector] = None):
        self.endpoints: List[EndpointInfo] = []
        self.timeout_s = timeout_s
        self.headstart_s = headstart_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        self.min_prompt_chars = min_prompt_chars
        self.selector = selector
        # per-model counters: a shared counter advanced by other models'
        # traffic would skew (or fully starve) a pool's rotation
        self._rr: Dict[str, int] = {}
        self._consecutive_failures: Dict[str, int] = {}
        self._open_until: Dict[str, float] = {}
        self.prefills = 0
        self.prefill_errors = 0
        self.breaker_opens = 0
        self.headstart_elapsed = 0   # decode routed before prefill done
        self.fallbacks: Dict[str, int] = {r: 0 for r in FALLBACK_REASONS}
        self.set_pool(backends, models)

    # -- pool management -------------------------------------------------

    def set_pool(self, backends: List[str], models: List[str]) -> None:
        """Swap the prefill endpoint set; per-URL breaker state and
        per-model rotation counters survive for surviving members."""
        if len(backends) != len(models):
            raise ValueError(
                f"{len(backends)} prefill backends but {len(models)} "
                f"models")
        backends = [u.rstrip("/") for u in backends]
        self.endpoints = [EndpointInfo(url=u, model=m, pool="prefill")
                          for u, m in zip(backends, models)]
        live = set(backends)
        self._consecutive_failures = {
            u: n for u, n in self._consecutive_failures.items()
            if u in live}
        self._open_until = {u: t for u, t in self._open_until.items()
                            if u in live}
        live_models = {ep.model for ep in self.endpoints}
        self._rr = {m: i for m, i in self._rr.items() if m in live_models}

    def pool_snapshot(self) -> dict:
        """Operator view for /health."""
        now = self._now()
        return {
            "endpoints": [ep.url for ep in self.endpoints],
            "models": sorted({ep.model for ep in self.endpoints}),
            "open_breakers": sorted(
                u for u, t in self._open_until.items() if t > now),
            "prefills": self.prefills,
            "prefill_errors": self.prefill_errors,
            "fallbacks": dict(self.fallbacks),
        }

    def _now(self) -> float:
        return time.monotonic()

    def _fallback(self, reason: str) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    def pick(self, model: str) -> Optional[str]:
        """Breaker-filtered per-model round-robin; None (with the
        fallback counted) when the pool can't take this prefill."""
        serving = [ep.url for ep in self.endpoints if ep.serves(model)]
        if not serving:
            self._fallback("no_pool")
            return None
        now = self._now()
        pool = [u for u in serving
                if self._open_until.get(u, 0.0) <= now]
        if not pool:
            self._fallback("breaker_open")
            return None
        idx = self._rr.get(model, 0)
        self._rr[model] = idx + 1
        return pool[idx % len(pool)]

    def _record(self, url: str, ok: bool) -> None:
        if ok:
            self._consecutive_failures[url] = 0
            return
        n = self._consecutive_failures.get(url, 0) + 1
        self._consecutive_failures[url] = n
        if n >= self.breaker_threshold:
            self._open_until[url] = self._now() + self.breaker_cooldown_s
            self._consecutive_failures[url] = 0
            self.breaker_opens += 1
            logger.warning(
                "disagg prefill breaker OPEN for %s (%d consecutive "
                "failures; cooldown %.0fs)", url, n,
                self.breaker_cooldown_s)

    # -- request gating --------------------------------------------------

    def should_run(self, endpoint_path: str, body: dict) -> bool:
        """Cheap pre-dispatch gate: right path, long-enough prompt.
        Short prompts skip the prefill stage entirely — a 1-token pass
        plus a tier walk costs more than just prefilling a few chars on
        the decode engine (``--disagg-min-prompt-chars``). Length is
        measured over message CONTENT, not JSON framing."""
        if endpoint_path not in PREFILL_PATHS:
            return False
        model = body.get("model")
        if not any(ep.serves(model) for ep in self.endpoints):
            # a model the pool was never configured for is not a
            # degradation — the disagg stage is simply inert for it;
            # counting it as a no_pool fallback would read a healthy
            # multi-model deployment as permanently degrading
            return False
        if self.min_prompt_chars <= 0:
            return True
        return DecodeSelector.prompt_chars(body) >= \
            self.min_prompt_chars

    # -- prefill stage ---------------------------------------------------

    @staticmethod
    def prefill_body(body: dict) -> dict:
        """The original request reduced to a 1-token non-streaming pass:
        enough for the producer engine to compute + publish the prompt
        KV, cheap enough to run serially before decode."""
        drop = ("stream", "stream_options") + CACHE_CONTROL_FIELDS
        out = {k: v for k, v in body.items() if k not in drop}
        out["max_tokens"] = 1
        out.pop("max_completion_tokens", None)
        return out

    def digests(self, body: dict) -> Optional[List[bytes]]:
        """Hash the prompt ONCE per request (the proxy threads the
        result through run_with_headstart / select_decode /
        on_decode_routed); None when no selector is configured."""
        if self.selector is None:
            return None
        return self.selector.digests(body)

    async def run_prefill(self, session: aiohttp.ClientSession,
                          endpoint_path: str, model: str, body: dict,
                          headers: Optional[Dict[str, str]] = None,
                          digests: Optional[List[bytes]] = None,
                          trace=None) -> bool:
        """Fire the prefill pass; True when the pool accepted it. Every
        failure path increments exactly one fallback reason. ``trace``
        (tracing.RequestTrace) gets a ``prefill`` EVENT span AT
        DISPATCH (so a pass that outlasts the sealed trace — long
        prompt, short decode — still leaves its evidence in the chain)
        and a ``prefill_result`` event when the pass settles, when the
        trace is still open. Events, not phases: the pass overlaps the
        decode-side phases past the head-start."""
        if endpoint_path not in PREFILL_PATHS:
            return False
        url = self.pick(model)
        if url is None:
            if trace is not None:
                trace.add_event("prefill", None, 0.0, status="fallback",
                                attrs={"reason": "no_pool_or_breaker"})
            return False            # pick counted no_pool/breaker_open
        self.prefills += 1
        t_pf = time.monotonic()
        if trace is not None:
            trace.add_event("prefill", t_pf, 0.0, status="dispatched",
                            attrs={"server": url})

        def _span(status: str) -> None:
            if trace is not None:
                trace.add_event("prefill_result", t_pf,
                                time.monotonic() - t_pf, status=status,
                                attrs={"server": url})
        if self.selector is not None:
            # mark at dispatch: the producer publishes progressively,
            # so by the time a post-head-start decode walks the tier
            # the leading chunks are (becoming) remote-visible
            self.selector.on_prefill_dispatched(
                digests if digests is not None
                else self.selector.digests(body))
        try:
            async with session.post(
                    f"{url}{endpoint_path}",
                    json=self.prefill_body(body),
                    headers=headers or {},
                    timeout=aiohttp.ClientTimeout(
                        total=self.timeout_s)) as resp:
                await resp.read()
                if resp.status == 200:
                    self._record(url, True)
                    _span("ok")
                    return True
                if resp.status in (429, 503) and \
                        "Retry-After" in resp.headers:
                    # prefill-queue pressure: the engine is healthy but
                    # full. Degrade to aggregated serving — decode-bound
                    # traffic is NOT shed and the breaker is NOT fed
                    # (shed ≠ sick, the r9 contract at this stage)
                    logger.debug("disagg prefill on %s shed (HTTP %d); "
                                 "decode recomputes", url, resp.status)
                    self.prefill_errors += 1
                    self._fallback("shed")
                    _span("shed")
                    return False
                logger.warning("disagg prefill on %s returned %d", url,
                               resp.status)
                self._fallback("http_error")
        except asyncio.TimeoutError:
            logger.warning("disagg prefill on %s timed out after %gs",
                           url, self.timeout_s)
            self._fallback("timeout")
        except (aiohttp.ClientError, ConnectionError, OSError) as e:
            logger.warning("disagg prefill on %s failed: %s", url, e)
            self._fallback("connect")
        except Exception as e:
            # the degradation contract admits no exception shape: a
            # prefill failure of ANY kind must degrade to aggregated
            # serving and be counted — never escape as an unretrieved
            # task exception (the head-start caller may not await us)
            logger.warning("disagg prefill on %s failed unexpectedly: "
                           "%s", url, e, exc_info=True)
            self._fallback("http_error")
        self.prefill_errors += 1
        self._record(url, False)
        _span("error")
        return False

    async def run_with_headstart(self, session: aiohttp.ClientSession,
                                 endpoint_path: str, model: str,
                                 body: dict,
                                 headers: Optional[Dict[str, str]] = None,
                                 digests: Optional[List[bytes]] = None,
                                 trace=None) -> None:
        """Overlap: give prefill at most ``headstart_s`` before decode
        routing proceeds. The prefill task keeps running (and its engine
        keeps publishing KV chunks progressively) in the background; a
        decode engine that starts early simply finds fewer cached chunks
        — never a wrong result."""
        task = asyncio.ensure_future(self.run_prefill(
            session, endpoint_path, model, body, headers,
            digests=digests, trace=trace))
        done, _ = await asyncio.wait({task}, timeout=self.headstart_s)
        if not done:
            self.headstart_elapsed += 1
            logger.debug("disagg prefill still running after %.1fs "
                         "head-start; routing decode now",
                         self.headstart_s)
            # surface late failures in logs/counters, never as exceptions
            task.add_done_callback(lambda t: t.exception())

    # -- decode stage ----------------------------------------------------

    def select_decode(self, body: dict, candidates, request_stats,
                      engine_stats,
                      digests: Optional[List[bytes]] = None,
                      explain: Optional[dict] = None
                      ) -> Optional[str]:
        """Transfer-cost-aware decode pick; None = let the routing
        policy decide (selector disabled or cold prefix)."""
        if self.selector is None:
            return None
        return self.selector.select(
            body, [ep.url for ep in candidates], request_stats,
            engine_stats or {}, digests=digests, explain=explain)

    def on_decode_routed(self, body: dict, url: str,
                         digests: Optional[List[bytes]] = None) -> None:
        if self.selector is not None:
            self.selector.on_decode_routed(
                digests if digests is not None
                else self.selector.digests(body), url)

    def on_decode_failed(self, body: dict, url: str,
                         digests: Optional[List[bytes]] = None) -> None:
        if self.selector is not None:
            self.selector.on_decode_failed(
                digests if digests is not None
                else self.selector.digests(body), url)


def make_orchestrator(args, kwargs: Optional[dict] = None
                      ) -> Optional[DisaggPrefillOrchestrator]:
    backends = parse_comma_separated(
        getattr(args, "prefill_backends", None))
    if not backends:
        return None
    models = parse_comma_separated(getattr(args, "prefill_models", None))
    return build_orchestrator(backends, models,
                              kwargs if kwargs is not None
                              else orchestrator_kwargs(args))


def build_orchestrator(backends: List[str], models: List[str],
                       kwargs: Optional[dict]
                       ) -> DisaggPrefillOrchestrator:
    """Construct an orchestrator from an ``orchestrator_kwargs`` dict.
    The selector factory (if any) is invoked HERE, so every
    orchestrator — startup or a dynamic-config enable — gets a FRESH
    DecodeSelector instead of inheriting a previous incarnation's
    locality state."""
    kw = dict(kwargs or {})
    factory = kw.pop("selector_factory", None)
    if factory is not None and kw.get("selector") is None:
        kw["selector"] = factory()
    return DisaggPrefillOrchestrator(backends, models, **kw)


def orchestrator_kwargs(args) -> dict:
    """The CLI-configured knobs, reusable by a dynamic-config swap that
    creates the orchestrator after startup (app state
    ``disagg_kwargs``). Carries a selector *factory*, not an instance —
    see build_orchestrator."""
    factory = None
    if not getattr(args, "no_disagg_decode_selection", False):
        knobs = dict(
            chunk_chars=getattr(args, "disagg_chunk_chars", 256),
            transfer_weight=getattr(args, "disagg_transfer_weight", 1.0),
            load_weight=getattr(args, "disagg_load_weight", 1.0),
            remote_fetch_cost=getattr(args, "disagg_remote_cost", 1.0),
            recompute_cost=getattr(args, "disagg_recompute_cost", 2.0))

        def factory(knobs=knobs):
            return DecodeSelector(**knobs)
    return dict(
        timeout_s=getattr(args, "prefill_timeout", 15.0),
        headstart_s=getattr(args, "prefill_headstart", 2.0),
        breaker_threshold=getattr(args, "prefill_breaker_threshold", 3),
        breaker_cooldown_s=getattr(args, "prefill_breaker_cooldown",
                                   30.0),
        min_prompt_chars=getattr(args, "disagg_min_prompt_chars", 0),
        selector_factory=factory)
