"""Disaggregated prefill orchestration (green-field — the reference only
roadmaps it: README.md:56, docs/source/tutorials/disagg.rst "Coming
soon"; the --kv-transfer-config kv_role producer/consumer knob,
deployment-vllm-multi.yaml:96-97, is its engine-side hook).

Architecture: a *prefill pool* of kv_producer engines (e.g. v5p slices —
prefill is compute-bound and loves MXU width) and a *decode pool* of
kv_consumer engines (e.g. v5e — decode is HBM-bandwidth-bound), joined
by the shared KV tier (host DRAM / disk / tpukv remote server over DCN).

Request flow: the router first sends the prompt to a prefill engine as a
1-token non-streaming completion. That engine computes the prompt KV and
its producer connector writes the full chunks through the shared tier.
The router then forwards the original request to a decode engine, whose
consumer connector pulls the cached prefix, so decode-side prefill
collapses to the chunk remainder. Prefill failures degrade gracefully:
the decode engine can always recompute from scratch.
"""

import asyncio
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.router.proxy import CACHE_CONTROL_FIELDS
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.utils import init_logger, parse_comma_separated

logger = init_logger(__name__)

PREFILL_PATHS = ("/v1/chat/completions", "/v1/completions")


class DisaggPrefillOrchestrator:
    """Round-robins prompts over the prefill pool before decode routing.

    Failure handling: a per-backend circuit breaker opens after
    ``breaker_threshold`` consecutive failures and skips the backend for
    ``breaker_cooldown_s`` (decode engines can always recompute, so an
    open breaker degrades to non-disagg behavior, never to errors).
    Latency: the proxy gives prefill only a bounded ``headstart_s``
    before routing decode (see run_with_headstart) — the producer keeps
    publishing KV chunks progressively in the background either way.
    """

    def __init__(self, backends: List[str], models: List[str],
                 timeout_s: float = 15.0, headstart_s: float = 2.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0):
        if len(backends) != len(models):
            raise ValueError(
                f"{len(backends)} prefill backends but {len(models)} models")
        self.endpoints = [EndpointInfo(url=u, model=m)
                          for u, m in zip(backends, models)]
        self.timeout_s = timeout_s
        self.headstart_s = headstart_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        # per-model counters: a shared counter advanced by other models'
        # traffic would skew (or fully starve) a pool's rotation
        self._rr: Dict[str, int] = {}
        self._consecutive_failures: Dict[str, int] = {}
        self._open_until: Dict[str, float] = {}
        self.prefills = 0
        self.prefill_errors = 0
        self.breaker_opens = 0

    def _now(self) -> float:
        import time
        return time.monotonic()

    def pick(self, model: str) -> Optional[str]:
        now = self._now()
        pool = [ep.url for ep in self.endpoints
                if ep.serves(model) and self._open_until.get(ep.url, 0.0)
                <= now]
        if not pool:
            return None
        idx = self._rr.get(model, 0)
        self._rr[model] = idx + 1
        return pool[idx % len(pool)]

    def _record(self, url: str, ok: bool) -> None:
        if ok:
            self._consecutive_failures[url] = 0
            return
        n = self._consecutive_failures.get(url, 0) + 1
        self._consecutive_failures[url] = n
        if n >= self.breaker_threshold:
            self._open_until[url] = self._now() + self.breaker_cooldown_s
            self._consecutive_failures[url] = 0
            self.breaker_opens += 1
            logger.warning(
                "disagg prefill breaker OPEN for %s (%d consecutive "
                "failures; cooldown %.0fs)", url, n,
                self.breaker_cooldown_s)

    @staticmethod
    def prefill_body(body: dict) -> dict:
        """The original request reduced to a 1-token non-streaming pass:
        enough for the producer engine to compute + publish the prompt
        KV, cheap enough to run serially before decode."""
        drop = ("stream", "stream_options") + CACHE_CONTROL_FIELDS
        out = {k: v for k, v in body.items() if k not in drop}
        out["max_tokens"] = 1
        out.pop("max_completion_tokens", None)
        return out

    async def run_prefill(self, session: aiohttp.ClientSession,
                          endpoint_path: str, model: str, body: dict,
                          headers: Optional[Dict[str, str]] = None) -> bool:
        """Fire the prefill pass; True when the pool accepted it."""
        if endpoint_path not in PREFILL_PATHS:
            return False
        url = self.pick(model)
        if url is None:
            return False
        self.prefills += 1
        try:
            async with session.post(
                    f"{url}{endpoint_path}",
                    json=self.prefill_body(body),
                    headers=headers or {},
                    timeout=aiohttp.ClientTimeout(
                        total=self.timeout_s)) as resp:
                await resp.read()
                if resp.status == 200:
                    self._record(url, True)
                    return True
                logger.warning("disagg prefill on %s returned %d", url,
                               resp.status)
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            logger.warning("disagg prefill on %s failed: %s", url, e)
        self.prefill_errors += 1
        self._record(url, False)
        return False

    async def run_with_headstart(self, session: aiohttp.ClientSession,
                                 endpoint_path: str, model: str,
                                 body: dict,
                                 headers: Optional[Dict[str, str]] = None,
                                 ) -> None:
        """Overlap: give prefill at most ``headstart_s`` before decode
        routing proceeds. The prefill task keeps running (and its engine
        keeps publishing KV chunks progressively) in the background; a
        decode engine that starts early simply finds fewer cached chunks
        — never a wrong result."""
        task = asyncio.ensure_future(self.run_prefill(
            session, endpoint_path, model, body, headers))
        done, _ = await asyncio.wait({task}, timeout=self.headstart_s)
        if not done:
            logger.debug("disagg prefill still running after %.1fs "
                         "head-start; routing decode now",
                         self.headstart_s)
            # surface late failures in logs, never as exceptions
            task.add_done_callback(lambda t: t.exception())


def make_orchestrator(args) -> Optional[DisaggPrefillOrchestrator]:
    backends = parse_comma_separated(
        getattr(args, "prefill_backends", None))
    if not backends:
        return None
    models = parse_comma_separated(getattr(args, "prefill_models", None))
    return DisaggPrefillOrchestrator(
        backends, models,
        timeout_s=getattr(args, "prefill_timeout", 15.0),
        headstart_s=getattr(args, "prefill_headstart", 2.0),
        breaker_threshold=getattr(args, "prefill_breaker_threshold", 3),
        breaker_cooldown_s=getattr(args, "prefill_breaker_cooldown", 30.0))
