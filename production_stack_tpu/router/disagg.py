"""Disaggregated prefill orchestration (green-field — the reference only
roadmaps it: README.md:56, docs/source/tutorials/disagg.rst "Coming
soon"; the --kv-transfer-config kv_role producer/consumer knob,
deployment-vllm-multi.yaml:96-97, is its engine-side hook).

Architecture: a *prefill pool* of kv_producer engines (e.g. v5p slices —
prefill is compute-bound and loves MXU width) and a *decode pool* of
kv_consumer engines (e.g. v5e — decode is HBM-bandwidth-bound), joined
by the shared KV tier (host DRAM / disk / tpukv remote server over DCN).

Request flow: the router first sends the prompt to a prefill engine as a
1-token non-streaming completion. That engine computes the prompt KV and
its producer connector writes the full chunks through the shared tier.
The router then forwards the original request to a decode engine, whose
consumer connector pulls the cached prefix, so decode-side prefill
collapses to the chunk remainder. Prefill failures degrade gracefully:
the decode engine can always recompute from scratch.
"""

import asyncio
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.router.proxy import CACHE_CONTROL_FIELDS
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.utils import init_logger, parse_comma_separated

logger = init_logger(__name__)

PREFILL_PATHS = ("/v1/chat/completions", "/v1/completions")


class DisaggPrefillOrchestrator:
    """Round-robins prompts over the prefill pool before decode routing."""

    def __init__(self, backends: List[str], models: List[str],
                 timeout_s: float = 120.0):
        if len(backends) != len(models):
            raise ValueError(
                f"{len(backends)} prefill backends but {len(models)} models")
        self.endpoints = [EndpointInfo(url=u, model=m)
                          for u, m in zip(backends, models)]
        self.timeout_s = timeout_s
        # per-model counters: a shared counter advanced by other models'
        # traffic would skew (or fully starve) a pool's rotation
        self._rr: Dict[str, int] = {}
        self.prefills = 0
        self.prefill_errors = 0

    def pick(self, model: str) -> Optional[str]:
        pool = [ep.url for ep in self.endpoints if ep.serves(model)]
        if not pool:
            return None
        idx = self._rr.get(model, 0)
        self._rr[model] = idx + 1
        return pool[idx % len(pool)]

    @staticmethod
    def prefill_body(body: dict) -> dict:
        """The original request reduced to a 1-token non-streaming pass:
        enough for the producer engine to compute + publish the prompt
        KV, cheap enough to run serially before decode."""
        drop = ("stream", "stream_options") + CACHE_CONTROL_FIELDS
        out = {k: v for k, v in body.items() if k not in drop}
        out["max_tokens"] = 1
        out.pop("max_completion_tokens", None)
        return out

    async def run_prefill(self, session: aiohttp.ClientSession,
                          endpoint_path: str, model: str, body: dict,
                          headers: Optional[Dict[str, str]] = None) -> bool:
        """Fire the prefill pass; True when the pool accepted it."""
        if endpoint_path not in PREFILL_PATHS:
            return False
        url = self.pick(model)
        if url is None:
            return False
        self.prefills += 1
        try:
            async with session.post(
                    f"{url}{endpoint_path}",
                    json=self.prefill_body(body),
                    headers=headers or {},
                    timeout=aiohttp.ClientTimeout(
                        total=self.timeout_s)) as resp:
                await resp.read()
                if resp.status == 200:
                    return True
                logger.warning("disagg prefill on %s returned %d", url,
                               resp.status)
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            logger.warning("disagg prefill on %s failed: %s", url, e)
        self.prefill_errors += 1
        return False


def make_orchestrator(args) -> Optional[DisaggPrefillOrchestrator]:
    backends = parse_comma_separated(
        getattr(args, "prefill_backends", None))
    if not backends:
        return None
    models = parse_comma_separated(getattr(args, "prefill_models", None))
    return DisaggPrefillOrchestrator(
        backends, models,
        timeout_s=getattr(args, "prefill_timeout", 120.0))
