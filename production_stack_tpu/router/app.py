"""Router application: endpoint surface + wiring + CLI.

Endpoint parity with reference src/vllm_router/routers/main_router.py:
42-160 — /v1/chat/completions, /v1/completions, /v1/embeddings,
/v1/rerank, /v1/score (proxied); /v1/models (aggregated, deduped);
/health (discovery + scraper + config watcher liveness + current dynamic
config); /metrics; /version. Files and batches endpoints live in
files_api.py / batches_api.py and are mounted here.

Everything is one aiohttp Application; background activities (K8s watch,
stats scraper, config watcher) are asyncio tasks started on app startup
and cancelled on cleanup.
"""

import argparse
import asyncio
import signal
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu import protocol as proto
from production_stack_tpu.router.dynamic_config import DynamicConfigWatcher
from production_stack_tpu.router.feature_gates import FeatureGates
from production_stack_tpu.router.metrics import RouterMetrics
from production_stack_tpu.router.pools import PoolManager, parse_pool_spec
from production_stack_tpu.router.proxy import route_general_request
from production_stack_tpu.router.resilience import (CLOSED,
                                                    HealthTracker,
                                                    RetryBudget,
                                                    wait_for_drain)
from production_stack_tpu.router.qos import QosPolicy
from production_stack_tpu.router.rewriter import make_rewriter
from production_stack_tpu.router.routing import make_router
from production_stack_tpu.router.shared_state import (RouterPeers,
                                                      derive_router_id,
                                                      peers_payload)
from production_stack_tpu.router.service_discovery import (
    K8sServiceDiscovery, StaticServiceDiscovery, engine_auth_headers)
from production_stack_tpu.router.stats import (EngineStatsScraper,
                                               RequestStatsMonitor)
from production_stack_tpu.slo import (SLOConfig, SLOEngine, SLOTask,
                                      default_config)
from production_stack_tpu.tracing import TraceRecorder, debug_traces_handler
from production_stack_tpu.utils import (init_logger, parse_comma_separated,
                                        parse_static_aliases,
                                        parse_static_urls, set_ulimit)
from production_stack_tpu.version import __version__

logger = init_logger(__name__)

PROXIED_PATHS = ["/v1/chat/completions", "/v1/completions", "/v1/embeddings",
                 "/v1/rerank", "/v2/rerank", "/v1/score"]


# ---------------------------------------------------------------- handlers

def _make_proxy_handler(path: str):
    async def handler(request: web.Request) -> web.StreamResponse:
        return await route_general_request(request, path)
    return handler


async def list_models(request: web.Request) -> web.Response:
    state = request.app["state"]
    cards = {}
    for ep in state["discovery"].get_endpoints():
        for name in [ep.model] + ep.model_aliases:
            if name not in cards:
                cards[name] = proto.ModelCard(id=name)
    # the configured fleet is the floor, not the catalog: adapters
    # loaded at runtime (/admin/lora/load) surface in each engine's
    # scraped /load ``models`` list one scrape interval later — merge
    # them so /v1/models reports what the fleet ACTUALLY serves
    scraper = state.get("scraper")
    if scraper is not None:
        for es in scraper.get().values():
            for name in getattr(es, "served_models", ()):
                if name not in cards:
                    cards[name] = proto.ModelCard(id=name)
    return web.json_response(
        proto.ModelList(data=list(cards.values())).model_dump())


async def health(request: web.Request) -> web.Response:
    state = request.app["state"]
    problems = []
    if not state["discovery"].get_endpoints():
        problems.append("no routable engine endpoints")
    if not state["discovery"].healthy():
        problems.append("service discovery task dead")
    if state.get("scraper") and not state["scraper"].healthy():
        problems.append("engine stats scraper dead")
    watcher = state.get("config_watcher")
    if watcher and not watcher.healthy():
        problems.append("dynamic config watcher dead")
    tracker = state.get("health")
    if tracker and not tracker.healthy():
        problems.append("health re-probe task dead")
    slo_task = state.get("slo_task")
    if slo_task and not slo_task.healthy():
        problems.append("SLO evaluation task dead")
    peers = state.get("peers")
    if peers is not None and not peers.healthy():
        problems.append("peer gossip task dead")
    endpoints = state["discovery"].get_endpoints()
    body = {
        "status": "ok" if not problems else "unhealthy",
        "problems": problems,
        "router_id": state["router_id"],
        "endpoints": len(endpoints),
        "healthy_endpoints": len([ep for ep in endpoints
                                  if tracker is None
                                  or tracker.is_routable(ep.url)]),
        "breakers": tracker.snapshot() if tracker else {},
        "sheds": dict(state.get("shed_counts") or {}),
        "draining": state.get("draining_listener", False),
        "dynamic_config": watcher.current.to_json()
        if watcher and watcher.current else None,
    }
    disagg = state.get("disagg")
    if disagg is not None:
        body["prefill_pool"] = disagg.pool_snapshot()
    pools = state.get("pools")
    if pools is not None and pools.active:
        body["pools"] = pools.snapshot()
    if peers is not None:
        body["peers"] = peers.snapshot()
    if state.get("qos") is not None:
        body["qos"] = state["qos"].snapshot()
    # firing burn-rate alerts ride on /health so a probe (or a human
    # with curl) sees SLO burn without knowing about /alerts — but
    # they do NOT flip status: a burning SLO is the fleet's problem
    # to diagnose (docs/runbooks.md), not this process being sick
    slo = state.get("slo")
    if slo is not None:
        # probes arrive faster than alert states can change; serve the
        # eval task's result when it is under half an interval old
        slo.evaluate(max_age_s=0.5)
        body["firing_alerts"] = slo.firing()
    return web.json_response(body, status=200 if not problems else 503)


async def alerts(request: web.Request) -> web.Response:
    """GET /alerts: the SLO engine's full state — per-SLO good/bad
    counts and burn rates for every window, plus the alert state
    machine (pending/firing/resolved, fire counts, runbook anchors).
    The read evaluates first, so a poll always sees current states."""
    slo = request.app["state"].get("slo")
    if slo is None:
        return web.json_response(
            {"enabled": False, "slos": [], "alerts": [], "firing": []})
    return web.json_response({"enabled": True, **slo.snapshot()})


async def admin_drain(request: web.Request) -> web.Response:
    """Start/stop draining one engine endpoint: no new admissions while
    in-flight requests finish on their existing proxied connections.
    Body: {"url": "http://engine:8100", "drain": true|false}."""
    state = request.app["state"]
    tracker = state["health"]
    try:
        body = await request.json()
        url = body["url"].rstrip("/")
        drain = bool(body.get("drain", True))
    except (ValueError, KeyError, AttributeError, TypeError):
        return web.json_response(
            {"error": {"message": "body must be JSON with a 'url' "
                                  "field (and optional bool 'drain')",
                       "type": "invalid_request_error"}}, status=400)
    if drain:
        # a typo'd URL would be accepted, matched against nothing, and
        # silently drain nobody — reject unknown endpoints instead
        # (end_drain stays permissive so stale flags can be cleared)
        known = {ep.url for ep in state["discovery"].all_endpoints()}
        if url not in known:
            return web.json_response(
                {"error": {"message": f"unknown endpoint {url!r}; "
                                      f"known: {sorted(known)}",
                           "type": "invalid_request_error"}},
                status=404)
        tracker.start_drain(url)
    else:
        tracker.end_drain(url)
    return web.json_response({"draining": tracker.draining()})


async def admin_breaker(request: web.Request) -> web.Response:
    """Administratively reset one endpoint's breaker to CLOSED (clears
    consecutive-failure and windowed-rate state). The remediator calls
    this after restarting a drained engine so routing resumes without
    waiting out the open-state cooldown.
    Body: {"url": "http://engine:8100"} (optional "action": "reset")."""
    state = request.app["state"]
    tracker = state["health"]
    try:
        body = await request.json()
        url = body["url"].rstrip("/")
        action = body.get("action", "reset")
    except (ValueError, KeyError, AttributeError, TypeError):
        return web.json_response(
            {"error": {"message": "body must be JSON with a 'url' "
                                  "field",
                       "type": "invalid_request_error"}}, status=400)
    if action != "reset":
        return web.json_response(
            {"error": {"message": f"unknown action {action!r}; only "
                                  f"'reset' is supported",
                       "type": "invalid_request_error"}}, status=400)
    tracker.reset(url)
    return web.json_response({"url": url,
                              "state": tracker.state_of(url)})


async def admin_kvplane_rehome(request: web.Request) -> web.Response:
    """kvplane migration hand-off: rewrite decode-locality evidence
    after KV chunks moved replica-to-replica, so transfer-cost scoring
    follows the bytes instead of steering migrated prefixes back at
    the replica that shed them. Body: {"from": url, "to": url,
    "digests": ["<hex>", ...]} — digests optional (omit to rehome every
    entry naming "from")."""
    state = request.app["state"]
    try:
        body = await request.json()
        from_url = body["from"].rstrip("/")
        to_url = body["to"].rstrip("/")
    except (ValueError, KeyError, AttributeError, TypeError):
        return web.json_response(
            {"error": {"message": "body must be JSON with 'from' and "
                                  "'to' URL fields (and optional "
                                  "'digests' hex list)",
                       "type": "invalid_request_error"}}, status=400)
    digests = None
    if body.get("digests") is not None:
        try:
            digests = [bytes.fromhex(d) for d in body["digests"]]
        except (ValueError, TypeError):
            return web.json_response(
                {"error": {"message": "digests must be hex strings",
                           "type": "invalid_request_error"}},
                status=400)
    disagg = state.get("disagg")
    selector = disagg.selector if disagg is not None else None
    if selector is None:
        # nothing to rewrite — not an error: the planner runs the same
        # hand-off against routers with and without disagg scoring
        return web.json_response({"enabled": False, "rehomed": 0})
    # a typo'd destination would silently collect locality credit for
    # a replica that does not exist (admin_drain precedent)
    known = {ep.url for ep in state["discovery"].all_endpoints()}
    if to_url not in known:
        return web.json_response(
            {"error": {"message": f"unknown endpoint {to_url!r}; "
                                  f"known: {sorted(known)}",
                       "type": "invalid_request_error"}}, status=404)
    moved = selector.rehome(from_url, to_url, digests)
    return web.json_response({"enabled": True, "rehomed": moved})


async def version(request: web.Request) -> web.Response:
    return web.json_response({"version": __version__})


async def metrics(request: web.Request) -> web.Response:
    state = request.app["state"]
    endpoints = state["discovery"].get_endpoints()
    # evictions key off the CONFIGURED fleet: an endpoint temporarily
    # withheld from routing (probe-marked unroutable) must not lose its
    # windows/breaker state over a scrape
    configured = state["discovery"].all_endpoints()
    state["request_stats"].evict_except(ep.url for ep in configured)
    # per-endpoint phase-histogram series leave with the endpoint, like
    # every other per-endpoint family (r8 refresh_resilience precedent)
    state["metrics"].evict_phase_servers(ep.url for ep in configured)
    tracker = state.get("health")
    if tracker is not None:
        tracker.evict_except(ep.url for ep in configured)
        healthy = len([ep for ep in endpoints
                       if tracker.is_routable(ep.url)])
    else:
        healthy = len(endpoints)
    disagg = state.get("disagg")
    if disagg is not None and disagg.selector is not None:
        # discovery-driven decode churn (k8s) never passes through a
        # dynamic-config apply, so the scrape is the only hook where a
        # departed decode URL can lose its warm locality evidence — a
        # scale-up reusing the URL starts a COLD process the ring
        # would otherwise score at zero transfer cost. Breaker-open
        # counts as departed for the same reason: an in-place restart
        # on the same URL comes back with empty tiers (pessimistic
        # costs pay a refetch; optimistic phantom-zero costs misroute).
        # Draining keeps its evidence — the process, and its KV, lives.
        live = [ep.url for ep in configured]
        if tracker is not None:
            live = [u for u in live if tracker.state_of(u) == CLOSED]
        disagg.selector.evict_except(live)
    state["metrics"].refresh(state["request_stats"].get(), healthy)
    state["metrics"].refresh_overload(state["shed_counts"])
    if tracker is not None:
        state["metrics"].refresh_resilience(tracker)
    if state.get("semantic_cache") is not None:
        state["metrics"].refresh_semantic_cache(state["semantic_cache"])
    if state.get("pii_middleware") is not None:
        state["metrics"].refresh_pii(state["pii_middleware"])
    state["metrics"].refresh_routing(state["router"])
    if disagg is not None:
        state["metrics"].refresh_disagg(disagg)
    if state.get("slo") is not None:
        state["metrics"].refresh_slo(state["slo"])
    if state.get("peers") is not None:
        state["metrics"].refresh_peers(state["peers"])
    if state.get("qos") is not None:
        state["metrics"].refresh_qos(state["qos"])
    if state.get("pools") is not None:
        state["metrics"].refresh_pools(state["pools"])
    return web.Response(body=state["metrics"].render(),
                        content_type="text/plain")


async def peers_endpoint(request: web.Request) -> web.Response:
    """GET /peers: this router's shareable control-plane facts — the
    gossip wire format peer replicas poll (shared_state.RouterPeers).
    Cheap by construction: a dict walk over breaker/drain state, no
    window math."""
    state = request.app["state"]
    return web.json_response(
        peers_payload(state["router_id"], state["health"]))


# ---------------------------------------------------------------- wiring

def build_app(args: argparse.Namespace) -> web.Application:
    app = web.Application(client_max_size=64 * 1024 * 1024)
    state: dict = {
        # replica identity: reported on /health, stamped as
        # x-router-id on EVERY response (loadgen trace three-way joins
        # attribute chains per replica), and exchanged in peer gossip
        "router_id": args.router_id or derive_router_id(args.host,
                                                        args.port),
        "request_timeout": args.request_timeout,
        # hot-path statics, built once: the client timeout object and
        # the engine-auth header overlay (proxy._forward_headers) are
        # per-request allocations otherwise
        "client_timeout": aiohttp.ClientTimeout(
            total=args.request_timeout),
        "auth_overlay": engine_auth_headers(),
        # downstream deadline injected when the client sent none: the
        # engine may drop the request from its queue the moment the
        # router's own --request-timeout would have fired anyway
        # (proxy._forward_headers; engine/server.py DEADLINE_HEADER)
        "deadline_overlay": {
            "x-request-deadline-ms":
                str(int(args.request_timeout * 1000))},
        "metrics": RouterMetrics(),
        "request_stats": RequestStatsMonitor(
            horizon_s=args.request_stats_window,
            snapshot_ttl_s=args.request_stats_snapshot_ttl),
        "feature_gates": FeatureGates(args.feature_gates),
        "rewriter": make_rewriter("noop"),
        # resilience plane: per-endpoint breaker + global retry budget
        # + failover bound, consumed by proxy.route_general_request
        "health": HealthTracker(
            failure_threshold=args.breaker_threshold,
            cooldown_s=args.breaker_cooldown,
            failure_rate=args.breaker_failure_rate,
            probe_interval_s=args.breaker_probe_interval),
        "retry_budget": RetryBudget(ratio=args.retry_budget),
        "failover_attempts": max(1, args.failover_attempts),
        "inflight": 0,
        "draining_listener": False,
        # overload protection (proxy.route_general_request): the
        # router-wide admission gate, the per-endpoint concurrency cap
        # override, and the shed accounting /metrics exports
        "max_inflight": max(0, args.max_inflight),
        "endpoint_cap": args.endpoint_inflight_cap,
        "proxied_inflight": 0,
        "shed_counts": {"admission": 0, "endpoint_cap": 0},
        # request tracing (tracing.py): span ring + traceparent
        # propagation + x-trace-id stamping, consumed by
        # proxy.route_general_request; completed traces on
        # GET /debug/traces, phase histograms on /metrics
        "tracer": TraceRecorder("router",
                                ring_entries=args.trace_ring_entries,
                                sample_rate=args.trace_sample_rate),
    }
    app["state"] = state

    # QoS priority tiers (router/qos.py): graduated low-tier-first
    # admission on the r9 gates + per-tier deadline budgets + optional
    # background preemption; off unless --qos-tiers names a tier set
    if args.qos_tiers:
        state["qos"] = QosPolicy(
            args.qos_tiers, tier_rates=args.qos_tier_rates,
            preempt_from=args.qos_preempt_from,
            tenant_rate=args.qos_tenant_rate)
        state["qos_deadline_overlays"] = [
            {"x-request-deadline-ms":
             str(max(1000, int(args.request_timeout * 1000
                               * state["qos"].deadline_factor(t))))}
            for t in state["qos"].tiers]

    async def stamp_router_id(request, response):
        response.headers["x-router-id"] = state["router_id"]
    app.on_response_prepare.append(stamp_router_id)

    @web.middleware
    async def track_inflight(request, handler):
        # graceful listener drain counts every live handler, not just
        # proxied inference requests
        state["inflight"] += 1
        try:
            return await handler(request)
        finally:
            state["inflight"] -= 1
    app.middlewares.append(track_inflight)

    if args.service_discovery == "static":
        state["discovery"] = StaticServiceDiscovery(
            parse_static_urls(args.static_backends),
            parse_comma_separated(args.static_models),
            aliases=parse_static_aliases(args.static_model_aliases),
            probe=args.probe_backends,
            probe_failure_threshold=args.probe_failure_threshold,
            health_tracker=state["health"],
        )
    elif args.service_discovery == "k8s":
        state["discovery"] = K8sServiceDiscovery(
            namespace=args.k8s_namespace,
            label_selector=args.k8s_label_selector,
            engine_port=args.k8s_engine_port)
    else:
        raise ValueError(
            f"unknown service discovery {args.service_discovery!r}")

    if args.routing_logic == "prefix" and not state["feature_gates"].enabled(
            "KVAwareRouting"):
        raise ValueError("--routing-logic prefix requires the "
                         "KVAwareRouting feature gate (BETA, on by "
                         "default; it was explicitly disabled)")
    # kept in state so a dynamic-config router swap preserves the
    # CLI-configured prefix knobs (dynamic_config._apply)
    state["router_kwargs"] = {
        "prefix_chunk_chars": args.prefix_chunk_chars,
        "prefix_ring_entries": args.prefix_ring_entries,
        "prefix_cache_aware": not args.no_prefix_cache_aware,
    }
    state["router"] = make_router(args.routing_logic, args.session_key,
                                  **state["router_kwargs"])

    if state["feature_gates"].enabled("PIIDetection"):
        from production_stack_tpu.router.pii import PIIConfig, PIIMiddleware
        state["pii_middleware"] = PIIMiddleware(PIIConfig.from_args(
            args.pii_analyzer, args.pii_action, args.pii_types))
        app.middlewares.append(state["pii_middleware"].middleware)

    if state["feature_gates"].enabled("SemanticCache"):
        from production_stack_tpu.router.semantic_cache import (
            SemanticCache, make_embedder)
        state["semantic_cache"] = SemanticCache(
            embedder=make_embedder(args.semantic_cache_embedder),
            threshold=args.semantic_cache_threshold,
            max_entries=args.semantic_cache_max_entries,
            persist_dir=args.semantic_cache_dir)
    from production_stack_tpu.router.disagg import (make_orchestrator,
                                                    orchestrator_kwargs)
    # kept in state so a dynamic-config pool swap (or late creation)
    # preserves the CLI-configured disagg knobs (dynamic_config._apply);
    # built once and shared with the startup orchestrator
    state["disagg_kwargs"] = orchestrator_kwargs(args)
    disagg = make_orchestrator(args, kwargs=state["disagg_kwargs"])
    if disagg is not None:
        state["disagg"] = disagg
        logger.info("disaggregated prefill: %d prefill backends, "
                    "decode selection %s", len(disagg.endpoints),
                    "on" if disagg.selector is not None else "off")

    # SLO engine (slo.py): good/bad accounting fed by the proxy's
    # completion path + the /load scraper, burn-rate alert evaluation
    # on a short interval task, surfaced on GET /alerts, /health, and
    # /metrics. On by default — the firedrill overhead guard holds the
    # r7 band with accounting enabled — and declarative: --slo-config
    # swaps the objective set, --slo-window-scale shrinks every window
    # for drills
    if not args.no_slo:
        if args.slo_config:
            slo_cfg = SLOConfig.from_file(args.slo_config)
        else:
            slo_cfg = default_config(
                window_scale=args.slo_window_scale,
                min_events=args.slo_min_events)
        state["slo"] = SLOEngine(slo_cfg)

    # indirect through state so dynamic-config discovery swaps are followed
    state["scraper"] = EngineStatsScraper(
        lambda: state["discovery"].get_endpoints(),
        interval_s=args.engine_stats_interval)
    # cache-aware prefix routing breaks warm-endpoint ties on the
    # scraped per-engine tier hit rate (routing.PrefixAwareRouter)
    if hasattr(state["router"], "attach_scraper"):
        state["router"].attach_scraper(state["scraper"].get)

    # named pools (router/pools.py): model -> endpoints -> per-pool
    # routing policy. The manager replaces service discovery — every
    # fleet-wide consumer sees the union of pools. The startup static
    # discovery (never started) is simply discarded; dynamic config
    # can still swap/disable the table via its ``pools`` key.
    if args.pools:
        raw = args.pools
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        manager = PoolManager(state["router_kwargs"])
        manager.attach_scraper(state["scraper"].get)
        manager.apply(parse_pool_spec(raw))
        state["pools"] = manager
        state["discovery"] = manager

    if args.dynamic_config_json:
        state["config_watcher"] = DynamicConfigWatcher(
            state, args.dynamic_config_json,
            interval_s=args.dynamic_config_interval)

    # multi-router shared state (router/shared_state.py): gossip
    # breaker/drain transitions with the named peer replicas and split
    # the fleet-wide per-endpoint caps across live routers.
    # --no-shared-state keeps the flags parsed but the plane dark —
    # the multirouter rig's anti-vacuity lever.
    if args.peer_routers and not args.no_shared_state:
        state["peers"] = RouterPeers(
            state["router_id"],
            parse_comma_separated(args.peer_routers),
            state["health"],
            known_urls=lambda: [ep.url for ep in
                                state["discovery"].all_endpoints()],
            interval_s=args.peer_gossip_interval,
            stale_after_s=args.peer_stale_after)

    for path in PROXIED_PATHS:
        app.router.add_post(path, _make_proxy_handler(path))
    app.router.add_get("/v1/models", list_models)
    app.router.add_get("/health", health)
    app.router.add_get("/version", version)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/traces",
                       debug_traces_handler(lambda: state["tracer"]))
    app.router.add_get("/alerts", alerts)
    # always served (even with zero peers configured): a replica
    # joining later can start polling before this one learns about it
    app.router.add_get("/peers", peers_endpoint)
    app.router.add_post("/admin/drain", admin_drain)
    app.router.add_post("/admin/breaker", admin_breaker)
    app.router.add_post("/admin/kvplane/rehome", admin_kvplane_rehome)

    if args.enable_files_api or args.enable_batch_api:
        from production_stack_tpu.router.files_api import mount_files_api
        mount_files_api(app, args.file_storage_path)
    if args.enable_batch_api:
        from production_stack_tpu.router.batches_api import mount_batches_api
        mount_batches_api(app, args.batch_db_path)

    if args.log_stats_interval > 0:
        from production_stack_tpu.router.stats import StatLogger
        state["stat_logger"] = StatLogger(
            lambda: state["discovery"].get_endpoints(),
            state["request_stats"], state["scraper"],
            metrics=state["metrics"],
            interval_s=args.log_stats_interval,
            health_tracker=state["health"])

    if "slo" in state:
        peers_get = None
        if "peers" in state:
            # peer gossip freshness feeds the router_peer_lost signal
            # SLO through the same ingest path as engine /load samples
            peers_get = lambda: state["peers"].signal_records()  # noqa: E731
        state["slo_task"] = SLOTask(
            state["slo"], scraper_get=lambda: state["scraper"].get(),
            interval_s=args.slo_eval_interval,
            peers_get=peers_get)

    async def on_startup(app):
        state["client"] = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0))
        await state["discovery"].start()
        await state["scraper"].start()
        await state["health"].start(state["client"])
        if "peers" in state:
            await state["peers"].start(state["client"])
        if "stat_logger" in state:
            await state["stat_logger"].start()
        if "config_watcher" in state:
            await state["config_watcher"].start()
        if "slo_task" in state:
            await state["slo_task"].start()

    async def on_cleanup(app):
        if "slo_task" in state:
            await state["slo_task"].close()
        if "stat_logger" in state:
            await state["stat_logger"].close()
        if "config_watcher" in state:
            await state["config_watcher"].close()
        if "peers" in state:
            await state["peers"].close()
        await state["health"].close()
        await state["scraper"].close()
        await state["discovery"].close()
        await state["client"].close()
        if state.get("semantic_cache") is not None:
            state["semantic_cache"].persist()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        "pstpu-router",
        description="OpenAI-compatible router over TPU engine replicas")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--service-discovery", choices=["static", "k8s"],
                   default="static")
    p.add_argument("--static-backends", default="",
                   help="comma-separated engine URLs")
    p.add_argument("--static-models", default="",
                   help="comma-separated model names (same order)")
    p.add_argument("--probe-backends", action="store_true",
                   help="query each static backend's /v1/models at "
                        "startup; extra served models (e.g. LoRA "
                        "adapters) become routable aliases")
    p.add_argument("--static-model-aliases", default="",
                   help="alias:model,... pairs")
    p.add_argument("--pools", default="",
                   help="named-pool fleet spec: JSON object (inline, "
                        "or @/path/to/file) mapping pool name to "
                        "{backends, models, routing_logic?, "
                        "session_key?}. Requests route on their body's "
                        "``model`` to the owning pool and ITS routing-"
                        "policy instance; a model no pool serves is a "
                        "structured 404. Replaces service discovery "
                        "with the union of pools; hot-swappable via "
                        "the dynamic-config ``pools`` key")
    p.add_argument("--k8s-namespace", default="default")
    p.add_argument("--k8s-label-selector", default="")
    p.add_argument("--k8s-engine-port", type=int, default=8100)
    p.add_argument("--routing-logic",
                   choices=["roundrobin", "session", "least_loaded",
                            "prefix"],
                   default="roundrobin")
    p.add_argument("--session-key", default="x-user-id")
    p.add_argument("--prefix-chunk-chars", type=int, default=256,
                   help="prefix-router ring granularity: prompt text is "
                        "chain-hashed in chunks of this many chars; one "
                        "ring entry per chunk (should roughly track the "
                        "engine-side kv chunk_size in text terms)")
    p.add_argument("--prefix-ring-entries", type=int, default=65536,
                   help="max chunk digests the prefix router tracks "
                        "(LRU)")
    p.add_argument("--no-prefix-cache-aware", action="store_true",
                   help="disable expected-hit-bytes scoring: the prefix "
                        "policy falls back to pure hash affinity "
                        "(pre-r11 behavior)")
    p.add_argument("--engine-stats-interval", type=float, default=10.0)
    p.add_argument("--log-stats-interval", type=float, default=0.0,
                   help="seconds between periodic per-engine stat log "
                        "lines (0 disables; the reference's "
                        "--log-stats equivalent)")
    p.add_argument("--request-stats-window", type=float, default=30.0)
    p.add_argument("--request-stats-snapshot-ttl", type=float,
                   default=0.05,
                   help="seconds a routing-decision stats snapshot may "
                        "be reused before the sliding-window aggregates "
                        "are recomputed (in-flight counters are always "
                        "live; 0 recomputes every request)")
    p.add_argument("--request-timeout", type=float, default=600.0)
    p.add_argument("--probe-failure-threshold", type=int, default=3,
                   help="consecutive /v1/models probe failures before "
                        "static discovery marks an endpoint unroutable "
                        "(with --probe-backends)")
    p.add_argument("--breaker-threshold", type=int, default=3,
                   help="consecutive upstream failures before an "
                        "endpoint's circuit opens")
    p.add_argument("--breaker-cooldown", type=float, default=5.0,
                   help="seconds an open circuit waits before the "
                        "half-open /v1/models re-probe")
    p.add_argument("--breaker-failure-rate", type=float, default=0.5,
                   help="windowed failure-rate trip (fraction, over "
                        ">=20 samples in the last 30s)")
    p.add_argument("--breaker-probe-interval", type=float, default=1.0,
                   help="seconds between half-open re-probe passes")
    p.add_argument("--failover-attempts", type=int, default=3,
                   help="max backend attempts per request for failures "
                        "occurring before any byte reaches the client "
                        "(1 disables failover)")
    p.add_argument("--max-inflight", type=int, default=0,
                   help="router-wide admission gate: shed with 429 + "
                        "Retry-After once this many proxied requests "
                        "are in flight (0 = unlimited)")
    p.add_argument("--endpoint-inflight-cap", type=int, default=0,
                   help="static per-endpoint concurrency cap; 0 derives "
                        "the cap from each engine's advertised capacity "
                        "(tpu:engine_capacity_seqs on /metrics; engines "
                        "with unbounded admission stay uncapped)")
    p.add_argument("--retry-budget", type=float, default=0.2,
                   help="failover retries allowed as a fraction of "
                        "request volume (token bucket; bounds retry "
                        "storms)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="seconds SIGTERM waits for in-flight requests "
                        "after the listener stops accepting")
    p.add_argument("--dynamic-config-json", default=None)
    p.add_argument("--dynamic-config-interval", type=float, default=10.0)
    p.add_argument("--feature-gates", default=None,
                   help="Name=true,Name2=false")
    p.add_argument("--semantic-cache-dir", default=None,
                   help="persist the semantic cache index/metadata here")
    p.add_argument("--semantic-cache-threshold", type=float, default=0.95)
    p.add_argument("--semantic-cache-max-entries", type=int, default=4096)
    p.add_argument("--semantic-cache-embedder", default="hashing",
                   help="'hashing' (dependency-free), "
                        "'engine:http://host:port[#model]' (REAL "
                        "embeddings via an engine's /v1/embeddings — "
                        "models/encoder.py), or "
                        "'sentence-transformers/<model>'")
    p.add_argument("--pii-analyzer", default="regex",
                   help="'regex' (dependency-free patterns) or "
                        "'ner:<checkpoint-dir>' (BERT token-"
                        "classification model via the JAX encoder — "
                        "finds names/places/orgs regex cannot)")
    p.add_argument("--pii-action", choices=["block", "redact"],
                   default="block")
    p.add_argument("--pii-types", default=None,
                   help="comma-separated PIIType values (default: all)")
    p.add_argument("--prefill-backends", default="",
                   help="comma-separated kv_producer engine URLs enabling "
                        "disaggregated prefill")
    p.add_argument("--prefill-models", default="",
                   help="comma-separated model names for the prefill pool "
                        "(same order)")
    p.add_argument("--prefill-timeout", type=float, default=15.0,
                   help="hard cap on one disagg prefill pass")
    p.add_argument("--prefill-headstart", type=float, default=2.0,
                   help="max seconds decode routing waits on the prefill "
                        "pool; past it, decode proceeds while prefill "
                        "keeps publishing KV in the background")
    p.add_argument("--prefill-breaker-threshold", type=int, default=3,
                   help="consecutive failures before a prefill backend's "
                        "circuit opens")
    p.add_argument("--prefill-breaker-cooldown", type=float, default=30.0,
                   help="seconds an open prefill circuit stays open")
    p.add_argument("--disagg-min-prompt-chars", type=int, default=0,
                   help="prompts shorter than this skip the prefill "
                        "stage entirely (a 1-token pass costs more "
                        "than prefilling a short prompt on the decode "
                        "engine; 0 = disaggregate everything)")
    p.add_argument("--disagg-chunk-chars", type=int, default=256,
                   help="decode-selection chunk granularity: prompt "
                        "text is chain-hashed in chunks of this many "
                        "chars for the transfer-cost model (should "
                        "roughly track the engine-side kv chunk_size "
                        "in text terms)")
    p.add_argument("--disagg-transfer-weight", type=float, default=1.0,
                   help="decode-selection weight on expected KV "
                        "transfer bytes")
    p.add_argument("--disagg-load-weight", type=float, default=1.0,
                   help="decode-selection weight on scraped decode "
                        "load (in-flight / advertised capacity)")
    p.add_argument("--disagg-remote-cost", type=float, default=1.0,
                   help="per-byte cost of pulling a chunk from the "
                        "shared remote tier (relative units)")
    p.add_argument("--disagg-recompute-cost", type=float, default=2.0,
                   help="per-byte cost of recomputing a chunk the "
                        "tiers don't hold (relative units; > remote "
                        "cost when the DCN link beats prefill compute)")
    p.add_argument("--no-disagg-decode-selection", action="store_true",
                   help="disable transfer-cost decode selection: the "
                        "configured routing policy picks the decode "
                        "engine unassisted")
    p.add_argument("--trace-ring-entries", type=int, default=2048,
                   help="completed request traces kept per process "
                        "(bounded ring served on GET /debug/traces)")
    p.add_argument("--trace-sample-rate", type=float, default=1.0,
                   help="fraction of requests whose span timeline "
                        "enters the trace ring (phase histograms always "
                        "record; an inbound sampled traceparent flag "
                        "wins either way)")
    p.add_argument("--no-slo", action="store_true",
                   help="disable the in-process SLO engine (burn-rate "
                        "accounting, /alerts, tpu:slo_* families)")
    p.add_argument("--slo-config", default=None,
                   help="SLO definition JSON file (slo.SLOConfig "
                        "shape: objectives, window_scale, min_events); "
                        "default: the built-in objective set")
    p.add_argument("--slo-window-scale", type=float, default=1.0,
                   help="multiply every burn-rate window and alert "
                        "hold duration (labels stay canonical; the "
                        "firedrill rig's lever — ignored when "
                        "--slo-config provides its own scale)")
    p.add_argument("--slo-min-events", type=int, default=12,
                   help="volume floor both windows of an alert must "
                        "hold before its condition can be true (one "
                        "bad request against an empty window must "
                        "never page)")
    p.add_argument("--slo-eval-interval", type=float, default=1.0,
                   help="seconds between alert-state evaluation ticks "
                        "(also pulls fresh /load samples into the "
                        "signal SLOs)")
    p.add_argument("--router-id", default=None,
                   help="replica identity reported on /health, "
                        "stamped as x-router-id on every response, "
                        "and exchanged in peer gossip (default: "
                        "derived from host:port)")
    p.add_argument("--peer-routers", default="",
                   help="comma-separated peer router base URLs: "
                        "enables the multi-router shared-state plane "
                        "(breaker/drain gossip via GET /peers, "
                        "apportioned per-endpoint caps)")
    p.add_argument("--peer-gossip-interval", type=float, default=1.0,
                   help="seconds between peer gossip rounds")
    p.add_argument("--peer-stale-after", type=float, default=None,
                   help="seconds of gossip silence before a peer "
                        "stops counting toward the live-router cap "
                        "split (default: 3x the gossip interval)")
    p.add_argument("--no-shared-state", action="store_true",
                   help="parse --peer-routers but keep the gossip "
                        "plane dark (no breaker/drain exchange, no "
                        "cap splitting) — the multirouter rig's "
                        "anti-vacuity lever")
    p.add_argument("--qos-tiers", default="",
                   help="enable QoS priority tiers: ordered "
                        "name=admit_fraction pairs, highest priority "
                        "first (canonical: "
                        "'tier0=1.0,tier1=0.85,tier2=0.7'). Requests "
                        "pick a tier via the x-priority-class header "
                        "(name or index; untagged traffic = tier 0); "
                        "tier k admits only while proxied in-flight "
                        "is under fraction*--max-inflight, so "
                        "saturation sheds low tiers first")
    p.add_argument("--qos-tier-rates", default="",
                   help="optional per-tier token buckets: "
                        "name=requests_per_second pairs (absent = "
                        "uncapped rate)")
    p.add_argument("--qos-tenant-rate", type=float, default=0.0,
                   help="per-tenant token bucket nested inside every "
                        "QoS tier (requests/second per distinct "
                        "x-tenant-id value; 0 disables). A tenant over "
                        "its bucket sheds 429 + Retry-After WITHOUT "
                        "drawing from its tier's shared budget, so a "
                        "noisy tenant cannot starve its tier peers")
    p.add_argument("--qos-preempt-from", type=int, default=None,
                   help="tiers at or past this index register as "
                        "preemptable while their backend dispatch is "
                        "pre-first-byte; a higher-priority arrival at "
                        "the full gate takes the newest such slot "
                        "(victim gets a structured 503 + Retry-After). "
                        "Default: only the lowest tier; pass the tier "
                        "count to disable preemption")
    p.add_argument("--enable-files-api", action="store_true")
    p.add_argument("--enable-batch-api", action="store_true")
    p.add_argument("--file-storage-path", default="/tmp/pstpu_files")
    p.add_argument("--batch-db-path", default="/tmp/pstpu_batches.db")
    args = p.parse_args(argv)
    if args.service_discovery == "static" and not args.static_backends \
            and not args.pools:
        p.error("--static-backends is required with static discovery "
                "(or name the fleet via --pools)")
    if args.service_discovery == "k8s" and not args.k8s_label_selector:
        p.error("--k8s-label-selector is required with k8s discovery")
    return args


def main(argv=None) -> None:
    args = parse_args(argv)
    set_ulimit()
    app = build_app(args)

    async def _serve():
        # handler_cancellation: a client disconnect cancels the relay
        # task, which closes the backend connection — propagating the
        # disconnect to the engine so IT can abort the generation
        # (aiohttp >= 3.9 defaults this off; without it an abandoned
        # request is only noticed when the next token write fails).
        # access_log=None: the default access logger formats a line per
        # request even when no handler consumes it — per-request stats
        # live in the stats plane, not in access logs
        runner = web.AppRunner(app, handler_cancellation=True,
                               access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, args.host, args.port)
        await site.start()
        logger.info("router listening on %s:%d (%s discovery, %s routing)",
                    args.host, args.port, args.service_discovery,
                    args.routing_logic)
        # graceful drain: SIGTERM/SIGINT stops the listener (no new
        # connections) and waits for in-flight requests to finish
        # within --drain-timeout before tearing the app down
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
        state = app["state"]
        state["draining_listener"] = True
        logger.info("shutdown: draining listener (%d in-flight, "
                    "bound %.0fs)", state["inflight"], args.drain_timeout)
        await site.stop()
        drained = await wait_for_drain(lambda: state["inflight"],
                                       args.drain_timeout)
        logger.info("shutdown: %s", "drained clean" if drained else
                    f"{state['inflight']} requests still in flight at "
                    f"the drain bound; closing anyway")
        await runner.cleanup()

    asyncio.run(_serve())


if __name__ == "__main__":
    main()
