"""Routing policies: pick a backend for each request.

Capability parity with reference src/vllm_router/routers/routing_logic.py
(round-robin :45-76; session consistent-hash with lowest-QPS fallback
:79-172), re-designed:

- The consistent-hash ring is implemented here directly (md5 points,
  vnode replicas, bisect lookup) instead of depending on uhashring;
  same invariants: stable mapping, minimal remapping on join/leave.
- An extra ``prefix`` policy routes by a hash of the request's prompt
  prefix — KV-cache-affinity routing so multi-round conversations with
  shared history land where their KV blocks live (the TPU stack's
  answer to LMCache-aware routing).

Health awareness: the proxy filters the endpoint list through
``resilience.HealthTracker.healthy_endpoints`` before calling ANY
policy, so breaker-open / draining endpoints are invisible here. The
session/prefix rings rebuild from whatever list arrives — consistent
hashing means a health transition remaps only the failed endpoint's
keys (to deterministic successors) and returns them when it recovers;
everyone else's mapping is untouched (pinned by
tests/test_router_resilience.py).
"""

import bisect
import hashlib
import json
import time
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Sequence

from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class Router(ABC):
    name = "abstract"

    @abstractmethod
    def route(self, endpoints: Sequence[EndpointInfo], request_stats: Dict,
              headers: Dict[str, str], body: dict) -> str:
        """Return the chosen backend URL. endpoints is non-empty."""


class RoundRobinRouter(Router):
    name = "roundrobin"

    def __init__(self):
        self._counter = 0

    def route(self, endpoints, request_stats, headers, body) -> str:
        ordered = sorted(endpoints, key=lambda e: e.url)
        choice = ordered[self._counter % len(ordered)]
        self._counter += 1
        return choice.url


class LeastLoadedRouter(Router):
    """Lowest observed in-flight requests (falls back to QPS, then RR).

    Slow start: an endpoint this router has never routed to (freshly
    added to the fleet) or one returning after an absence (filtered
    out while its breaker was open / probe-marked unroutable) used to
    score as idle and absorb the entire arrival burst at once. Such
    endpoints instead carry a virtual load — just above the busiest
    known endpoint's in-flight count, decaying linearly to zero over
    ``slow_start_s`` — so traffic ramps onto them. A cold start (the
    router's very first call, when everything is equally new) ramps
    nothing. Absence is detected against routing activity: an
    endpoint missing from ``absent_reset_s`` worth of *calls* restarts
    its ramp; an idle router restarts nobody's.
    """

    name = "least_loaded"

    def __init__(self, slow_start_s: float = 10.0,
                 absent_reset_s: float = 2.0,
                 now_fn=time.monotonic):
        self._rr = RoundRobinRouter()
        self.slow_start_s = slow_start_s
        self.absent_reset_s = absent_reset_s
        self._now = now_fn
        self._last_seen: Dict[str, float] = {}   # url -> last call with it
        self._ramp_from: Dict[str, float] = {}   # url -> ramp start
        self._last_call_at: Optional[float] = None

    def route(self, endpoints, request_stats, headers, body) -> str:
        now = self._now()
        cold = not self._last_seen
        for ep in endpoints:
            last = self._last_seen.get(ep.url)
            if last is None:
                if not cold:
                    self._ramp_from[ep.url] = now
            elif self._last_call_at is not None and \
                    self._last_call_at - last >= self.absent_reset_s:
                # the router kept routing without this endpoint (it was
                # health-filtered away): back from the dead, ramp it
                self._ramp_from[ep.url] = now
            self._last_seen[ep.url] = now
        self._last_call_at = now
        if len(self._last_seen) > 4 * len(endpoints) + 64:
            # bound growth across dynamic-config fleet swaps
            live = {ep.url for ep in endpoints}
            self._last_seen = {u: t for u, t in self._last_seen.items()
                               if u in live}
        peak = max((st.in_flight for st in request_stats.values()),
                   default=0)

        def load(ep: EndpointInfo):
            st = request_stats.get(ep.url)
            real = (float(st.in_flight), st.qps) if st is not None \
                else (0.0, 0.0)
            start = self._ramp_from.get(ep.url)
            if start is None or self.slow_start_s <= 0:
                return real
            ramp = min(1.0, (now - start) / self.slow_start_s)
            if ramp >= 1.0:
                del self._ramp_from[ep.url]
                return real
            # peak+1 (not peak): the ramping endpoint must start
            # strictly busier-looking than the busiest known one, or
            # the qps tiebreak still hands it the whole burst
            return (max(real[0], (1.0 - ramp) * (peak + 1.0)), real[1])
        if not request_stats:
            return self._rr.route(endpoints, request_stats, headers, body)
        return min(endpoints, key=load).url


class HashRing:
    """Consistent hashing: md5 ring with virtual nodes."""

    def __init__(self, vnodes: int = 128):
        self.vnodes = vnodes
        self._points: List[int] = []
        self._owners: Dict[int, str] = {}
        self._nodes: List[str] = []

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")

    def rebuild(self, nodes: Sequence[str]) -> None:
        nodes = sorted(set(nodes))
        if nodes == self._nodes:
            return
        self._nodes = list(nodes)
        self._points = []
        self._owners = {}
        for node in nodes:
            for i in range(self.vnodes):
                p = self._hash(f"{node}#{i}")
                self._points.append(p)
                self._owners[p] = node
        self._points.sort()

    def lookup(self, key: str) -> Optional[str]:
        if not self._points:
            return None
        h = self._hash(key)
        idx = bisect.bisect(self._points, h) % len(self._points)
        return self._owners[self._points[idx]]


class SessionRouter(Router):
    """Sticky sessions via consistent hashing on a session header.

    Requests without the session header fall back to least-loaded
    (parity with reference routing_logic.py:94-115's QPS fallback).
    """

    name = "session"

    def __init__(self, session_key: str = "x-user-id", vnodes: int = 128):
        self.session_key = session_key
        self._ring = HashRing(vnodes)
        self._fallback = LeastLoadedRouter()

    def route(self, endpoints, request_stats, headers, body) -> str:
        self._ring.rebuild([e.url for e in endpoints])
        session_id = headers.get(self.session_key)
        if not session_id:
            return self._fallback.route(endpoints, request_stats, headers,
                                        body)
        return self._ring.lookup(session_id)


class PrefixAwareRouter(Router):
    """KV-affinity: hash the first `prefix_chars` of the prompt/messages.

    Conversations sharing a long system prompt + history map to the same
    engine, whose KV tiers (HBM/host) already hold those blocks.
    """

    name = "prefix"

    def __init__(self, prefix_chars: int = 1024, vnodes: int = 128):
        self.prefix_chars = prefix_chars
        self._ring = HashRing(vnodes)
        self._fallback = LeastLoadedRouter()

    @staticmethod
    def _prompt_text(body: dict) -> str:
        if "messages" in body:
            try:
                return json.dumps(body["messages"])
            except (TypeError, ValueError):
                return ""
        prompt = body.get("prompt", "")
        return prompt if isinstance(prompt, str) else json.dumps(prompt)

    def route(self, endpoints, request_stats, headers, body) -> str:
        self._ring.rebuild([e.url for e in endpoints])
        text = self._prompt_text(body)[:self.prefix_chars]
        if not text:
            return self._fallback.route(endpoints, request_stats, headers,
                                        body)
        return self._ring.lookup(text)


_ROUTERS = {
    "roundrobin": RoundRobinRouter,
    "least_loaded": LeastLoadedRouter,
    "session": SessionRouter,
    "prefix": PrefixAwareRouter,
}


def make_router(name: str, session_key: str = "x-user-id") -> Router:
    if name not in _ROUTERS:
        raise ValueError(f"unknown routing logic {name!r}; "
                         f"options: {sorted(_ROUTERS)}")
    if name == "session":
        return SessionRouter(session_key=session_key)
    return _ROUTERS[name]()
