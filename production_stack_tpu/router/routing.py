"""Routing policies: pick a backend for each request.

Capability parity with reference src/vllm_router/routers/routing_logic.py
(round-robin :45-76; session consistent-hash with lowest-QPS fallback
:79-172), re-designed:

- The consistent-hash ring is implemented here directly (md5 points,
  vnode replicas, bisect lookup) instead of depending on uhashring;
  same invariants: stable mapping, minimal remapping on join/leave.
- An extra ``prefix`` policy routes by a hash of the request's prompt
  prefix — KV-cache-affinity routing so multi-round conversations with
  shared history land where their KV blocks live (the TPU stack's
  answer to LMCache-aware routing).

Health awareness: the proxy filters the endpoint list through
``resilience.HealthTracker.healthy_endpoints`` before calling ANY
policy, so breaker-open / draining endpoints are invisible here. The
session/prefix rings rebuild from whatever list arrives — consistent
hashing means a health transition remaps only the failed endpoint's
keys (to deterministic successors) and returns them when it recovers;
everyone else's mapping is untouched (pinned by
tests/test_router_resilience.py).
"""

import bisect
import hashlib
import json
import time
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Sequence

from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


def prompt_text(body: dict) -> str:
    """Canonical prompt rendering for chunk hashing — shared by the
    prefix ring and the disagg DecodeSelector. Both rings must chunk
    the SAME text or affinity and transfer-cost scoring silently
    diverge on identical requests."""
    if "messages" in body:
        try:
            return json.dumps(body["messages"])
        except (TypeError, ValueError):
            return ""
    prompt = body.get("prompt", "")
    return prompt if isinstance(prompt, str) else json.dumps(prompt)


def prompt_chunk_digests(text: str, chunk_chars: int,
                         max_track_chars: int) -> List[bytes]:
    """Chained digests of the prompt's full chunk_chars chunks
    (bounded by max_track_chars; a partial tail chunk is skipped,
    mirroring chunk-granular tier storage)."""
    from production_stack_tpu.kvcache.chunks import chain_digest_bytes
    data = text[:max_track_chars].encode("utf-8", "ignore")
    return chain_digest_bytes(data, chunk_chars)


def record_chunk_holders(ring, digests, url: str, *,
                         urls_per_chunk: int, max_entries: int) -> None:
    """Record ``url`` as a recent holder of each digest in an
    OrderedDict ring (most recent last, LRU over digests, at most
    ``urls_per_chunk`` holders per digest)."""
    for d in digests:
        urls = ring.get(d)
        if urls is None:
            ring[d] = [url]
        else:
            if url in urls:
                urls.remove(url)
            urls.append(url)
            del urls[:-urls_per_chunk]
            ring.move_to_end(d)
    while len(ring) > max_entries:
        ring.popitem(last=False)


class AffinityTracker:
    """Counts affinity *moves*: routing decisions where a key
    (session id / prompt prefix) lands on a different endpoint than
    its previous home. Reasons:

    - ``endpoint_lost`` — the previous home is absent from this
      decision's candidate set (breaker open, draining, removed):
      the expected, bounded churn consistent hashing promises.
    - ``endpoint_recovered`` — the key moved BACK to the home it held
      before its last ``endpoint_lost`` displacement: the second half
      of the same expected churn (breaker closed / drain ended), not
      drift — counting it as rebalance would spike the split-brain
      signal after every ordinary recovery.
    - ``rebalance`` — the previous home was still a candidate but
      the policy picked elsewhere: in a single router this is warm-
      scoring drift; across N routers it is the split-brain signal
      the multi-router control plane exists to keep near zero
      (``tpu:router_affinity_moves_total{reason}``).

    Bounded LRU over keys; one dict get + set per routing decision.
    """

    __slots__ = ("_homes", "max_entries", "moves")

    def __init__(self, max_entries: int = 4096):
        import collections
        # key -> [current_home, displaced_home-or-None]
        self._homes: "collections.OrderedDict[str, List[Optional[str]]]" \
            = collections.OrderedDict()
        self.max_entries = max_entries
        self.moves = {"endpoint_lost": 0, "endpoint_recovered": 0,
                      "rebalance": 0}

    def note(self, key: str, url: str, candidate_urls) -> None:
        entry = self._homes.get(key)
        if entry is None:
            entry = self._homes[key] = [url, None]
        else:
            prev, displaced = entry
            if prev != url:
                if prev not in candidate_urls:
                    self.moves["endpoint_lost"] += 1
                    entry[1] = prev       # remember the real home
                elif url == displaced:
                    self.moves["endpoint_recovered"] += 1
                    entry[1] = None
                else:
                    self.moves["rebalance"] += 1
                    entry[1] = None
                entry[0] = url
        self._homes.move_to_end(key)
        while len(self._homes) > self.max_entries:
            self._homes.popitem(last=False)


class Router(ABC):
    name = "abstract"

    @abstractmethod
    def route(self, endpoints: Sequence[EndpointInfo], request_stats: Dict,
              headers: Dict[str, str], body: dict) -> str:
        """Return the chosen backend URL. endpoints is non-empty."""


class RoundRobinRouter(Router):
    name = "roundrobin"

    def __init__(self):
        self._counter = 0

    def route(self, endpoints, request_stats, headers, body) -> str:
        ordered = sorted(endpoints, key=lambda e: e.url)
        choice = ordered[self._counter % len(ordered)]
        self._counter += 1
        return choice.url


class LeastLoadedRouter(Router):
    """Lowest observed in-flight requests (falls back to QPS, then RR).

    Slow start: an endpoint this router has never routed to (freshly
    added to the fleet) or one returning after an absence (filtered
    out while its breaker was open / probe-marked unroutable) used to
    score as idle and absorb the entire arrival burst at once. Such
    endpoints instead carry a virtual load — just above the busiest
    known endpoint's in-flight count, decaying linearly to zero over
    ``slow_start_s`` — so traffic ramps onto them. A cold start (the
    router's very first call, when everything is equally new) ramps
    nothing. Absence is detected against routing activity: an
    endpoint missing from ``absent_reset_s`` worth of *calls* restarts
    its ramp; an idle router restarts nobody's.
    """

    name = "least_loaded"

    def __init__(self, slow_start_s: float = 10.0,
                 absent_reset_s: float = 2.0,
                 now_fn=time.monotonic):
        self._rr = RoundRobinRouter()
        self.slow_start_s = slow_start_s
        self.absent_reset_s = absent_reset_s
        self._now = now_fn
        self._last_seen: Dict[str, float] = {}   # url -> last call with it
        self._ramp_from: Dict[str, float] = {}   # url -> ramp start
        self._last_call_at: Optional[float] = None

    def route(self, endpoints, request_stats, headers, body) -> str:
        now = self._now()
        cold = not self._last_seen
        for ep in endpoints:
            last = self._last_seen.get(ep.url)
            if last is None:
                if not cold:
                    self._ramp_from[ep.url] = now
            elif self._last_call_at is not None and \
                    self._last_call_at - last >= self.absent_reset_s:
                # the router kept routing without this endpoint (it was
                # health-filtered away): back from the dead, ramp it
                self._ramp_from[ep.url] = now
            self._last_seen[ep.url] = now
        self._last_call_at = now
        if len(self._last_seen) > 4 * len(endpoints) + 64:
            # bound growth across dynamic-config fleet swaps
            live = {ep.url for ep in endpoints}
            self._last_seen = {u: t for u, t in self._last_seen.items()
                               if u in live}
        peak = max((st.in_flight for st in request_stats.values()),
                   default=0)

        def load(ep: EndpointInfo):
            st = request_stats.get(ep.url)
            real = (float(st.in_flight), st.qps) if st is not None \
                else (0.0, 0.0)
            start = self._ramp_from.get(ep.url)
            if start is None or self.slow_start_s <= 0:
                return real
            ramp = min(1.0, (now - start) / self.slow_start_s)
            if ramp >= 1.0:
                del self._ramp_from[ep.url]
                return real
            # peak+1 (not peak): the ramping endpoint must start
            # strictly busier-looking than the busiest known one, or
            # the qps tiebreak still hands it the whole burst
            return (max(real[0], (1.0 - ramp) * (peak + 1.0)), real[1])
        if not request_stats:
            return self._rr.route(endpoints, request_stats, headers, body)
        return min(endpoints, key=load).url


class HashRing:
    """Consistent hashing: md5 ring with virtual nodes."""

    def __init__(self, vnodes: int = 128):
        self.vnodes = vnodes
        self._points: List[int] = []
        self._owners: Dict[int, str] = {}
        self._nodes: List[str] = []

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")

    def rebuild(self, nodes: Sequence[str]) -> None:
        nodes = sorted(set(nodes))
        if nodes == self._nodes:
            return
        self._nodes = list(nodes)
        self._points = []
        self._owners = {}
        for node in nodes:
            for i in range(self.vnodes):
                p = self._hash(f"{node}#{i}")
                self._points.append(p)
                self._owners[p] = node
        self._points.sort()

    def lookup(self, key: str) -> Optional[str]:
        if not self._points:
            return None
        h = self._hash(key)
        idx = bisect.bisect(self._points, h) % len(self._points)
        return self._owners[self._points[idx]]


class SessionRouter(Router):
    """Sticky sessions via consistent hashing on a session header.

    Requests without the session header fall back to least-loaded
    (parity with reference routing_logic.py:94-115's QPS fallback).
    """

    name = "session"

    def __init__(self, session_key: str = "x-user-id", vnodes: int = 128):
        self.session_key = session_key
        self._ring = HashRing(vnodes)
        self._fallback = LeastLoadedRouter()
        self.affinity = AffinityTracker()

    @property
    def affinity_moves(self) -> Dict[str, int]:
        return self.affinity.moves

    def route(self, endpoints, request_stats, headers, body) -> str:
        self._ring.rebuild([e.url for e in endpoints])
        session_id = headers.get(self.session_key)
        if not session_id:
            return self._fallback.route(endpoints, request_stats, headers,
                                        body)
        url = self._ring.lookup(session_id)
        self.affinity.note(session_id, url, {e.url for e in endpoints})
        return url


class PrefixAwareRouter(Router):
    """Cache-aware KV-affinity routing (ISSUE 6 / ROADMAP item 1).

    Scores candidate endpoints by *expected prefix-hit bytes*, not just
    a prefix hash. Two signals feed the score:

    - a **chunk-granularity prefix ring**: the prompt text is chained
      into fixed-size chunk digests (the router-side mirror of
      kvcache/chunks.ChunkHasher — chunk i's digest folds chunk i-1's,
      so a digest match implies the whole leading prefix matches), and
      every routing decision records the chosen endpoint against the
      prompt's digests. Because the chosen engine prefills and then
      *publishes* exactly those chunks through its tiers
      (connector.on_prefill_progress/on_finish), the ring is a
      router-side view of the producer-publish path. Expected hit bytes
      for an endpoint = longest leading digest run it has served ×
      chunk size.
    - **scraped per-endpoint hit stats**: ties between equally-warm
      endpoints break on the engine-reported tier hit rate
      (EngineStats.kv_hit_rate from /load), then on live in-flight.

    Cold prefixes (no endpoint has served any leading chunk) fall back
    to consistent-hash affinity over the full configured prefix — the
    pre-cache-aware behavior — so repeated cold prefixes still converge
    onto one replica, and a replica that never saw the session still
    hits through the shared remote tier (kvcache/server.py as the
    cross-replica rendezvous).

    The ring is bounded (``ring_entries`` digests, LRU) and each digest
    remembers at most ``_URLS_PER_CHUNK`` recent servers — with a shared
    remote tier EVERY replica can serve a published chunk, but host-RAM
    locality (and therefore TTFT) is best on the replicas that computed
    or recently fetched it.
    """

    name = "prefix"

    _URLS_PER_CHUNK = 4

    def __init__(self, prefix_chars: int = 1024, vnodes: int = 128,
                 chunk_chars: int = 256, ring_entries: int = 65536,
                 max_track_chars: int = 8192, cache_aware: bool = True):
        self.prefix_chars = prefix_chars
        self.chunk_chars = max(1, chunk_chars)
        self.ring_entries = ring_entries
        self.max_track_chars = max_track_chars
        self.cache_aware = cache_aware
        self._ring = HashRing(vnodes)
        self._fallback = LeastLoadedRouter()
        # digest -> list of recent server URLs (most recent last); LRU
        # over digests via OrderedDict move_to_end
        import collections
        self._chunks: "collections.OrderedDict[bytes, List[str]]" = \
            collections.OrderedDict()
        self._get_engine_stats = None    # attach_scraper
        self.warm_routes = 0
        self.cold_routes = 0
        self.affinity = AffinityTracker()

    @property
    def affinity_moves(self) -> Dict[str, int]:
        return self.affinity.moves

    def attach_scraper(self, get_stats) -> None:
        """``get_stats() -> {url: EngineStats}`` (the router app passes
        EngineStatsScraper.get) — enables the hit-rate tiebreak."""
        self._get_engine_stats = get_stats

    _prompt_text = staticmethod(prompt_text)

    def _chunk_digests(self, text: str) -> List[bytes]:
        return prompt_chunk_digests(text, self.chunk_chars,
                                    self.max_track_chars)

    def _record(self, digests: List[bytes], url: str) -> None:
        """Feed the ring: the chosen engine will prefill-and-publish
        these chunks (producer path), or already held them."""
        record_chunk_holders(self._chunks, digests, url,
                             urls_per_chunk=self._URLS_PER_CHUNK,
                             max_entries=self.ring_entries)

    def _expected_hit_chunks(self, digests: List[bytes],
                             urls) -> Dict[str, int]:
        """Deepest recorded digest membership per candidate. A chained
        digest at depth i matches only if the WHOLE prefix through i
        matches, and an endpoint was recorded for depth i only by
        serving a prompt covering depths 0..i — so one deep membership
        is complete evidence for the full leading run. Scoring by the
        deepest membership (not a leading-run intersection) keeps the
        per-chunk holder cap harmless: a popular fleet-wide system
        prompt may evict an endpoint from the crowded EARLY chunks'
        holder lists while its session-specific deep chunks still
        name it."""
        score = {u: 0 for u in urls}
        for i, d in enumerate(digests):
            holders = self._chunks.get(d)
            if not holders:
                continue   # LRU-evicted or never seen; deeper evidence
                           # (if any) still stands on its own
            for u in holders:
                if u in score:
                    score[u] = i + 1
        return score

    def route(self, endpoints, request_stats, headers, body) -> str:
        self._ring.rebuild([e.url for e in endpoints])
        text = self._prompt_text(body)
        if not text:
            return self._fallback.route(endpoints, request_stats, headers,
                                        body)
        if not self.cache_aware:
            return self._ring.lookup(text[:self.prefix_chars])
        digests = self._chunk_digests(text)
        score = self._expected_hit_chunks(
            digests, [e.url for e in endpoints]) if digests else {}
        best = max(score.values(), default=0)
        if best > 0:
            self.warm_routes += 1
            warm = [u for u, s in score.items() if s == best]
            url = warm[0] if len(warm) == 1 else self._tiebreak(
                warm, request_stats)
        else:
            # cold prefix: consistent-hash affinity so repeats converge
            self.cold_routes += 1
            url = self._ring.lookup(text[:self.prefix_chars])
        self._record(digests, url)
        self.affinity.note(text[:self.prefix_chars], url,
                           {e.url for e in endpoints})
        return url

    def _tiebreak(self, urls: List[str], request_stats) -> str:
        """Equally-warm endpoints: prefer the higher engine-reported
        tier hit rate, then the lower live in-flight, then URL order
        (deterministic)."""
        stats = {}
        if self._get_engine_stats is not None:
            try:
                stats = self._get_engine_stats() or {}
            except Exception:
                stats = {}

        def key(u: str):
            es = stats.get(u)
            rs = request_stats.get(u)
            return (-(es.kv_hit_rate if es is not None else 0.0),
                    rs.in_flight if rs is not None else 0,
                    u)
        return min(urls, key=key)

    def expected_hit_bytes(self, body: dict, url: str,
                           bytes_per_chunk: Optional[int] = None) -> int:
        """Introspection/debug: the score the router would assign
        ``url`` for this body, in (approximate) bytes."""
        digests = self._chunk_digests(self._prompt_text(body))
        score = self._expected_hit_chunks(digests, [url]).get(url, 0)
        return score * (bytes_per_chunk or self.chunk_chars)


_ROUTERS = {
    "roundrobin": RoundRobinRouter,
    "least_loaded": LeastLoadedRouter,
    "session": SessionRouter,
    "prefix": PrefixAwareRouter,
}


def make_router(name: str, session_key: str = "x-user-id",
                prefix_chunk_chars: int = 256,
                prefix_ring_entries: int = 65536,
                prefix_cache_aware: bool = True) -> Router:
    if name not in _ROUTERS:
        raise ValueError(f"unknown routing logic {name!r}; "
                         f"options: {sorted(_ROUTERS)}")
    if name == "session":
        return SessionRouter(session_key=session_key)
    if name == "prefix":
        return PrefixAwareRouter(chunk_chars=prefix_chunk_chars,
                                 ring_entries=prefix_ring_entries,
                                 cache_aware=prefix_cache_aware)
    return _ROUTERS[name]()
