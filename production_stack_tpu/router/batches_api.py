"""OpenAI Batch API: JSONL batches executed against the routed engines.

Capability parity with reference src/vllm_router/services/batch_service/
(SQLite-backed queue, local_processor.py:24-210) and routers/
batches_router.py (POST/GET/list/cancel). The reference's processor is a
broken placeholder (imports a nonexistent package and sleeps instead of
running requests — SURVEY.md §2.1 batch row); this one actually executes:
each JSONL line is routed through the live routing policy to a backend,
responses are written to an output file in OpenAI batch-output format.

SQLite is used synchronously — batch bookkeeping writes are tiny and
rare relative to inference; the event loop impact is microseconds.
"""

import asyncio
import json
import sqlite3
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS batches (
    id TEXT PRIMARY KEY,
    status TEXT NOT NULL,
    input_file_id TEXT NOT NULL,
    endpoint TEXT NOT NULL,
    completion_window TEXT,
    created_at INTEGER,
    completed_at INTEGER,
    output_file_id TEXT,
    error_file_id TEXT,
    counts TEXT DEFAULT '{}'
)
"""


class BatchStore:
    def __init__(self, path: str):
        self.db = sqlite3.connect(path)
        self.db.row_factory = sqlite3.Row
        self.db.execute(_SCHEMA)
        # batches orphaned in_progress by a crash/restart are re-queued
        # (idempotent: line results are regenerated from the input file)
        self.db.execute(
            "UPDATE batches SET status='validating' "
            "WHERE status='in_progress'")
        self.db.commit()

    def create(self, input_file_id: str, endpoint: str,
               completion_window: str) -> dict:
        batch_id = f"batch-{uuid.uuid4().hex[:24]}"
        self.db.execute(
            "INSERT INTO batches (id, status, input_file_id, endpoint, "
            "completion_window, created_at) VALUES (?,?,?,?,?,?)",
            (batch_id, "validating", input_file_id, endpoint,
             completion_window, int(time.time())))
        self.db.commit()
        return self.get(batch_id)

    def get(self, batch_id: str) -> Optional[dict]:
        row = self.db.execute("SELECT * FROM batches WHERE id=?",
                              (batch_id,)).fetchone()
        return self._to_obj(row) if row else None

    def list(self) -> list:
        rows = self.db.execute(
            "SELECT * FROM batches ORDER BY created_at DESC").fetchall()
        return [self._to_obj(r) for r in rows]

    def update(self, batch_id: str, **fields) -> None:
        sets = ", ".join(f"{k}=?" for k in fields)
        self.db.execute(f"UPDATE batches SET {sets} WHERE id=?",
                        (*fields.values(), batch_id))
        self.db.commit()

    def next_pending(self) -> Optional[dict]:
        row = self.db.execute(
            "SELECT * FROM batches WHERE status='validating' "
            "ORDER BY created_at LIMIT 1").fetchone()
        return self._to_obj(row) if row else None

    @staticmethod
    def _to_obj(row: sqlite3.Row) -> dict:
        counts = json.loads(row["counts"] or "{}")
        return {
            "id": row["id"], "object": "batch", "status": row["status"],
            "input_file_id": row["input_file_id"],
            "endpoint": row["endpoint"],
            "completion_window": row["completion_window"],
            "created_at": row["created_at"],
            "completed_at": row["completed_at"],
            "output_file_id": row["output_file_id"],
            "error_file_id": row["error_file_id"],
            "request_counts": counts,
        }


class BatchProcessor:
    """Polls for pending batches and executes them line by line."""

    def __init__(self, state: dict, store: BatchStore,
                 poll_interval: float = 1.0):
        self.state = state
        self.store = store
        self.poll_interval = poll_interval
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop(), name="batch-proc")

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        while True:
            batch = self.store.next_pending()
            if batch is None:
                await asyncio.sleep(self.poll_interval)
                continue
            try:
                await self._run_batch(batch)
            except Exception:
                logger.exception("batch %s failed", batch["id"])
                self.store.update(batch["id"], status="failed")

    async def _run_batch(self, batch: dict) -> None:
        storage = self.state["file_storage"]
        content = await storage.get_content(batch["input_file_id"])
        if content is None:
            self.store.update(batch["id"], status="failed")
            return
        self.store.update(batch["id"], status="in_progress")
        results, errors = [], []
        completed = failed = 0
        for line in content.decode().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
                result = await self._run_one(batch, req)
                results.append(json.dumps(result))
                if result["response"]["status_code"] == 200:
                    completed += 1
                else:
                    failed += 1
            except Exception as e:
                failed += 1
                errors.append(json.dumps({
                    "custom_id": None, "error": str(e)}))
            # allow cancellation between lines
            current = self.store.get(batch["id"])
            if current and current["status"] == "cancelled":
                return
        out = await storage.save(f"{batch['id']}-output.jsonl",
                                 ("\n".join(results) + "\n").encode(),
                                 purpose="batch_output")
        err_id = None
        if errors:
            err = await storage.save(f"{batch['id']}-errors.jsonl",
                                     ("\n".join(errors) + "\n").encode(),
                                     purpose="batch_output")
            err_id = err.id
        self.store.update(
            batch["id"], status="completed", completed_at=int(time.time()),
            output_file_id=out.id, error_file_id=err_id,
            counts=json.dumps({"total": completed + failed,
                               "completed": completed, "failed": failed}))
        logger.info("batch %s done: %d ok, %d failed", batch["id"],
                    completed, failed)

    async def _run_one(self, batch: dict, req: dict) -> dict:
        """Route one batch line through the live routing policy."""
        body = req.get("body", {})
        model = body.get("model", "")
        endpoints = [ep for ep in self.state["discovery"].get_endpoints()
                     if ep.serves(model)]
        if not endpoints:
            return {"id": f"batch_req_{uuid.uuid4().hex[:16]}",
                    "custom_id": req.get("custom_id"),
                    "response": {"status_code": 400, "body": {
                        "error": f"no backend serves {model!r}"}}}
        health = self.state.get("health")
        if health is not None:
            endpoints = health.healthy_endpoints(endpoints)
        url = self.state["router"].route(
            endpoints, self.state["request_stats"].snapshot(), {}, body)
        path = req.get("url", batch["endpoint"])
        session: aiohttp.ClientSession = self.state["client"]
        from production_stack_tpu.router.service_discovery import (
            engine_auth_headers)
        async with session.post(f"{url}{path}", json=body,
                                headers=engine_auth_headers()) as resp:
            try:
                payload = await resp.json()
            except (aiohttp.ContentTypeError, json.JSONDecodeError):
                payload = {"error": await resp.text()}
            return {"id": f"batch_req_{uuid.uuid4().hex[:16]}",
                    "custom_id": req.get("custom_id"),
                    "response": {"status_code": resp.status,
                                 "body": payload}}


# ---------------------------------------------------------------- handlers

def mount_batches_api(app: web.Application, db_path: str) -> None:
    store = BatchStore(db_path)
    state = app["state"]
    state["batch_store"] = store
    processor = BatchProcessor(state, store)
    state["batch_processor"] = processor

    async def create(request: web.Request) -> web.Response:
        body = await request.json()
        for field in ("input_file_id", "endpoint"):
            if field not in body:
                return web.json_response(
                    {"error": {"message": f"missing {field!r}"}}, status=400)
        if await state["file_storage"].get(body["input_file_id"]) is None:
            return web.json_response(
                {"error": {"message": "input file not found"}}, status=404)
        batch = store.create(body["input_file_id"], body["endpoint"],
                             body.get("completion_window", "24h"))
        return web.json_response(batch)

    async def retrieve(request: web.Request) -> web.Response:
        batch = store.get(request.match_info["batch_id"])
        if batch is None:
            return web.json_response(
                {"error": {"message": "batch not found"}}, status=404)
        return web.json_response(batch)

    async def list_batches(request: web.Request) -> web.Response:
        return web.json_response({"object": "list", "data": store.list()})

    async def cancel(request: web.Request) -> web.Response:
        batch = store.get(request.match_info["batch_id"])
        if batch is None:
            return web.json_response(
                {"error": {"message": "batch not found"}}, status=404)
        if batch["status"] in ("validating", "in_progress"):
            store.update(batch["id"], status="cancelled")
            batch = store.get(batch["id"])
        return web.json_response(batch)

    app.router.add_post("/v1/batches", create)
    app.router.add_get("/v1/batches", list_batches)
    app.router.add_get("/v1/batches/{batch_id}", retrieve)
    app.router.add_post("/v1/batches/{batch_id}/cancel", cancel)
    app.router.add_delete("/v1/batches/{batch_id}", cancel)

    async def start_proc(app):
        await processor.start()

    async def stop_proc(app):
        await processor.close()

    app.on_startup.append(start_proc)
    app.on_cleanup.append(stop_proc)
