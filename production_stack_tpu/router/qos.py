"""QoS priority tiers: low-tier-first shedding on the r9 admission path.

The r9 overload machinery (docs/router.md "Overload protection") sheds
*uniformly*: past ``--max-inflight`` every request gets the same 429.
Under a saturating fleet that is the wrong shape — an interactive chat
request and a batch summarization job are not worth the same slot.
This module grades the existing gates by priority tier so the fleet
degrades *by tier* instead:

- **Tiers** come from ``--qos-tiers`` (``name=admit_fraction,...``,
  highest priority first, e.g. ``tier0=1.0,tier1=0.85,tier2=0.7``).
  A request names its tier in the ``x-priority-class`` header (tier
  name or index); untagged traffic lands in tier 0, so enabling QoS
  never penalizes clients that predate it.
- **Graduated admission.** Tier *k* is admitted only while the
  router's proxied in-flight count is under
  ``admit_fraction[k] × --max-inflight``: as pressure rises the
  background tiers hit their (lower) ceilings first and shed with the
  standard 429 + ``Retry-After`` — low-tier-first, and tier 0 keeps
  the full gate. Sheds are intentional backpressure: counted
  (``tpu:router_qos_sheds_total{tier}``), never breaker signals.
- **Per-tier token buckets** (``--qos-tier-rates name=req_per_s``)
  bound a tier's *rate* outright, pressure or not — the lever for a
  contractual background-tier budget.
- **Per-tenant token buckets inside each tier**
  (``--qos-tenant-rate req_per_s``, ``x-tenant-id`` header): every
  (tier, tenant) pair gets its own lazily created bucket, so a noisy
  tenant's burst sheds against ITS budget — its tier peers' buckets,
  and the tier's shared admission fraction, are untouched
  (noisy-neighbor containment, docs/multitenancy.md). Tenant sheds
  carry reason ``tenant`` and are counted per (tenant, tier) in
  ``tpu:router_tenant_sheds_total``; untagged requests (no tenant
  header) are never tenant-bucketed. The bucket table is a bounded
  LRU (``max_tenants``), so label cardinality and memory stay fixed
  no matter how many tenant ids clients invent.
- **Deadline budgets, low-tier-first.** The downstream deadline the
  router injects when the client sent none (``--request-timeout``)
  scales by the tier's admit fraction, so under queueing the engine
  expires background work first (the r9 ``expire_waiting`` sweep is
  the actual preemption point engine-side).
- **Preemption.** Tiers at or past ``--qos-preempt-from`` register as
  preemptable while their backend dispatch is in flight and no byte
  has reached the client. A tier-0 arrival that would otherwise shed
  at the full gate cancels the newest such victim (it gets a
  structured 503 ``preempted`` + ``Retry-After``) and takes the slot.
  Once a response byte has been relayed a request is never preempted
  — bytes cannot be un-sent.
- **Per-tier SLO classes.** Tiered requests feed the burn-rate engine
  (slo.py) under their tier name as the request class, so
  ``tier0_shed_rate`` (default objective set) pages when the one tier
  that must never shed starts shedding.

Closed loop: the saturation sweep in ``python -m
production_stack_tpu.loadgen multirouter`` holds tier-0 goodput flat
(≥95% of pre-saturation) while tier-2 sheds ≥50%
(``MULTIROUTER_r16.json``).
"""

import collections
import itertools
import time
from typing import Dict, List, Optional, Tuple

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

PRIORITY_HEADER = "x-priority-class"
TENANT_HEADER = "x-tenant-id"

# canonical three-tier spec (docs/router.md "QoS priority tiers")
DEFAULT_TIER_SPEC = "tier0=1.0,tier1=0.85,tier2=0.7"

SHED_REASONS = ("bucket", "pressure", "preempted", "tenant")


class _TokenBucket:
    """Continuous-refill token bucket: ``rate`` admissions/second
    sustained, ``burst`` instantaneous."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 now_fn=time.monotonic):
        self.rate = rate
        self.burst = burst if burst is not None else max(1.0, rate)
        self.tokens = self.burst
        self._now = now_fn
        self._last = now_fn()

    def try_take(self) -> bool:
        now = self._now()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class QosTier:
    __slots__ = ("name", "index", "admit_fraction", "bucket")

    def __init__(self, name: str, index: int, admit_fraction: float,
                 bucket: Optional[_TokenBucket] = None):
        self.name = name
        self.index = index
        self.admit_fraction = admit_fraction
        self.bucket = bucket


class _PreemptSlot:
    """One preemptable in-flight request. The proxy races its backend
    dispatch against ``event``; a preemptor sets it."""

    __slots__ = ("tier", "event", "key")

    def __init__(self, tier: QosTier, event, key: int):
        self.tier = tier
        self.event = event
        self.key = key


def parse_tier_spec(spec: str) -> List[Tuple[str, float]]:
    """``"tier0=1.0,tier1=0.85,tier2=0.7"`` -> ordered (name, frac)
    pairs. Order is priority order (first = highest); fractions must
    be non-increasing in (0, 1] — a background tier admitted deeper
    into the gate than an interactive one is a config error, not a
    policy."""
    pairs: List[Tuple[str, float]] = []
    seen = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"--qos-tiers entry {part!r} is not "
                             f"name=admit_fraction")
        name, _, frac_s = part.partition("=")
        name = name.strip()
        frac = float(frac_s)
        if not name or name in seen:
            raise ValueError(f"--qos-tiers: duplicate/empty tier name "
                             f"{name!r}")
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"--qos-tiers: {name} admit fraction "
                             f"{frac} outside (0, 1]")
        if pairs and frac > pairs[-1][1]:
            raise ValueError(f"--qos-tiers: {name} admits at {frac} > "
                             f"the higher-priority {pairs[-1][0]}'s "
                             f"{pairs[-1][1]} (fractions must be "
                             f"non-increasing)")
        seen.add(name)
        pairs.append((name, frac))
    if not pairs:
        raise ValueError("--qos-tiers parsed to zero tiers")
    return pairs


class QosPolicy:
    """Tier resolution + graduated admission + preemption registry for
    one router process. Event-loop-single-threaded like the rest of
    the router: no locks."""

    def __init__(self, spec: str = DEFAULT_TIER_SPEC,
                 tier_rates: str = "",
                 preempt_from: Optional[int] = None,
                 tenant_rate: float = 0.0,
                 max_tenants: int = 256,
                 now_fn=time.monotonic):
        rates: Dict[str, float] = {}
        for part in (tier_rates or "").split(","):
            part = part.strip()
            if not part:
                continue
            name, _, rate_s = part.partition("=")
            rates[name.strip()] = float(rate_s)
        self.tiers: List[QosTier] = []
        self._by_name: Dict[str, QosTier] = {}
        for idx, (name, frac) in enumerate(parse_tier_spec(spec)):
            rate = rates.pop(name, 0.0)
            bucket = _TokenBucket(rate, now_fn=now_fn) if rate > 0 \
                else None
            tier = QosTier(name, idx, frac, bucket)
            self.tiers.append(tier)
            self._by_name[name.lower()] = tier
        if rates:
            raise ValueError(f"--qos-tier-rates names unknown tiers: "
                             f"{sorted(rates)}")
        # preemptable tiers: index >= preempt_from (default: only the
        # lowest tier; len(tiers) disables preemption entirely)
        self.preempt_from = len(self.tiers) - 1 if preempt_from is None \
            else preempt_from
        # newest-last per tier so a preemptor cancels the request with
        # the least progress to lose
        self._preemptable: List["collections.OrderedDict[int, _PreemptSlot]"] = [
            collections.OrderedDict() for _ in self.tiers]
        self._slot_ids = itertools.count()
        # telemetry (delta-synced into tpu:router_qos_* at scrape)
        self.admitted = [0] * len(self.tiers)
        self.completed = [0] * len(self.tiers)
        self.inflight = [0] * len(self.tiers)
        self.sheds: Dict[Tuple[str, str], int] = collections.defaultdict(int)
        self.preemptions = [0] * len(self.tiers)   # as victim
        # per-tenant buckets nested inside tiers: (tier, tenant) ->
        # bucket, bounded LRU. tenant_sheds keys (tenant, tier) —
        # metrics label order — and is evicted WITH the bucket so the
        # exported label set stays bounded by max_tenants too.
        self.tenant_rate = float(tenant_rate)
        self.max_tenants = max_tenants
        self._now_fn = now_fn
        self._tenant_buckets: \
            "collections.OrderedDict[Tuple[str, str], _TokenBucket]" = \
            collections.OrderedDict()
        self.tenant_sheds: Dict[Tuple[str, str], int] = \
            collections.defaultdict(int)

    # -- tier resolution ------------------------------------------------

    def resolve(self, headers) -> QosTier:
        """``x-priority-class`` by tier name or index; absent/unknown
        lands in tier 0 (the top tier) so untagged traffic — every
        client that predates QoS — is never penalized."""
        raw = headers.get(PRIORITY_HEADER) if headers is not None else None
        if not raw:
            return self.tiers[0]
        key = raw.strip().lower()
        tier = self._by_name.get(key)
        if tier is not None:
            return tier
        try:
            idx = int(key)
        except ValueError:
            return self.tiers[0]
        if 0 <= idx < len(self.tiers):
            return self.tiers[idx]
        return self.tiers[0]

    def resolve_tenant(self, headers) -> Optional[str]:
        """``x-tenant-id`` value, or None when absent or tenant
        bucketing is off — None short-circuits every tenant check, so
        untagged traffic (every client that predates tenancy) pays
        nothing."""
        if self.tenant_rate <= 0 or headers is None:
            return None
        raw = headers.get(TENANT_HEADER)
        return raw.strip() if raw else None

    # -- admission ------------------------------------------------------

    def _tenant_allows(self, tier: QosTier,
                       tenant: Optional[str]) -> bool:
        """One draw on the (tier, tenant) bucket — lazily created at
        the flat per-tenant rate, LRU-bounded. An evicted tenant's
        next request simply re-creates a full bucket: the LRU bound is
        a memory cap, not a policy (an attacker cycling tenant ids is
        the admission fraction's problem, not this table's)."""
        if tenant is None:
            return True
        key = (tier.name, tenant)
        bucket = self._tenant_buckets.get(key)
        if bucket is None:
            bucket = _TokenBucket(self.tenant_rate, now_fn=self._now_fn)
            self._tenant_buckets[key] = bucket
            while len(self._tenant_buckets) > self.max_tenants:
                old_key, _ = self._tenant_buckets.popitem(last=False)
                self.tenant_sheds.pop((old_key[1], old_key[0]), None)
        else:
            self._tenant_buckets.move_to_end(key)
        return bucket.try_take()

    def _shed_tenant(self, tier: QosTier, tenant: str) -> None:
        self.sheds[(tier.name, "tenant")] += 1
        self.tenant_sheds[(tenant, tier.name)] += 1

    def admit(self, tier: QosTier, inflight: int, max_inflight: int,
              tenant: Optional[str] = None
              ) -> Tuple[str, Optional[_PreemptSlot]]:
        """One admission decision. Returns ``(verdict, victim)``:
        ``("admit", None)`` / ``("admit", slot)`` (slot preempted to
        make room — caller delivers the victim its 503) /
        ``("shed", None)`` (reason already counted).

        The pressure gate runs BEFORE the token bucket: a request that
        is going to be pressure-shed anyway must not drain the tier's
        contractual rate budget, or sustained pressure double-charges
        the bucket and starves the tier after the pressure clears.
        The TENANT bucket is drawn before the tier bucket for the same
        reason in the other direction: a tenant-shed request must not
        drain the tier's shared budget (one tenant's burst would spend
        its peers' rate), and tenant-refused requests never preempt."""
        if max_inflight and inflight >= max_inflight * tier.admit_fraction:
            victim = None
            if tier.index < self.preempt_from:
                victim = self._pick_victim(tier)
            if victim is None:
                self.sheds[(tier.name, "pressure")] += 1
                return "shed", None
            if not self._tenant_allows(tier, tenant):
                # over the TENANT's rate even with a victim available:
                # put the victim back and shed — never burn a
                # background dispatch for a request this tenant's own
                # budget refuses anyway
                self._preemptable[victim.tier.index][victim.key] = victim
                self._shed_tenant(tier, tenant)
                return "shed", None
            if tier.bucket is not None and not tier.bucket.try_take():
                # over its rate even with a victim available: shed
                # WITHOUT preempting (never burn a background dispatch
                # for a request the bucket refuses anyway)
                self._preemptable[victim.tier.index][victim.key] = victim
                self.sheds[(tier.name, "bucket")] += 1
                return "shed", None
            victim.event.set()
            self.preemptions[victim.tier.index] += 1
            self.sheds[(victim.tier.name, "preempted")] += 1
            self.admitted[tier.index] += 1
            return "admit", victim
        if not self._tenant_allows(tier, tenant):
            self._shed_tenant(tier, tenant)
            return "shed", None
        if tier.bucket is not None and not tier.bucket.try_take():
            self.sheds[(tier.name, "bucket")] += 1
            return "shed", None
        self.admitted[tier.index] += 1
        return "admit", None

    def on_start(self, tier: QosTier) -> None:
        self.inflight[tier.index] += 1

    def on_complete(self, tier: QosTier) -> None:
        self.inflight[tier.index] = max(0, self.inflight[tier.index] - 1)
        self.completed[tier.index] += 1

    # -- preemption registry --------------------------------------------

    def _pick_victim(self, preemptor: QosTier) -> Optional[_PreemptSlot]:
        """Newest request in the worst occupied preemptable tier that
        is strictly lower-priority than the preemptor."""
        for idx in range(len(self.tiers) - 1, self.preempt_from - 1, -1):
            if idx <= preemptor.index:
                break
            slots = self._preemptable[idx]
            if slots:
                _, slot = slots.popitem(last=True)
                return slot
        return None

    def register_preemptable(self, tier: QosTier,
                             event) -> Optional[_PreemptSlot]:
        """Called by the proxy when a preemptable-tier request starts
        its backend dispatch; returns None for tiers that never
        preempt-register (the hot path for tier 0)."""
        if tier.index < self.preempt_from:
            return None
        slot = _PreemptSlot(tier, event, next(self._slot_ids))
        self._preemptable[tier.index][slot.key] = slot
        return slot

    def unregister_preemptable(self, slot: Optional[_PreemptSlot]) -> None:
        if slot is not None:
            self._preemptable[slot.tier.index].pop(slot.key, None)

    # -- deadlines ------------------------------------------------------

    def deadline_factor(self, tier: QosTier) -> float:
        """Scale for the router-injected downstream deadline: tier 0
        keeps the full ``--request-timeout`` budget; background tiers
        get proportionally less, so the engine's queue-expiry sweep
        drops THEIR queued work first when delay builds."""
        return tier.admit_fraction

    # -- introspection --------------------------------------------------

    def snapshot(self) -> Dict:
        tiers = []
        for t in self.tiers:
            shed = {r: self.sheds.get((t.name, r), 0)
                    for r in SHED_REASONS}
            tiers.append({
                "tier": t.name, "index": t.index,
                "admit_fraction": t.admit_fraction,
                "rate_limited": t.bucket is not None,
                "admitted": self.admitted[t.index],
                "completed": self.completed[t.index],
                "in_flight": self.inflight[t.index],
                "sheds": shed,
                "shed_total": sum(shed.values()),
                "preempted": self.preemptions[t.index],
            })
        out = {"preempt_from": self.preempt_from, "tiers": tiers}
        if self.tenant_rate > 0:
            out["tenant_rate"] = self.tenant_rate
            out["tenants_tracked"] = len(self._tenant_buckets)
            out["tenant_sheds"] = {
                f"{tenant}/{tier}": n
                for (tenant, tier), n in sorted(self.tenant_sheds.items())}
        return out

    def shed_totals(self) -> Dict[str, int]:
        out: Dict[str, int] = {t.name: 0 for t in self.tiers}
        for (name, _reason), n in self.sheds.items():
            out[name] += n
        return out
