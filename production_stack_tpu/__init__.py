"""TPU Production Stack: a TPU-native LLM serving stack.

A from-scratch rebuild of the capabilities of vLLM Production Stack
(reference: bytedance-iaas/production-stack) for GKE TPU pods:

- ``engine/``   — a JAX/XLA-native serving engine (continuous batching,
  static-shape KV cache, OpenAI-compatible HTTP server). The reference
  delegates this layer to the external ``vllm/vllm-openai`` container
  (reference: helm/templates/deployment-vllm-multi.yaml:57-64); here it is
  a first-class, TPU-first component.
- ``models/``   — Llama-family decoder models as pure-JAX functions.
- ``ops/``      — TPU compute ops (RMSNorm, RoPE, attention; Pallas kernels).
- ``parallel/`` — device-mesh parallelism (dp/tp/sp) via jax.sharding.
- ``router/``   — the L7 OpenAI-compatible request router (reference:
  src/vllm_router/), with service discovery, session-affinity routing,
  stats, dynamic config, files/batches APIs.
- ``utils/``    — logging, singletons, misc helpers.
"""

from production_stack_tpu.version import __version__

__all__ = ["__version__"]
