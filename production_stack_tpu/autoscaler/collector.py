"""Signal collection: per-engine /load + router health, one FleetSignal.

The collector owns (or borrows) a ``signals.LoadPoller`` and reduces
its per-engine reports into the single ``FleetSignal`` the policy
consumes each tick:

- ``queue_delay_ms``  — max of the engines' service-EWMA estimates
  (the *worst* replica is what a newly routed request may hit);
- ``in_flight`` / ``capacity`` — fleet sums; utilization is their
  ratio when at least one engine advertises bounded admission;
- ``ready``           — replicas with a *fresh* report (launched-but-
  still-compiling replicas have none, which is exactly the policy's
  settling gate);
- ``router_healthy``  — the router's own healthy-endpoint count from
  ``/health``, a cross-check that config swaps actually landed.

Pass ``poller=`` to share an existing poller (e.g. the router's
``EngineStatsScraper`` when the autoscaler runs in the router process)
so each engine is scraped once per interval no matter how many
consumers read it.

``FleetSignalCollector`` (r20 fleet pilot) consumes the obsplane's
``GET /fleet`` instead: the same per-engine numbers (scraped once for
the whole fleet by the aggregator), PLUS the burn-rate alerts and
live per-stage phase percentiles the raw loop never sees. When the
obsplane is unreachable or stale it degrades to exactly the raw
``/load`` pass above — the pilot is never *less* robust than the dumb
loop it replaces — and every signal carries its ``source`` so the
decision log shows which path produced each decision.
"""

import asyncio
import time
from typing import Callable, Dict, Iterable, Optional

import aiohttp

from production_stack_tpu.autoscaler.policy import FleetSignal
from production_stack_tpu.signals import (EngineLoad, LoadPoller,
                                          coerce_load,
                                          parse_load_report)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class SignalCollector:
    def __init__(self, get_urls: Callable[[], Iterable[str]], *,
                 router_url=None,
                 poller: Optional[LoadPoller] = None,
                 poll_interval_s: float = 5.0,
                 freshness_s: float = 10.0):
        self._get_urls = get_urls
        # one router URL, a comma-separated string, or a list: with N
        # router replicas behind an L4 split the cross-check asks every
        # one and takes the max healthy-endpoint count — any single
        # replica being mid-restart must not read as "config never
        # landed" while its peers see the full fleet
        if isinstance(router_url, str):
            router_url = [u.strip() for u in router_url.split(",")
                          if u.strip()]
        self.router_urls = list(router_url or [])
        self.router_url = self.router_urls[0] if self.router_urls \
            else None          # kept for existing callers/logs
        self._owns_poller = poller is None
        self.poller = poller if poller is not None else \
            LoadPoller(get_urls, interval_s=poll_interval_s)
        self.freshness_s = freshness_s
        self._session: Optional[aiohttp.ClientSession] = None

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        if self._owns_poller:
            # on-demand, not interval: collect() polls at every control
            # tick, so a background loop would just double each
            # engine's scrape rate
            self.poller.attach(self._session)

    async def close(self) -> None:
        if self._owns_poller:
            await self.poller.close()
        if self._session:
            await self._session.close()
            self._session = None

    # -- reads ----------------------------------------------------------

    def per_engine(self) -> Dict[str, EngineLoad]:
        return {url: coerce_load(rec)
                for url, rec in self.poller.get().items()}

    async def collect(self,
                      replicas: Optional[int] = None) -> FleetSignal:
        """One fresh pass: poll every engine now, aggregate.

        ``replicas`` overrides the fleet size when the actuator — not
        the polled URL set — is authoritative (KubernetesActuator,
        whose pods are not in ``get_urls``)."""
        if self._owns_poller:
            await self.poller.poll_now()
        loads = self.per_engine()
        urls = [u.rstrip("/") for u in self._get_urls()]
        now = time.time()
        fresh = {u: l for u, l in loads.items()
                 if u in urls and now - l.scraped_at <= self.freshness_s}
        in_flight = sum(l.in_flight for l in fresh.values())
        bounded = {u: l for u, l in fresh.items()
                   if l.capacity is not None and l.capacity > 0}
        advertised = [l.capacity for l in bounded.values()]
        n = len(urls) if replicas is None else replicas
        # "ready" counts only OBSERVABLE replicas against freshness:
        # replicas outside the polled URL set (a KubernetesActuator's
        # pods, which get_urls cannot enumerate) are presumed ready,
        # otherwise the policy's settling gate would hold forever the
        # moment the actuator's count diverges from the static list
        ready = max(0, min(n, n - (len(urls) - len(fresh))))
        return FleetSignal(
            replicas=n,
            ready=ready,
            in_flight=in_flight,
            capacity=sum(advertised) if advertised else None,
            bounded_in_flight=(sum(l.in_flight
                                   for l in bounded.values())
                               if advertised else None),
            queue_delay_ms=max(
                (l.est_queue_delay_ms for l in fresh.values()),
                default=0.0),
            router_healthy=await self._router_healthy(),
        )

    async def _router_healthy(self) -> Optional[int]:
        if not self.router_urls or self._session is None:
            return None
        counts = await asyncio.gather(
            *(self._one_router_healthy(u) for u in self.router_urls))
        live = [c for c in counts if c is not None]
        return max(live) if live else None

    async def _one_router_healthy(self, url: str) -> Optional[int]:
        try:
            async with self._session.get(
                    f"{url}/health",
                    timeout=aiohttp.ClientTimeout(total=3)) as r:
                body = await r.json()
                return body.get("healthy_endpoints")
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return None


class FleetSignalCollector(SignalCollector):
    """The fleet pilot's collector: ``GET /fleet`` first, raw ``/load``
    as the degradation path (module docstring).

    Freshness is judged per engine from the snapshot's own sample ages
    (``autoscaler_signal[url].age_s``): an obsplane that answers HTTP
    but whose poll loop died serves stale rows, and stale rows for
    every managed engine mean the payload is unusable — fall back,
    same as unreachable."""

    def __init__(self, get_urls: Callable[[], Iterable[str]], *,
                 obsplane_url: str,
                 router_url=None,
                 poller: Optional[LoadPoller] = None,
                 poll_interval_s: float = 5.0,
                 freshness_s: float = 10.0,
                 fleet_timeout_s: float = 3.0):
        super().__init__(get_urls, router_url=router_url,
                         poller=poller,
                         poll_interval_s=poll_interval_s,
                         freshness_s=freshness_s)
        self.obsplane_url = obsplane_url.rstrip("/")
        self._fleet_timeout = aiohttp.ClientTimeout(
            total=fleet_timeout_s)
        self.last_source: Optional[str] = None
        self.fleet_polls = 0
        self.fleet_failures = 0
        # last USABLE fleet rows, for per_engine() victim picking
        self._fleet_rows: Dict[str, dict] = {}

    async def _fetch_fleet(self) -> Optional[dict]:
        if self._session is None:
            return None
        try:
            async with self._session.get(
                    f"{self.obsplane_url}/fleet",
                    timeout=self._fleet_timeout) as r:
                if r.status != 200:
                    return None
                return await r.json()
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError, ValueError):
            return None

    def _note_source(self, source: str) -> None:
        if source != self.last_source:
            if source == "load":
                logger.warning(
                    "fleet pilot degrading to raw /load polling "
                    "(obsplane %s unreachable or stale)",
                    self.obsplane_url)
            else:
                logger.info("fleet pilot consuming %s/fleet",
                            self.obsplane_url)
        self.last_source = source

    async def collect(self,
                      replicas: Optional[int] = None) -> FleetSignal:
        self.fleet_polls += 1
        fleet = await self._fetch_fleet()
        urls = [u.rstrip("/") for u in self._get_urls()]
        fresh: Dict[str, dict] = {}
        if fleet is not None:
            block = fleet.get("autoscaler_signal") or {}
            fresh = {
                u: row for u, row in block.items()
                if u in urls and row.get("state") == "live"
                and row.get("age_s") is not None
                and row["age_s"] <= self.freshness_s
                and "in_flight" in row}
        if fleet is None or (urls and not fresh):
            # unreachable, or every managed engine's row is stale or
            # missing: the raw pass is strictly better information
            self.fleet_failures += 1
            self._fleet_rows = {}
            sig = await super().collect(replicas=replicas)
            self._note_source("load")
            return sig
        self._note_source("fleet")
        self._fleet_rows = fresh
        n = len(urls) if replicas is None else replicas
        ready = max(0, min(n, n - (len(urls) - len(fresh))))
        bounded = {u: row for u, row in fresh.items()
                   if row.get("capacity")}
        advertised = [row["capacity"] for row in bounded.values()]
        percentiles = fleet.get("fleet_percentiles") or {}
        phase_p95: Dict[str, float] = {}
        for phases in percentiles.values():
            for phase, row in phases.items():
                p95 = row.get("p95_ms")
                if p95 is not None:
                    phase_p95[phase] = max(phase_p95.get(phase, 0.0),
                                           p95)
        return FleetSignal(
            replicas=n,
            ready=ready,
            in_flight=sum(row["in_flight"] for row in fresh.values()),
            capacity=sum(advertised) if advertised else None,
            bounded_in_flight=(sum(row["in_flight"]
                                   for row in bounded.values())
                               if advertised else None),
            queue_delay_ms=max(
                (row.get("est_queue_delay_ms") or 0.0
                 for row in fresh.values()), default=0.0),
            router_healthy=await self._router_healthy(),
            source="fleet",
            alerts_firing=tuple(fleet.get("firing_alerts") or ()),
            phase_p95_ms=phase_p95 or None,
        )

    def per_engine(self) -> Dict[str, EngineLoad]:
        """Victim picking rides the same source as the decision: the
        fleet rows when the last collect used them, the raw poller
        otherwise."""
        if self._fleet_rows:
            return {
                url: parse_load_report({
                    "running": row.get("in_flight"),
                    "capacity": row.get("capacity"),
                    "est_queue_delay_ms":
                        row.get("est_queue_delay_ms"),
                })
                for url, row in self._fleet_rows.items()}
        return super().per_engine()
