"""Scaling policy: load signals in, bounded replica decisions out.

Pure and clock-injected — every branch is unit-testable without a
stack. The controller feeds one ``FleetSignal`` per tick; the policy
answers with a ``Decision`` that is already clamped, stepped, cooled
down, and hysteresis-filtered, so actuators never need judgement of
their own.

Anti-thrash machinery (the part an HPA gives you for free and an
in-process controller must own):

- **Hysteresis band** — scale-up and scale-down trigger on *different*
  thresholds (``target_queue_delay_ms`` / ``down_queue_delay_ms``,
  ``target_utilization`` / ``down_utilization``). Load sitting between
  the bands holds.
- **Consecutive-breach ticks** — one spiky sample never scales; the
  breach must persist ``up_breach_ticks`` / ``down_breach_ticks``
  consecutive ticks. A single in-band tick resets the streak.
- **Cooldowns** — after a scale event the same direction is locked out
  for ``up_cooldown_s`` / ``down_cooldown_s``; scale-down additionally
  cools down after a scale-UP (a spike that just forced capacity up
  must not reclaim it seconds later).
- **Step limits** — one decision moves at most ``up_step`` /
  ``down_step`` replicas; the loop re-evaluates on real signals
  instead of extrapolating to a far-away target.
- **Settling gate** — while launched replicas are not yet reporting
  load (XLA warmup), scale-down holds: retiring capacity based on a
  fleet that is not fully in service yet double-counts headroom.
"""

import time
from dataclasses import asdict, dataclass, field
from typing import Optional

UP = "up"
DOWN = "down"
HOLD = "hold"


@dataclass
class PolicyConfig:
    """Knobs; defaults suit a small interactive fleet (docs/autoscaling.md)."""

    min_replicas: int = 1
    max_replicas: int = 4
    # queue-delay band (ms): the engine's own service-EWMA estimate of
    # how long a new arrival waits before prefill (tpu:est_queue_delay_ms)
    target_queue_delay_ms: float = 500.0
    down_queue_delay_ms: float = 100.0
    # utilization band: fleet in-flight / fleet advertised capacity
    target_utilization: float = 0.90
    down_utilization: float = 0.50
    up_step: int = 1
    down_step: int = 1
    up_cooldown_s: float = 15.0
    down_cooldown_s: float = 60.0
    up_breach_ticks: int = 2
    down_breach_ticks: int = 3
    # backstop on the settling gate: after this many CONSECUTIVE ticks
    # with ready < replicas, decisions resume on the signals of the
    # replicas that ARE reporting. Without it, one crashed replica
    # (stale /load forever) would wedge both directions permanently.
    # Size it to comfortably exceed engine warmup at your tick
    # interval (default 120 ticks = 4 min at the 2 s default).
    settling_grace_ticks: int = 120

    def validate(self) -> "PolicyConfig":
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.up_step < 1 or self.down_step < 1:
            raise ValueError("scale steps must be >= 1")
        if self.down_queue_delay_ms > self.target_queue_delay_ms:
            raise ValueError("down_queue_delay_ms must not exceed "
                             "target_queue_delay_ms (hysteresis band)")
        if self.down_utilization > self.target_utilization:
            raise ValueError("down_utilization must not exceed "
                             "target_utilization (hysteresis band)")
        if self.up_breach_ticks < 1 or self.down_breach_ticks < 1:
            raise ValueError("breach tick counts must be >= 1")
        if self.settling_grace_ticks < 1:
            raise ValueError("settling_grace_ticks must be >= 1")
        return self


@dataclass
class FleetSignal:
    """One tick's aggregated input (autoscaler/collector.py)."""

    replicas: int                       # endpoints the actuator owns
    ready: int                          # of those, reporting fresh /load
    in_flight: float = 0.0              # sum queued+running across fleet
    capacity: Optional[float] = None    # sum advertised; None = unknown
    # in-flight on the capacity-advertising engines only: utilization's
    # numerator must cover the same engines as its denominator, or a
    # mixed fleet (one bounded, one unbounded engine) reads as
    # over-utilized forever. None = same as in_flight (uniform fleet).
    bounded_in_flight: Optional[float] = None
    queue_delay_ms: float = 0.0         # max est_queue_delay_ms
    router_healthy: Optional[int] = None  # router's own healthy count

    @property
    def utilization(self) -> Optional[float]:
        if self.capacity is None or self.capacity <= 0:
            return None
        numerator = (self.in_flight if self.bounded_in_flight is None
                     else self.bounded_in_flight)
        return numerator / self.capacity


@dataclass
class Decision:
    direction: str                      # "up" | "down" | "hold"
    current: int
    target: int
    reason: str
    signal: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return asdict(self)


class AutoscalerPolicy:
    """Stateful (breach streaks + cooldown stamps), side-effect free.

    ``decide`` never mutates the fleet; the controller applies the
    decision and confirms it back via ``note_scaled`` so a failed
    actuation does not start a cooldown.
    """

    def __init__(self, cfg: PolicyConfig):
        self.cfg = cfg.validate()
        self._up_streak = 0
        self._down_streak = 0
        self._settling_streak = 0
        self._last_up_at = float("-inf")
        self._last_down_at = float("-inf")

    # -- controller feedback -------------------------------------------

    def note_scaled(self, direction: str,
                    now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if direction == UP:
            self._last_up_at = now
        elif direction == DOWN:
            self._last_down_at = now
        self._up_streak = 0
        self._down_streak = 0

    # -- the decision ---------------------------------------------------

    def decide(self, sig: FleetSignal,
               now: Optional[float] = None) -> Decision:
        now = time.monotonic() if now is None else now
        cfg = self.cfg
        util = sig.utilization

        def hold(reason):
            return self._decision(HOLD, sig, sig.replicas, reason, util)

        breach_up = (sig.queue_delay_ms > cfg.target_queue_delay_ms or
                     (util is not None and util > cfg.target_utilization))
        breach_down = (sig.queue_delay_ms < cfg.down_queue_delay_ms and
                       (util is None or util < cfg.down_utilization))
        self._up_streak = self._up_streak + 1 if breach_up else 0
        self._down_streak = self._down_streak + 1 if breach_down else 0
        # the settling gate, with a grace bound: a replica that stays
        # unready past the grace window (crashed, not warming) must not
        # wedge the controller — decisions resume on what IS reporting
        self._settling_streak = (self._settling_streak + 1
                                 if sig.ready < sig.replicas else 0)
        settling = (sig.ready < sig.replicas and
                    self._settling_streak <= cfg.settling_grace_ticks)

        if breach_up:
            if sig.replicas >= cfg.max_replicas:
                return hold("at_max")
            if settling:
                # capacity already on the way up is still warming; its
                # effect is not in the signal yet
                return hold("settling")
            if self._up_streak < cfg.up_breach_ticks:
                return hold("breach_pending_up")
            if now - self._last_up_at < cfg.up_cooldown_s:
                return hold("cooldown_up")
            target = min(sig.replicas + cfg.up_step, cfg.max_replicas)
            reason = ("queue_delay"
                      if sig.queue_delay_ms > cfg.target_queue_delay_ms
                      else "utilization")
            return self._decision(UP, sig, target, reason, util)

        if breach_down:
            if sig.replicas <= cfg.min_replicas:
                return hold("at_min")
            if settling:
                return hold("settling")
            if self._down_streak < cfg.down_breach_ticks:
                return hold("breach_pending_down")
            # scale-down cools down after ANY scale event: reclaiming
            # capacity seconds after a spike forced it up is the thrash
            # this controller exists to prevent
            if now - max(self._last_up_at,
                         self._last_down_at) < cfg.down_cooldown_s:
                return hold("cooldown_down")
            target = max(sig.replicas - cfg.down_step, cfg.min_replicas)
            return self._decision(DOWN, sig, target, "idle", util)

        return hold("in_band")

    def _decision(self, direction: str, sig: FleetSignal, target: int,
                  reason: str, util: Optional[float]) -> Decision:
        return Decision(
            direction=direction, current=sig.replicas, target=target,
            reason=reason,
            signal={
                "replicas": sig.replicas,
                "ready": sig.ready,
                "in_flight": round(sig.in_flight, 2),
                "capacity": sig.capacity,
                "utilization": None if util is None else round(util, 4),
                "queue_delay_ms": round(sig.queue_delay_ms, 1),
                "router_healthy": sig.router_healthy,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
            })
