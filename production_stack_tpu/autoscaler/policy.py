"""Scaling policy: load signals in, bounded replica decisions out.

Pure and clock-injected — every branch is unit-testable without a
stack. The controller feeds one ``FleetSignal`` per tick; the policy
answers with a ``Decision`` that is already clamped, stepped, cooled
down, and hysteresis-filtered, so actuators never need judgement of
their own.

Anti-thrash machinery (the part an HPA gives you for free and an
in-process controller must own):

- **Hysteresis band** — scale-up and scale-down trigger on *different*
  thresholds (``target_queue_delay_ms`` / ``down_queue_delay_ms``,
  ``target_utilization`` / ``down_utilization``). Load sitting between
  the bands holds.
- **Consecutive-breach ticks** — one spiky sample never scales; the
  breach must persist ``up_breach_ticks`` / ``down_breach_ticks``
  consecutive ticks. A single in-band tick resets the streak.
- **Cooldowns** — after a scale event the same direction is locked out
  for ``up_cooldown_s`` / ``down_cooldown_s``; scale-down additionally
  cools down after a scale-UP (a spike that just forced capacity up
  must not reclaim it seconds later).
- **Step limits** — one decision moves at most ``up_step`` /
  ``down_step`` replicas; the loop re-evaluates on real signals
  instead of extrapolating to a far-away target.
- **Settling gate** — while launched replicas are not yet reporting
  load (XLA warmup), scale-down holds: retiring capacity based on a
  fleet that is not fully in service yet double-counts headroom.

Fleet-pilot inputs (r20, ROADMAP item 4) — each one is opt-in and
rides the same clamps/cooldowns as the raw signals:

- **Burn-rate input** (``burn_rate_input``) — a page-severity SLO
  alert firing on the fleet IS the breach: scale up immediately
  (reason ``burn_rate``) without waiting for queue delay to cross its
  target or for breach ticks — the alert's own multi-window
  persistence already debounced it. While a page fires, scale-down is
  off the table.
- **Phase-percentile input** (``phase_p95_targets``) — live per-stage
  p95s from the obsplane's stitched chains (e.g. ``engine.prefill``)
  breach like queue delay does (reason ``phase_p95``), so a pool can
  be right-sized on the stage it is actually slow in.
- **Scheduled floors** (``scheduled_floors``) — wall-clock replica
  floors for diurnal ramps (reason ``scheduled``): capacity is up
  BEFORE the morning traffic, not two breach ticks after it.
"""

import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional, Tuple

UP = "up"
DOWN = "down"
HOLD = "hold"


@dataclass
class PolicyConfig:
    """Knobs; defaults suit a small interactive fleet (docs/autoscaling.md)."""

    min_replicas: int = 1
    max_replicas: int = 4
    # queue-delay band (ms): the engine's own service-EWMA estimate of
    # how long a new arrival waits before prefill (tpu:est_queue_delay_ms)
    target_queue_delay_ms: float = 500.0
    down_queue_delay_ms: float = 100.0
    # utilization band: fleet in-flight / fleet advertised capacity
    target_utilization: float = 0.90
    down_utilization: float = 0.50
    up_step: int = 1
    down_step: int = 1
    up_cooldown_s: float = 15.0
    down_cooldown_s: float = 60.0
    up_breach_ticks: int = 2
    down_breach_ticks: int = 3
    # backstop on the settling gate: after this many CONSECUTIVE ticks
    # with ready < replicas, decisions resume on the signals of the
    # replicas that ARE reporting. Without it, one crashed replica
    # (stale /load forever) would wedge both directions permanently.
    # Size it to comfortably exceed engine warmup at your tick
    # interval (default 120 ticks = 4 min at the 2 s default).
    settling_grace_ticks: int = 120
    # fleet-pilot inputs (module docstring); all default off so the
    # raw-signal loop is byte-identical without them
    burn_rate_input: bool = False
    # qualified stitched phase -> p95 bound in ms, e.g.
    # {"engine.prefill": 250.0} (parse_phase_targets)
    phase_p95_targets: Optional[Dict[str, float]] = None
    # ((start_minute, end_minute, floor), ...) minutes-of-day local
    # time; end < start wraps midnight (parse_schedule)
    scheduled_floors: Tuple[Tuple[int, int, int], ...] = ()

    def validate(self) -> "PolicyConfig":
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.up_step < 1 or self.down_step < 1:
            raise ValueError("scale steps must be >= 1")
        if self.down_queue_delay_ms > self.target_queue_delay_ms:
            raise ValueError("down_queue_delay_ms must not exceed "
                             "target_queue_delay_ms (hysteresis band)")
        if self.down_utilization > self.target_utilization:
            raise ValueError("down_utilization must not exceed "
                             "target_utilization (hysteresis band)")
        if self.up_breach_ticks < 1 or self.down_breach_ticks < 1:
            raise ValueError("breach tick counts must be >= 1")
        if self.settling_grace_ticks < 1:
            raise ValueError("settling_grace_ticks must be >= 1")
        for phase, bound in (self.phase_p95_targets or {}).items():
            if bound <= 0:
                raise ValueError(f"phase_p95_targets[{phase!r}] must "
                                 f"be a positive ms bound")
        for start, end, floor in self.scheduled_floors:
            if not (0 <= start < 1440 and 0 <= end < 1440):
                raise ValueError("scheduled floor windows must use "
                                 "minutes-of-day in [0, 1440)")
            if floor < 1 or floor > self.max_replicas:
                raise ValueError(f"scheduled floor {floor} outside "
                                 f"[1, max_replicas]")
        return self


def parse_phase_targets(spec: str) -> Dict[str, float]:
    """``"engine.prefill=250,router.backend_ttfb=400"`` -> bounds
    dict keyed by the obsplane's qualified phase names (ms)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"phase target {part!r}: expected "
                             f"phase=ms")
        phase, _, ms = part.partition("=")
        out[phase.strip()] = float(ms)
    return out


def parse_schedule(spec: str) -> Tuple[Tuple[int, int, int], ...]:
    """``"08:00-18:00=3,22:30-01:00=2"`` -> minute-of-day floor
    windows; end before start wraps midnight."""
    def minute(hhmm: str) -> int:
        hh, _, mm = hhmm.strip().partition(":")
        m = int(hh) * 60 + int(mm or 0)
        if not 0 <= m < 1440:
            raise ValueError(f"bad time of day {hhmm!r}")
        return m

    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        window, _, floor = part.partition("=")
        start, _, end = window.partition("-")
        if not (start and end and floor):
            raise ValueError(f"schedule entry {part!r}: expected "
                             f"HH:MM-HH:MM=replicas")
        out.append((minute(start), minute(end), int(floor)))
    return tuple(out)


@dataclass
class FleetSignal:
    """One tick's aggregated input (autoscaler/collector.py)."""

    replicas: int                       # endpoints the actuator owns
    ready: int                          # of those, reporting fresh /load
    in_flight: float = 0.0              # sum queued+running across fleet
    capacity: Optional[float] = None    # sum advertised; None = unknown
    # in-flight on the capacity-advertising engines only: utilization's
    # numerator must cover the same engines as its denominator, or a
    # mixed fleet (one bounded, one unbounded engine) reads as
    # over-utilized forever. None = same as in_flight (uniform fleet).
    bounded_in_flight: Optional[float] = None
    queue_delay_ms: float = 0.0         # max est_queue_delay_ms
    router_healthy: Optional[int] = None  # router's own healthy count
    # fleet-pilot inputs (FleetSignalCollector); absent on the raw
    # /load path, so the dumb loop's signals are unchanged
    source: str = "load"                # "fleet" | "load"
    # ({"name", "slo", "severity", "router"}, ...) currently firing
    alerts_firing: Tuple[dict, ...] = ()
    # qualified stitched phase -> live p95 ms (max across classes)
    phase_p95_ms: Optional[Dict[str, float]] = None

    def page_alerts(self) -> Tuple[dict, ...]:
        return tuple(a for a in self.alerts_firing
                     if a.get("severity") == "page")

    @property
    def utilization(self) -> Optional[float]:
        if self.capacity is None or self.capacity <= 0:
            return None
        numerator = (self.in_flight if self.bounded_in_flight is None
                     else self.bounded_in_flight)
        return numerator / self.capacity


@dataclass
class Decision:
    direction: str                      # "up" | "down" | "hold"
    current: int
    target: int
    reason: str
    signal: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return asdict(self)


class AutoscalerPolicy:
    """Stateful (breach streaks + cooldown stamps), side-effect free.

    ``decide`` never mutates the fleet; the controller applies the
    decision and confirms it back via ``note_scaled`` so a failed
    actuation does not start a cooldown.
    """

    def __init__(self, cfg: PolicyConfig, wallclock_fn=None):
        self.cfg = cfg.validate()
        self._up_streak = 0
        self._down_streak = 0
        self._settling_streak = 0
        self._last_up_at = float("-inf")
        self._last_down_at = float("-inf")
        # scheduled floors read wall-clock local time, injectable for
        # tests (returns a struct_time)
        self._wallclock = wallclock_fn or time.localtime

    # -- controller feedback -------------------------------------------

    def note_scaled(self, direction: str,
                    now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if direction == UP:
            self._last_up_at = now
        elif direction == DOWN:
            self._last_down_at = now
        self._up_streak = 0
        self._down_streak = 0

    # -- scheduled floors ----------------------------------------------

    def scheduled_floor(self) -> int:
        """The largest replica floor whose wall-clock window covers
        now (0 when none do)."""
        if not self.cfg.scheduled_floors:
            return 0
        lt = self._wallclock()
        m = lt.tm_hour * 60 + lt.tm_min
        floor = 0
        for start, end, n in self.cfg.scheduled_floors:
            inside = (start <= m < end if start <= end
                      else m >= start or m < end)
            if inside:
                floor = max(floor, n)
        return floor

    # -- the decision ---------------------------------------------------

    def decide(self, sig: FleetSignal,
               now: Optional[float] = None) -> Decision:
        now = time.monotonic() if now is None else now
        cfg = self.cfg
        util = sig.utilization

        def hold(reason):
            return self._decision(HOLD, sig, sig.replicas, reason, util)

        # phase-percentile input: any configured stage over its bound
        # breaches like queue delay (same ticks, same cooldowns)
        phase_breach = None
        if cfg.phase_p95_targets and sig.phase_p95_ms:
            for phase, bound in cfg.phase_p95_targets.items():
                v = sig.phase_p95_ms.get(phase)
                if v is not None and v > bound:
                    phase_breach = phase
                    break
        breach_up = (sig.queue_delay_ms > cfg.target_queue_delay_ms or
                     (util is not None and util > cfg.target_utilization)
                     or phase_breach is not None)
        breach_down = (sig.queue_delay_ms < cfg.down_queue_delay_ms and
                       (util is None or util < cfg.down_utilization)
                       and phase_breach is None)
        self._up_streak = self._up_streak + 1 if breach_up else 0
        self._down_streak = self._down_streak + 1 if breach_down else 0
        # the settling gate, with a grace bound: a replica that stays
        # unready past the grace window (crashed, not warming) must not
        # wedge the controller — decisions resume on what IS reporting
        self._settling_streak = (self._settling_streak + 1
                                 if sig.ready < sig.replicas else 0)
        settling = (sig.ready < sig.replicas and
                    self._settling_streak <= cfg.settling_grace_ticks)

        # burn-rate input: a firing page IS the breach — no tick
        # accumulation (the alert's multi-window evaluation already
        # debounced it), but max/settling/cooldown still bind, and a
        # burning fleet never scales down (the fall-through below can
        # only hold or go up while pages fire)
        if cfg.burn_rate_input and sig.page_alerts():
            if sig.replicas >= cfg.max_replicas:
                return hold("at_max")
            if settling:
                return hold("settling")
            if now - self._last_up_at < cfg.up_cooldown_s:
                return hold("cooldown_up")
            target = min(sig.replicas + cfg.up_step, cfg.max_replicas)
            return self._decision(UP, sig, target, "burn_rate", util)

        # scheduled floor: pre-provision the diurnal ramp (no breach
        # ticks — the schedule is the operator's explicit intent)
        floor = min(self.scheduled_floor(), cfg.max_replicas)
        if sig.replicas < floor:
            if settling:
                return hold("settling")
            if now - self._last_up_at < cfg.up_cooldown_s:
                return hold("cooldown_up")
            target = min(sig.replicas + cfg.up_step, floor)
            return self._decision(UP, sig, target, "scheduled", util)

        if breach_up:
            if sig.replicas >= cfg.max_replicas:
                return hold("at_max")
            if settling:
                # capacity already on the way up is still warming; its
                # effect is not in the signal yet
                return hold("settling")
            if self._up_streak < cfg.up_breach_ticks:
                return hold("breach_pending_up")
            if now - self._last_up_at < cfg.up_cooldown_s:
                return hold("cooldown_up")
            target = min(sig.replicas + cfg.up_step, cfg.max_replicas)
            if sig.queue_delay_ms > cfg.target_queue_delay_ms:
                reason = "queue_delay"
            elif util is not None and util > cfg.target_utilization:
                reason = "utilization"
            else:
                reason = "phase_p95"
            return self._decision(UP, sig, target, reason, util)

        if breach_down:
            if sig.replicas <= max(cfg.min_replicas, floor):
                # a scheduled floor holds like min_replicas does
                return hold("at_min")
            if settling:
                return hold("settling")
            if self._down_streak < cfg.down_breach_ticks:
                return hold("breach_pending_down")
            # scale-down cools down after ANY scale event: reclaiming
            # capacity seconds after a spike forced it up is the thrash
            # this controller exists to prevent
            if now - max(self._last_up_at,
                         self._last_down_at) < cfg.down_cooldown_s:
                return hold("cooldown_down")
            target = max(sig.replicas - cfg.down_step,
                         cfg.min_replicas, floor)
            return self._decision(DOWN, sig, target, "idle", util)

        return hold("in_band")

    def _decision(self, direction: str, sig: FleetSignal, target: int,
                  reason: str, util: Optional[float]) -> Decision:
        return Decision(
            direction=direction, current=sig.replicas, target=target,
            reason=reason,
            signal={
                "replicas": sig.replicas,
                "ready": sig.ready,
                "in_flight": round(sig.in_flight, 2),
                "capacity": sig.capacity,
                "utilization": None if util is None else round(util, 4),
                "queue_delay_ms": round(sig.queue_delay_ms, 1),
                "router_healthy": sig.router_healthy,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                # fleet-pilot provenance: every decision names the
                # signal path that produced it
                "source": sig.source,
                "alerts_firing": [a.get("name")
                                  for a in sig.alerts_firing],
                "phase_p95_ms": ({
                    ph: round(sig.phase_p95_ms[ph], 1)
                    for ph in (self.cfg.phase_p95_targets or {})
                    if sig.phase_p95_ms
                    and sig.phase_p95_ms.get(ph) is not None
                } or None),
            })
