"""The closed loop: collect -> decide -> actuate, every tick, explained.

``Autoscaler`` is an asyncio task (same ownership idiom as the
router's scraper/watcher tasks). Each tick it

1. collects a fresh ``FleetSignal`` (collector polls every engine's
   ``/load`` concurrently),
2. asks the policy for a ``Decision``,
3. appends a structured record to the decision log (ring buffer +
   optional JSON-lines file + metrics) — **every** tick, holds
   included, so "why didn't it scale?" is as answerable as "why did
   it?", and
4. applies non-hold decisions through the actuator, picking the
   least-loaded replicas as scale-down victims, and confirms success
   back to the policy (a failed actuation must not start a cooldown).

Actuation is deliberately serialized with collection: while a drain-
and-retire is in progress the loop does not evaluate new decisions, so
cooldowns are measured from *completed* fleet changes and a slow drain
can never overlap a concurrent scale-up on stale signals.

The decision-log FILE is size-capped (``decision_log_max_bytes``,
single ``.1`` rotation): a week-long pilot run appends every tick and
must not grow disk without bound; the newest full generation plus the
live file always survive.

A ``remediator`` (autoscaler/remediator.py) can ride the same loop:
after each decision is logged, its tick runs and every remediation
attempt — executed or suppressed — lands in the SAME decision log
(``kind: "remediation"``), so one file answers both "why did it
scale?" and "what did it do to the sick replica?".

Metrics (rendered by ``AutoscalerMetrics``, served by the standalone
CLI's ``/metrics``):

- ``tpu:autoscaler_replicas{state}``        — ready / starting / draining
- ``tpu:autoscaler_decisions_total{direction,reason}``
- ``tpu:autoscaler_signal_source{source}``  — 1 on the active path
- ``tpu:autoscaler_remediations_total{action,outcome}``
"""

import asyncio
import collections
import json
import os
import time
from typing import Dict, List, Optional

from prometheus_client import (CollectorRegistry, Counter, Gauge,
                               generate_latest)

from production_stack_tpu.autoscaler.actuator import Actuator
from production_stack_tpu.autoscaler.collector import SignalCollector
from production_stack_tpu.autoscaler.policy import (DOWN, HOLD,
                                                    AutoscalerPolicy)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class AutoscalerMetrics:
    def __init__(self):
        self.registry = CollectorRegistry()
        self.replicas = Gauge(
            "tpu:autoscaler_replicas",
            "Replicas by lifecycle state (ready = fresh load report; "
            "starting = launched, not yet reporting; draining = "
            "scale-down in progress)",
            ["state"], registry=self.registry)
        self.decisions = Counter(
            "tpu:autoscaler_decisions",
            "Autoscaler decisions by direction and reason (holds "
            "included — every tick is accounted for)",
            ["direction", "reason"], registry=self.registry)
        self.signal_source = Gauge(
            "tpu:autoscaler_signal_source",
            "1 on the signal path the last decision consumed: "
            "source=fleet (obsplane GET /fleet) or source=load (raw "
            "per-engine /load degradation path)",
            ["source"], registry=self.registry)
        self.remediations = Counter(
            "tpu:autoscaler_remediations",
            "Incident remediation attempts by action "
            "(drain_restart / breaker_reset) and outcome (resolved / "
            "unresolved / failed / suppressed_*) — suppressions are "
            "attempts the bounded policy refused, counted so a "
            "kill-switched pilot is visibly NOT acting",
            ["action", "outcome"], registry=self.registry)

    def observe(self, decision, *, ready: int, draining: int,
                replicas: int, source: Optional[str] = None) -> None:
        self.decisions.labels(direction=decision.direction,
                              reason=decision.reason).inc()
        self.replicas.labels(state="ready").set(ready)
        self.replicas.labels(state="draining").set(draining)
        self.replicas.labels(state="starting").set(
            max(0, replicas - ready - draining))
        if source is not None:
            for s in ("fleet", "load"):
                self.signal_source.labels(source=s).set(
                    1.0 if s == source else 0.0)

    def render(self) -> bytes:
        return generate_latest(self.registry)


class ActuationBudget:
    """One shared fleet-change budget across N per-pool control loops.

    With named pools, prefill/decode splits, and multi-model fleets,
    several ``Autoscaler`` instances run against ONE router and one
    host's process/chip budget. Each serializes its own actuation with
    its own collection (module docstring), but nothing serialized them
    with EACH OTHER — two pools deciding to scale up in the same tick
    would launch simultaneously and overshoot the shared budget. This
    object is the cross-loop gate: at most ``max_concurrent`` fleet
    changes in flight at once; a loop that cannot acquire DEFERS its
    decision (logged ``deferred: actuation_budget``, no cooldown
    started — the policy re-evaluates next tick with fresh signals,
    by which time the budget usually freed)."""

    def __init__(self, max_concurrent: int = 1):
        self.max_concurrent = max(1, int(max_concurrent))
        self.in_flight = 0
        self.deferred = 0

    def try_acquire(self) -> bool:
        if self.in_flight >= self.max_concurrent:
            self.deferred += 1
            return False
        self.in_flight += 1
        return True

    def release(self) -> None:
        self.in_flight = max(0, self.in_flight - 1)

    def snapshot(self) -> dict:
        return {"max_concurrent": self.max_concurrent,
                "in_flight": self.in_flight,
                "deferred": self.deferred}


class Autoscaler:
    """Owns the control loop; see module docstring."""

    def __init__(self, policy: AutoscalerPolicy, actuator: Actuator,
                 collector: SignalCollector, *,
                 interval_s: float = 2.0,
                 decision_log_path: Optional[str] = None,
                 decision_log_max_bytes: int = 16 * 1024 * 1024,
                 metrics: Optional[AutoscalerMetrics] = None,
                 max_decisions: int = 4096,
                 alerts_fetch=None,
                 remediator=None,
                 pool: Optional[str] = None,
                 budget: Optional[ActuationBudget] = None):
        self.policy = policy
        self.actuator = actuator
        self.collector = collector
        self.interval_s = interval_s
        self.decision_log_path = decision_log_path
        self.decision_log_max_bytes = max(4096, decision_log_max_bytes)
        self.metrics = metrics or AutoscalerMetrics()
        self.decisions: collections.deque = collections.deque(
            maxlen=max_decisions)
        self.scale_events: List[dict] = []
        self.remediation_events: List[dict] = []
        self.remediator = remediator
        if remediator is not None and \
                getattr(remediator, "metrics", None) is None:
            remediator.metrics = self.metrics
        # optional async callable returning the router's firing
        # burn-rate alert names (slo.py; the standalone CLI wires it to
        # GET {router}/alerts) — each tick's decision record is
        # annotated with whatever is firing, so "the fleet scaled while
        # chat_availability_page was burning" is readable straight off
        # the decision log
        self._alerts_fetch = alerts_fetch
        # named pool this loop owns (None = the whole fleet): stamped
        # on every decision record so an N-pool deployment's shared
        # decision log stays attributable per pool
        self.pool = pool
        # shared cross-loop actuation gate (None = unbudgeted)
        self.budget = budget
        self._task: Optional[asyncio.Task] = None

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> None:
        await self.collector.start()
        self._task = asyncio.create_task(self._loop(), name="autoscaler")

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.collector.close()

    def healthy(self) -> bool:
        return self._task is not None and not self._task.done()

    async def _loop(self) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("autoscaler tick failed")
            await asyncio.sleep(self.interval_s)

    # -- one control tick (tests drive this directly) --------------------

    async def tick(self, now: Optional[float] = None) -> dict:
        wall0 = time.monotonic()
        now = wall0 if now is None else now
        sig = await self.collector.collect(
            replicas=self.actuator.replicas)
        decision = self.policy.decide(sig, now)
        record = {"ts": round(time.time(), 3),
                  # top-level provenance stamp: the pilot must make
                  # "which signal path produced this?" grep-able
                  # without digging into the signal dict
                  "signal_source": sig.source,
                  **decision.to_json()}
        if self.pool is not None:
            record["pool"] = self.pool
        if self._alerts_fetch is not None:
            # annotation only: a dead router must never stall scaling
            try:
                firing = await self._alerts_fetch()
            except Exception as e:
                logger.debug("alerts fetch failed: %s", e)
                firing = None
            if firing:
                record["alerts_firing"] = sorted(firing)

        if decision.direction != HOLD:
            tag = f" [{self.pool}]" if self.pool else ""
            if self.budget is not None and not self.budget.try_acquire():
                # another pool's fleet change is in flight: defer, no
                # cooldown — the policy re-decides next tick on fresh
                # signals instead of silently queueing a stale target
                record["applied"] = False
                record["deferred"] = "actuation_budget"
                logger.info("autoscaler%s: %s %d -> %d deferred "
                            "(shared actuation budget exhausted)", tag,
                            decision.direction, decision.current,
                            decision.target)
            else:
                victims = None
                if decision.direction == DOWN:
                    victims = self._pick_victims(
                        decision.current - decision.target)
                    record["victims"] = victims
                logger.info("autoscaler%s: %s %d -> %d (%s) signal=%s",
                            tag, decision.direction, decision.current,
                            decision.target, decision.reason,
                            decision.signal)
                try:
                    await self.actuator.apply(decision.target,
                                              victims=victims)
                except Exception as e:
                    logger.exception("actuation %d -> %d failed",
                                     decision.current, decision.target)
                    record["applied"] = False
                    record["error"] = f"{type(e).__name__}: {e}"
                else:
                    record["applied"] = True
                    # only a COMPLETED fleet change starts a cooldown (a
                    # failed actuation must stay immediately retryable),
                    # and it starts when the change finished: a 30 s
                    # drain must not have silently consumed the down
                    # cooldown. Expressed as tick-clock + elapsed wall
                    # time so injected-clock tests and production agree.
                    self.policy.note_scaled(
                        decision.direction,
                        now + (time.monotonic() - wall0))
                    self.scale_events.append(record)
                finally:
                    if self.budget is not None:
                        self.budget.release()

        self._log(record, sig)
        if self.remediator is not None:
            # remediation rides the same loop but must never stall
            # scaling: its failures are logged, not raised
            try:
                for rem in await self.remediator.tick(now):
                    self._log_remediation(rem)
            except Exception:
                logger.exception("remediator tick failed")
        return record

    def _pick_victims(self, count: int) -> List[str]:
        """Least-loaded managed endpoints retire first: minimum
        in-flight work to drain, minimum sessions disturbed."""
        loads = self.collector.per_engine()
        managed = self.actuator.endpoint_urls()
        return sorted(
            managed,
            key=lambda u: (loads[u].in_flight if u in loads
                           else float("-inf")))[:count]

    def _log(self, record: dict, sig) -> None:
        self.decisions.append(record)
        self.metrics.observe(
            _DecisionView(record["direction"], record["reason"]),
            ready=sig.ready,
            draining=len(self.actuator.draining_urls()),
            replicas=sig.replicas,
            source=record.get("signal_source"))
        self._append_log_line(record)

    def _log_remediation(self, record: dict) -> None:
        record = {"kind": "remediation", **record}
        record.setdefault("ts", round(time.time(), 3))
        self.decisions.append(record)
        self.remediation_events.append(record)
        self.metrics.remediations.labels(
            action=record.get("action", "none"),
            outcome=record.get("outcome", "unknown")).inc()
        self._append_log_line(record)

    def _append_log_line(self, record: dict) -> None:
        if not self.decision_log_path:
            return
        try:
            self._maybe_rotate_log()
            with open(self.decision_log_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            logger.exception("decision log write failed")

    def _maybe_rotate_log(self) -> None:
        """Size-capped rotation: one ``.1`` generation, so the log's
        disk footprint is bounded at ~2x the cap however long the
        pilot runs."""
        try:
            size = os.path.getsize(self.decision_log_path)
        except OSError:
            return
        if size < self.decision_log_max_bytes:
            return
        os.replace(self.decision_log_path,
                   self.decision_log_path + ".1")
        logger.info("decision log rotated at %d bytes -> %s.1",
                    size, self.decision_log_path)

    # -- reporting ------------------------------------------------------

    def timeline(self) -> List[dict]:
        return list(self.decisions)

    def summary(self) -> Dict:
        ups = [e for e in self.scale_events if e["direction"] == "up"]
        downs = [e for e in self.scale_events
                 if e["direction"] == "down"]
        return {
            "ticks": len(self.decisions),
            "scale_ups": len(ups),
            "scale_downs": len(downs),
            "failed_actuations": len(
                [e for e in self.decisions
                 if e.get("applied") is False]),
            "max_replicas_observed": max(
                (e["target"] for e in ups),
                default=self.actuator.replicas),
            "scale_events": self.scale_events,
            "remediations": self.remediation_events,
        }


class _DecisionView:
    """Just the two fields AutoscalerMetrics.observe reads."""

    __slots__ = ("direction", "reason")

    def __init__(self, direction, reason):
        self.direction = direction
        self.reason = reason
