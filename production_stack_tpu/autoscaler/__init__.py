"""Closed-loop autoscaler: load-signal-driven replica scaling.

The in-process alternative to the Kubernetes HPA shipped under
``observability/`` — both drive replica count off the same engine
signals (``tpu:est_queue_delay_ms``, ``tpu:engine_capacity_seqs``);
this one closes the loop in-repo, testably, with drain-safe
scale-down. See docs/autoscaling.md.
"""

from production_stack_tpu.autoscaler.actuator import (Actuator,
                                                      KubernetesActuator,
                                                      LocalProcessActuator)
from production_stack_tpu.autoscaler.collector import SignalCollector
from production_stack_tpu.autoscaler.controller import (Autoscaler,
                                                        AutoscalerMetrics)
from production_stack_tpu.autoscaler.policy import (AutoscalerPolicy,
                                                    Decision, FleetSignal,
                                                    PolicyConfig)

__all__ = [
    "Actuator", "Autoscaler", "AutoscalerMetrics", "AutoscalerPolicy",
    "Decision", "FleetSignal", "KubernetesActuator",
    "LocalProcessActuator", "PolicyConfig", "SignalCollector",
]
