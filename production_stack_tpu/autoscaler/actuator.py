"""Actuators: turn a replica-count decision into real fleet changes.

Two implementations behind one interface:

- ``LocalProcessActuator`` — owns real engine processes on this host.
  Scale-up launches engines (loadgen orchestrator), waits for health,
  and swaps the router's endpoint set by rewriting the
  ``--dynamic-config-json`` file the router hot-reloads. Scale-down is
  **loss-free by construction** and the ordering is the contract
  (pinned by tests/test_autoscaler.py):

      1. ``POST /admin/drain`` on the router — the victim takes no new
         admissions while existing requests keep their connections;
      2. wait until the victim's ``/load`` reports zero in-flight
         (bounded by ``drain_timeout_s``);
      3. rewrite the dynamic config without the victim and wait for
         the router to apply it;
      4. clear the (now pointless) drain flag and only then terminate
         the process.

  A client-visible 5xx during scale-down means step order was violated
  somewhere; ``loadgen autoscale`` exits 1 on any.

- ``KubernetesActuator`` — patches ``spec.replicas`` on a Deployment
  (the cluster equivalent of the same decision). ``dry_run=True`` (the
  default, and what tests exercise) only records the patch it *would*
  apply; live mode shells out to ``kubectl patch``. Pod-level drain
  safety is delegated to the chart's preStop hook +
  ``terminationGracePeriodSeconds`` — the in-process actuator is the
  path that proves the drain contract end to end in-repo.

Every fleet mutation appends to ``self.events`` (ordered, inspectable)
so scale events stay explainable after the fact.
"""

import asyncio
import json
import os
import time
from abc import ABC, abstractmethod
from typing import Awaitable, Callable, Dict, List, Optional

import aiohttp

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class Actuator(ABC):
    """The controller's view of a scalable fleet."""

    @property
    @abstractmethod
    def replicas(self) -> int:
        """Replicas currently owned/requested."""

    def endpoint_urls(self) -> List[str]:
        """Engine URLs this actuator manages ([] when the platform,
        not the actuator, owns endpoints — e.g. Kubernetes)."""
        return []

    def draining_urls(self) -> List[str]:
        return []

    @abstractmethod
    async def apply(self, target: int,
                    victims: Optional[List[str]] = None) -> None:
        """Drive the fleet to ``target`` replicas. ``victims`` is the
        controller's least-loaded pick for scale-down; actuators that
        cannot honour it may ignore it."""

    async def close(self) -> None:
        pass


class PoolConfigWriter:
    """One shared dynamic-config document for N per-pool actuators.

    With named pools (router/pools.py) every pool's membership lives in
    ONE ``pools`` key of the router's dynamic config — so per-pool
    actuators cannot each own the file (the last writer would wipe the
    other pools). This writer holds the full table; each actuator calls
    ``set_pool`` with just ITS pool's membership and the whole document
    is rewritten atomically. The router's diff-and-swap apply keeps
    untouched pools' policy state, so pool A scaling never resets pool
    B's rings (the r11/r12 contract the multitenant rig gates on).

    ``history`` keeps every URL a pool has EVER contained — the rig's
    routing-correctness audit joins ok-responses' x-engine-id against
    it, because a response served just before a scale-down lands after
    the membership shrank.
    """

    def __init__(self, path: str, extra_config: Optional[Dict] = None):
        self.path = path
        self.extra_config = dict(extra_config or {})
        self.pools: Dict[str, dict] = {}
        self.history: Dict[str, set] = {}
        self.writes = 0

    def set_pool(self, name: str, urls: List[str], models: List[str],
                 routing_logic: str = "roundrobin",
                 session_key: str = "x-user-id") -> None:
        self.pools[name] = {
            "backends": list(urls),
            "models": list(models),
            "routing_logic": routing_logic,
            "session_key": session_key,
        }
        self.history.setdefault(name, set()).update(urls)
        self._write()

    def total_endpoints(self) -> int:
        """Fleet-wide endpoint count of the CURRENT document — what the
        router's /health reports once the swap applies."""
        return sum(len(p["backends"]) for p in self.pools.values())

    def _write(self) -> None:
        cfg = {"pools": {n: dict(p) for n, p in self.pools.items()},
               **self.extra_config}
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(cfg, f, indent=1)
        os.replace(tmp, self.path)
        self.writes += 1


class LocalProcessActuator(Actuator):
    """Real engine processes + the router's dynamic-config hot reload.

    ``spawn``/``kill`` are injectable (tier-1 tests swap in in-process
    fake-engine servers); the defaults launch real engine processes via
    the loadgen orchestrator. ``router_url`` may be set after
    construction — the orchestration order is engines first, router
    (pointing at them) second, drains third.

    **Pool mode** (``config_writer`` + ``pool``): instead of owning the
    whole config file, the actuator publishes its membership as ONE
    named pool through a shared :class:`PoolConfigWriter` — N per-pool
    actuators coexist on one router, and the applied-config wait
    checks the fleet-wide endpoint count (the union the router's
    /health reports), not just this pool's.
    """

    def __init__(self, *, engine: str = "fake",
                 dynamic_config_path: str,
                 router_url: Optional[str] = None,
                 routing_logic: str = "least_loaded",
                 log_dir: str = "loadgen-logs",
                 platform: str = "cpu",
                 engine_extra_args: Optional[List[str]] = None,
                 startup_timeout_s: float = 420.0,
                 drain_timeout_s: float = 60.0,
                 drain_poll_s: float = 0.25,
                 config_apply_timeout_s: float = 30.0,
                 extra_config: Optional[Dict] = None,
                 pool: Optional[str] = None,
                 pool_models: Optional[List[str]] = None,
                 config_writer: Optional[PoolConfigWriter] = None,
                 spawn: Optional[Callable[[], Awaitable[object]]] = None,
                 kill: Optional[
                     Callable[[object], Awaitable[None]]] = None):
        self.engine = engine
        self.model = "fake-model" if engine == "fake" else engine
        # pool mode: this actuator's membership is one named pool in a
        # shared pools document (see class docstring)
        self.pool = pool
        self.pool_models = list(pool_models or [])
        self.config_writer = config_writer
        if (config_writer is None) != (pool is None):
            raise ValueError("pool mode needs BOTH config_writer and "
                             "pool (or neither)")
        self.dynamic_config_path = dynamic_config_path
        self.router_url = router_url
        self.routing_logic = routing_logic
        self.log_dir = log_dir
        self.platform = platform
        self.engine_extra_args = engine_extra_args
        self.startup_timeout_s = startup_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.drain_poll_s = drain_poll_s
        self.config_apply_timeout_s = config_apply_timeout_s
        # pool-label pass-through: keys merged verbatim into every
        # dynamic-config write (e.g. prefill_backends/prefill_models of
        # a disaggregated deployment) — an autoscaler that owns only
        # the decode pool must not wipe the router's prefill pool on
        # each scale event (router/dynamic_config.py treats an ABSENT
        # prefill key as "leave the pool alone", so the default None
        # is also safe)
        self.extra_config = dict(extra_config or {})
        self._spawn = spawn or self._spawn_process
        self._kill = kill or self._kill_process
        self._handles: Dict[str, object] = {}     # url -> spawn handle
        self._draining: set = set()
        self._session: Optional[aiohttp.ClientSession] = None
        self.events: List[tuple] = []             # ordered mutation log

    # -- lifecycle ------------------------------------------------------

    async def start(self, initial: int) -> List[str]:
        """Launch the initial fleet and write the first config file.
        Called before the router exists; returns the engine URLs to
        hand the router as its ``--static-backends``."""
        self._session = aiohttp.ClientSession()
        await self._launch(initial)
        self._write_config()
        return self.endpoint_urls()

    async def close(self) -> None:
        for url in list(self._handles):
            await self._kill(self._handles.pop(url))
            self.events.append(("terminate", url))
        if self._session:
            await self._session.close()
            self._session = None

    # -- Actuator surface -----------------------------------------------

    @property
    def replicas(self) -> int:
        return len(self._handles)

    def endpoint_urls(self) -> List[str]:
        return sorted(self._handles)

    def draining_urls(self) -> List[str]:
        return sorted(self._draining)

    async def apply(self, target: int,
                    victims: Optional[List[str]] = None) -> None:
        if target > self.replicas:
            await self._scale_up(target - self.replicas)
        elif target < self.replicas:
            want = self.replicas - target
            victims = list(victims or [])[:want]
            # the controller picks least-loaded victims; top up
            # arbitrarily if it named fewer than the step needs
            for url in self.endpoint_urls():
                if len(victims) >= want:
                    break
                if url not in victims:
                    victims.append(url)
            for url in victims:
                await self._retire(url)

    # -- scale-up -------------------------------------------------------

    async def _launch(self, count: int) -> List[str]:
        handles = await asyncio.gather(
            *(self._spawn() for _ in range(count)))
        from production_stack_tpu.loadgen.orchestrator import wait_healthy
        await asyncio.gather(*(
            wait_healthy(h.url, self.startup_timeout_s) for h in handles))
        for h in handles:
            self._handles[h.url.rstrip("/")] = h
            self.events.append(("launch", h.url.rstrip("/")))
        return [h.url.rstrip("/") for h in handles]

    async def _scale_up(self, count: int) -> None:
        added = await self._launch(count)
        self._write_config()
        self.events.append(("config_swap", tuple(self.endpoint_urls())))
        await self._wait_router_applied(self._expected_fleet())
        logger.info("scale-up%s: +%d -> %d replicas (%s)",
                    f" [{self.pool}]" if self.pool else "", count,
                    self.replicas, ", ".join(added))

    # -- scale-down (the drain-safe ordering contract) -------------------

    async def _retire(self, url: str) -> None:
        url = url.rstrip("/")
        handle = self._handles.get(url)
        if handle is None:
            return
        self._draining.add(url)
        try:
            await self._set_drain(url, True)
            self.events.append(("drain", url))
            drained = await self._wait_drained(url)
            self.events.append(("drained" if drained else "drain_timeout",
                                url))
            del self._handles[url]
            self._write_config()
            self.events.append(("config_swap",
                                tuple(self.endpoint_urls())))
            await self._wait_router_applied(self._expected_fleet())
            # the endpoint is out of discovery; clear the stale flag so
            # a future replica reusing the port is not born draining
            await self._set_drain(url, False)
            await self._kill(handle)
            self.events.append(("terminate", url))
            logger.info("scale-down: retired %s (%s) -> %d replicas",
                        url, "drained clean" if drained else
                        f"drain bound {self.drain_timeout_s:.0f}s hit",
                        self.replicas)
        finally:
            self._draining.discard(url)

    async def _set_drain(self, url: str, drain: bool) -> None:
        if self.router_url is None:
            return
        try:
            async with self._session.post(
                    f"{self.router_url}/admin/drain",
                    json={"url": url, "drain": drain},
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                if r.status != 200:
                    logger.warning("drain(%s, %s) answered HTTP %d: %s",
                                   url, drain, r.status,
                                   (await r.text())[:200])
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            logger.warning("drain(%s, %s) failed: %s", url, drain, e)

    async def _wait_drained(self, url: str) -> bool:
        """Poll the victim's /load until nothing is queued or running."""
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            try:
                async with self._session.get(
                        f"{url}/load",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    if r.status == 200:
                        body = await r.json()
                        if (body.get("queue_depth") or 0) == 0 and \
                                (body.get("running") or 0) == 0:
                            return True
            except (aiohttp.ClientConnectionError, ConnectionError):
                return True          # nothing listening = nothing in flight
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    ValueError):
                pass                 # busy/garbled: keep polling to the bound
            await asyncio.sleep(self.drain_poll_s)
        return False

    # -- dynamic-config swap --------------------------------------------

    def _expected_fleet(self) -> int:
        """Endpoint count the router should report once the last write
        applies: fleet-wide (all pools) in pool mode, else this
        actuator's own fleet."""
        if self.config_writer is not None:
            return self.config_writer.total_endpoints()
        return len(self._handles)

    def _write_config(self) -> None:
        urls = self.endpoint_urls()
        if self.config_writer is not None:
            self.config_writer.set_pool(
                self.pool, urls, self.pool_models or [self.model],
                routing_logic=self.routing_logic)
            return
        cfg = {
            "service_discovery": "static",
            "routing_logic": self.routing_logic,
            "static_backends": urls,
            "static_models": [self.model] * len(urls),
            **self.extra_config,
        }
        # atomic replace: the router's watcher must never read half a
        # JSON document
        tmp = f"{self.dynamic_config_path}.tmp"
        with open(tmp, "w") as f:
            json.dump(cfg, f, indent=1)
        os.replace(tmp, self.dynamic_config_path)

    async def _wait_router_applied(self, expect: int) -> None:
        if self.router_url is None:
            return
        deadline = time.monotonic() + self.config_apply_timeout_s
        last = None
        while time.monotonic() < deadline:
            try:
                async with self._session.get(
                        f"{self.router_url}/health",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    body = await r.json()
                    last = body.get("endpoints")
                    if last == expect:
                        return
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    ValueError):
                pass
            await asyncio.sleep(0.1)
        logger.warning("router did not reach %d endpoints within %.0fs "
                       "(last saw %s); proceeding", expect,
                       self.config_apply_timeout_s, last)

    # -- default process backend ----------------------------------------

    async def _spawn_process(self):
        from production_stack_tpu.loadgen.orchestrator import (free_port,
                                                               launch_engine)
        return launch_engine(self.engine, free_port(),
                             log_dir=self.log_dir, platform=self.platform,
                             extra_args=self.engine_extra_args)

    async def _kill_process(self, proc) -> None:
        from production_stack_tpu.loadgen.orchestrator import _stop
        await asyncio.to_thread(_stop, [proc])


class KubernetesActuator(Actuator):
    """Patch a Deployment's ``spec.replicas`` (the HPA-shaped half of
    the actuator abstraction).

    ``dry_run=True`` records every patch in ``self.patches`` without
    touching a cluster — deterministic for tests and usable as a
    "what would the autoscaler do" shadow mode against production
    signals. Live mode requires only ``kubectl`` on PATH (no python
    kubernetes client dependency).
    """

    def __init__(self, *, deployment: str, namespace: str = "default",
                 initial_replicas: int = 1, dry_run: bool = True,
                 kubectl: str = "kubectl", pool: Optional[str] = None):
        self.deployment = deployment
        self.namespace = namespace
        self.dry_run = dry_run
        self.kubectl = kubectl
        # named pool this deployment backs (disaggregated topologies
        # run one policy loop per pool — prefill and decode deployments
        # scale independently); recorded on every patch so decision
        # logs stay attributable
        self.pool = pool
        self._replicas = initial_replicas
        self.patches: List[dict] = []
        self.events: List[tuple] = []

    @property
    def replicas(self) -> int:
        return self._replicas

    async def apply(self, target: int,
                    victims: Optional[List[str]] = None) -> None:
        patch = {"spec": {"replicas": target}}
        record = {
            "namespace": self.namespace,
            "deployment": self.deployment,
            "patch": patch,
            "dry_run": self.dry_run,
            "previous_replicas": self._replicas,
        }
        if self.pool:
            record["pool"] = self.pool
        self.patches.append(record)
        self.events.append(("patch", self.deployment, target))
        if not self.dry_run:
            cmd = [self.kubectl, "-n", self.namespace, "patch",
                   "deployment", self.deployment, "--type", "merge",
                   "-p", json.dumps(patch)]
            proc = await asyncio.create_subprocess_exec(
                *cmd, stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT)
            out, _ = await proc.communicate()
            if proc.returncode != 0:
                raise RuntimeError(
                    f"kubectl patch failed rc={proc.returncode}: "
                    f"{out.decode(errors='replace')[:400]}")
        logger.info("k8s actuator: %s/%s spec.replicas %d -> %d%s",
                    self.namespace, self.deployment, self._replicas,
                    target, " (dry-run)" if self.dry_run else "")
        self._replicas = target
