"""CLI: python -m production_stack_tpu.autoscaler — standalone controller.

Runs the collect->decide->actuate loop against an already-running
fleet. Two actuator modes:

- ``--k8s-deployment NAME`` — KubernetesActuator. Dry-run by default:
  every tick's would-be ``spec.replicas`` patch is logged instead of
  applied, which makes this a zero-risk shadow controller to compare
  against a live HPA on the same signals. ``--k8s-live`` applies
  patches via ``kubectl``.
- no deployment flag — observe-only: decisions are logged (and served
  on ``/metrics``) but nothing actuates. The full local-process
  actuator path (launching/retiring real engines) is exercised by
  ``python -m production_stack_tpu.loadgen autoscale``, which owns the
  whole stack's lifecycle.

Signals come from ``--engines`` (comma-separated engine URLs, each
polled on ``/load``) plus ``--router-url`` for the router's healthy
count. ``--metrics-port`` serves tpu:autoscaler_* gauges.

**Fleet pilot** (docs/autoscaling.md "Fleet pilot"): ``--obsplane-url``
switches the collector to the obsplane's ``GET /fleet`` (burn-rate
alerts + per-stage phase percentiles ride along; raw ``/load`` polling
stays wired as the degradation path). ``--burn-rate-input``,
``--phase-p95-target`` and ``--schedule`` enable the three pilot
policy inputs; ``--remediate`` (kill-switch, default off) arms the
bounded incident remediator against the same obsplane.
"""

import argparse
import asyncio
from typing import Optional

from aiohttp import web

from production_stack_tpu.autoscaler.actuator import (Actuator,
                                                      KubernetesActuator)
from production_stack_tpu.autoscaler.collector import (
    FleetSignalCollector, SignalCollector)
from production_stack_tpu.autoscaler.controller import (Autoscaler,
                                                        AutoscalerMetrics)
from production_stack_tpu.autoscaler.policy import (AutoscalerPolicy,
                                                    PolicyConfig,
                                                    parse_phase_targets,
                                                    parse_schedule)
from production_stack_tpu.autoscaler.remediator import (RemediationPolicy,
                                                        Remediator)
from production_stack_tpu.utils import init_logger, parse_comma_separated

logger = init_logger(__name__)


class _ObserveOnlyActuator(Actuator):
    """Records targets, changes nothing (decision shadow mode)."""

    def __init__(self, initial: int):
        self._replicas = initial
        self.targets = []

    @property
    def replicas(self) -> int:
        return self._replicas

    async def apply(self, target: int, victims=None) -> None:
        self.targets.append(target)
        logger.info("observe-only: would scale %d -> %d",
                    self._replicas, target)
        self._replicas = target


def add_policy_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--target-queue-delay-ms", type=float, default=500.0,
                   help="scale up when any engine's est queue delay "
                        "exceeds this")
    p.add_argument("--down-queue-delay-ms", type=float, default=100.0,
                   help="scale down only below this (hysteresis band)")
    p.add_argument("--target-utilization", type=float, default=0.90,
                   help="scale up when fleet in-flight / advertised "
                        "capacity exceeds this")
    p.add_argument("--down-utilization", type=float, default=0.50)
    p.add_argument("--up-step", type=int, default=1)
    p.add_argument("--down-step", type=int, default=1)
    p.add_argument("--up-cooldown", type=float, default=15.0)
    p.add_argument("--down-cooldown", type=float, default=60.0)
    p.add_argument("--up-breach-ticks", type=int, default=2)
    p.add_argument("--down-breach-ticks", type=int, default=3)
    p.add_argument("--burn-rate-input", action="store_true",
                   help="fleet pilot: a firing page-severity burn-rate "
                        "alert in GET /fleet is an immediate scale-up "
                        "breach (no consecutive-tick requirement) and "
                        "blocks scale-down while burning")
    p.add_argument("--phase-p95-target", default="",
                   help="fleet pilot: per-stage p95 bounds from the "
                        "obsplane's stitched phase percentiles, e.g. "
                        "'engine.prefill=250,engine.queued=500' (ms); "
                        "any breach is a scale-up signal")
    p.add_argument("--schedule", default="",
                   help="fleet pilot: wall-clock replica floors for "
                        "predictable ramps, e.g. "
                        "'08:00-18:00=3,18:00-22:00=2' (end before "
                        "start wraps midnight)")


def policy_config(args: argparse.Namespace) -> PolicyConfig:
    return PolicyConfig(
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        target_queue_delay_ms=args.target_queue_delay_ms,
        down_queue_delay_ms=args.down_queue_delay_ms,
        target_utilization=args.target_utilization,
        down_utilization=args.down_utilization,
        up_step=args.up_step, down_step=args.down_step,
        up_cooldown_s=args.up_cooldown,
        down_cooldown_s=args.down_cooldown,
        up_breach_ticks=args.up_breach_ticks,
        down_breach_ticks=args.down_breach_ticks,
        burn_rate_input=args.burn_rate_input,
        phase_p95_targets=(parse_phase_targets(args.phase_p95_target)
                           or None),
        scheduled_floors=parse_schedule(args.schedule)).validate()


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        "python -m production_stack_tpu.autoscaler",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--engines", required=True,
                   help="comma-separated engine URLs to poll /load on")
    p.add_argument("--router-url", default=None,
                   help="router base URL(s) for the healthy-endpoint "
                        "cross-check; comma-separated with N router "
                        "replicas (the collector asks every one and "
                        "takes the max)")
    p.add_argument("--alerts-url", default=None,
                   help="router base URL whose GET /alerts firing set "
                        "annotates every decision record (defaults to "
                        "--router-url when that is set; 'off' "
                        "disables)")
    p.add_argument("--obsplane-url", default=None,
                   help="fleet pilot: obsplane base URL; the collector "
                        "consumes GET /fleet (alerts + phase "
                        "percentiles ride along) and degrades to raw "
                        "/load polling whenever it is unreachable or "
                        "stale")
    p.add_argument("--fleet-freshness", type=float, default=10.0,
                   help="max age (s) of a /fleet per-engine sample "
                        "before the pilot treats the snapshot as stale "
                        "and falls back to /load")
    p.add_argument("--interval", type=float, default=5.0,
                   help="seconds between control ticks")
    p.add_argument("--decision-log", default=None,
                   help="append one JSON line per tick here")
    p.add_argument("--decision-log-max-bytes", type=int,
                   default=16 * 1024 * 1024,
                   help="rotate the decision log to .1 at this size "
                        "(disk footprint stays bounded at ~2x)")
    p.add_argument("--remediate", action="store_true",
                   help="KILL-SWITCH for incident auto-remediation "
                        "(default off): when set AND --obsplane-url "
                        "is given, high-confidence incident "
                        "attributions are drained/breaker-reset "
                        "within the bounds below; without it every "
                        "attempt is logged suppressed_killswitch")
    p.add_argument("--remediate-confidence", default="high",
                   choices=("high", "medium", "none"),
                   help="minimum attribution confidence the "
                        "remediator will act on")
    p.add_argument("--remediate-rate", type=int, default=1,
                   help="max executed remediations per window")
    p.add_argument("--remediate-window", type=float, default=600.0,
                   help="the rate-limit window (s)")
    p.add_argument("--remediate-cooldown", type=float, default=120.0,
                   help="seconds after an executed remediation before "
                        "the next may run")
    p.add_argument("--remediate-verify-timeout", type=float,
                   default=60.0,
                   help="bounded wait for the triggering alert to "
                        "leave the firing set before the attempt is "
                        "logged unresolved")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve tpu:autoscaler_* on this port (0 = off)")
    p.add_argument("--k8s-deployment", default=None,
                   help="Deployment to patch (KubernetesActuator)")
    p.add_argument("--k8s-namespace", default="default")
    p.add_argument("--k8s-live", action="store_true",
                   help="actually apply patches via kubectl (default: "
                        "dry-run — log the patch, touch nothing)")
    add_policy_args(p)
    return p.parse_args(argv)


def make_alerts_fetch(router_url: str):
    """Async fetcher of the router's firing burn-rate alert names
    (GET /alerts "firing" list) for decision-log annotation. Failures
    raise — the controller catches and skips the annotation. Holds ONE
    lazily-created session across ticks (the collector/actuator
    convention — no per-tick connection setup); callers close it via
    ``fetch.aclose()``."""
    import aiohttp

    holder = {"session": None}

    async def fetch():
        if holder["session"] is None or holder["session"].closed:
            holder["session"] = aiohttp.ClientSession()
        timeout = aiohttp.ClientTimeout(total=2.0)
        async with holder["session"].get(f"{router_url}/alerts",
                                         timeout=timeout) as r:
            if r.status != 200:
                raise RuntimeError(f"/alerts HTTP {r.status}")
            body = await r.json()
            return list(body.get("firing") or [])

    async def aclose():
        if holder["session"] is not None and not holder["session"].closed:
            await holder["session"].close()

    fetch.aclose = aclose
    return fetch


async def serve_metrics(metrics: AutoscalerMetrics,
                        port: int) -> Optional[web.AppRunner]:
    if port <= 0:
        return None

    async def handler(request: web.Request) -> web.Response:
        return web.Response(body=metrics.render(),
                            content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", handler)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    await web.TCPSite(runner, "0.0.0.0", port).start()
    logger.info("autoscaler metrics on :%d/metrics", port)
    return runner


async def amain(args: argparse.Namespace) -> None:
    urls = parse_comma_separated(args.engines)
    initial = len(urls)
    if args.k8s_deployment:
        actuator = KubernetesActuator(
            deployment=args.k8s_deployment,
            namespace=args.k8s_namespace,
            initial_replicas=initial,
            dry_run=not args.k8s_live)
    else:
        actuator = _ObserveOnlyActuator(initial)
    if args.obsplane_url:
        collector = FleetSignalCollector(
            lambda: urls,
            obsplane_url=args.obsplane_url,
            router_url=args.router_url,
            poll_interval_s=args.interval,
            freshness_s=args.fleet_freshness)
    else:
        collector = SignalCollector(lambda: urls,
                                    router_url=args.router_url,
                                    poll_interval_s=args.interval)
    alerts_fetch = None
    # with N router replicas, alerts come from the first listed one
    # (every replica computes its own burn off its own traffic; any
    # live replica's firing set is a valid annotation source)
    first_router = (args.router_url or "").split(",")[0].strip() or None
    alerts_url = args.alerts_url or first_router
    if alerts_url and alerts_url != "off":
        alerts_fetch = make_alerts_fetch(alerts_url.rstrip("/"))
    remediator = None
    if args.obsplane_url:
        # constructed even with the kill-switch down: suppressed
        # attempts must land in the decision log so "the pilot saw it
        # and chose not to act" is auditable
        remediator = Remediator(
            obsplane_url=args.obsplane_url,
            router_urls=args.router_url or [],
            policy=RemediationPolicy(
                enabled=args.remediate,
                confidence_floor=args.remediate_confidence,
                max_per_window=args.remediate_rate,
                window_s=args.remediate_window,
                cooldown_s=args.remediate_cooldown,
                verify_timeout_s=args.remediate_verify_timeout),
            engine_urls_fn=lambda: urls)
    scaler = Autoscaler(AutoscalerPolicy(policy_config(args)), actuator,
                        collector, interval_s=args.interval,
                        decision_log_path=args.decision_log,
                        decision_log_max_bytes=args.decision_log_max_bytes,
                        alerts_fetch=alerts_fetch,
                        remediator=remediator)
    runner = await serve_metrics(scaler.metrics, args.metrics_port)
    await scaler.start()
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await scaler.close()
        if remediator is not None:
            await remediator.close()
        if alerts_fetch is not None:
            await alerts_fetch.aclose()
        if runner is not None:
            await runner.cleanup()


def main(argv=None) -> None:
    try:
        asyncio.run(amain(parse_args(argv)))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
