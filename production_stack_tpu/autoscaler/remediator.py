"""Bounded incident auto-remediation: the runbook as code.

The obsplane's flight recorder (r18) already writes machine
attribution into every incident bundle — "engine X, phase prefill,
confidence high". This module closes that loop: it polls the
obsplane's ``GET /fleet/incidents`` index and, for an incident whose
attribution names a guilty engine, executes the runbook the human
would have followed:

1. **drain** the culprit at every router (``POST /admin/drain`` — the
   same plumbing the actuator's drain-safe scale-down uses),
2. wait for its in-flight work to finish (bounded),
3. **restart** it via the injected ``restart_fn`` (the orchestration
   layer owns process lifecycles; a k8s deployment would delete the
   pod) — or fall back to a breaker reset when no restart hook is
   wired,
4. **undrain** + ``POST /admin/breaker`` reset so routing resumes,
5. **verify**: poll ``GET /fleet`` until the triggering alert leaves
   the firing set (bounded) — a remediation that does not resolve its
   alert is logged ``unresolved``, never silently declared victory.

Every attempt — executed or refused — is returned to the controller
and lands in the decision log with an outcome; the bounded policy is
the point:

- **kill-switch** (``enabled``, default OFF): nothing actuates until
  an operator opts in; suppressions are still logged, so a
  kill-switched pilot is visibly *choosing* not to act.
- **confidence floor**: weak attributions ("medium"/"none") are not
  chased by default.
- **rate limit**: at most ``max_per_window`` executed remediations
  per ``window_s`` — an attribution gone wrong must not be able to
  roll the whole fleet.
- **cooldown**: after any executed remediation the loop waits
  ``cooldown_s`` before the next, so verify windows never overlap.
- **role filter**: only engine/prefill processes are remediable; a
  guilty *router* is somebody's pager, not this loop's business.
"""

import asyncio
import collections
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import aiohttp

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

_CONFIDENCE_RANK = {"none": 0, "medium": 1, "high": 2}


@dataclass
class RemediationPolicy:
    """Bounds; every one must hold before anything actuates."""

    enabled: bool = False               # the kill-switch (default OFF)
    confidence_floor: str = "high"      # minimum attribution confidence
    target_roles: Tuple[str, ...] = ("engine", "prefill")
    max_per_window: int = 1             # executed remediations...
    window_s: float = 600.0             # ...per this window
    cooldown_s: float = 120.0           # after each executed one
    drain_timeout_s: float = 30.0       # bounded wait for in-flight 0
    drain_poll_s: float = 0.5
    verify_timeout_s: float = 60.0      # bounded wait for alert clear
    verify_poll_s: float = 1.0

    def validate(self) -> "RemediationPolicy":
        if self.confidence_floor not in _CONFIDENCE_RANK:
            raise ValueError(f"confidence_floor must be one of "
                             f"{sorted(_CONFIDENCE_RANK)}")
        if self.max_per_window < 1:
            raise ValueError("max_per_window must be >= 1")
        if self.window_s <= 0 or self.cooldown_s < 0:
            raise ValueError("window_s must be positive, cooldown_s "
                             "non-negative")
        return self


class Remediator:
    """Polls the incident index, executes the bounded runbook.

    ``restart_fn(url) -> awaitable[bool]`` is injected by whatever
    owns process lifecycles (the fleetdrill relaunches the fake
    engine; a k8s operator would delete the pod). Without one, the
    action degrades to drain + breaker reset + undrain
    (``breaker_reset``) — enough for a wedged breaker, explicit in
    the log when it was all we could do.
    """

    def __init__(self, *, obsplane_url: str, router_urls,
                 policy: Optional[RemediationPolicy] = None,
                 restart_fn=None,
                 session: Optional[aiohttp.ClientSession] = None,
                 engine_urls_fn=None,
                 now_fn=time.monotonic,
                 wall_fn=time.time,
                 metrics=None):
        self.obsplane_url = obsplane_url.rstrip("/")
        if isinstance(router_urls, str):
            router_urls = [u.strip() for u in router_urls.split(",")
                           if u.strip()]
        self.router_urls = [u.rstrip("/") for u in router_urls]
        self.policy = (policy or RemediationPolicy()).validate()
        self.restart_fn = restart_fn
        self._session = session
        self._owns_session = session is None
        # optional managed-endpoint enumerator (actuator.endpoint_urls):
        # when present, attributions naming processes outside the
        # managed set are refused — this loop must never drain an
        # engine some other controller owns
        self._engine_urls_fn = engine_urls_fn
        self._now = now_fn
        self._wall = wall_fn
        self.metrics = metrics            # AutoscalerMetrics or None
        self._timeout = aiohttp.ClientTimeout(total=5)
        # incident cursor: only incidents captured after the
        # remediator came up are actionable (a restart must not replay
        # a week of stale bundles), and each id is acted on once
        self._since_captured_at = wall_fn()
        self._seen: set = set()
        self._executed_at: collections.deque = collections.deque()
        self._last_executed_at: Optional[float] = None

    async def start(self) -> None:
        if self._owns_session and self._session is None:
            self._session = aiohttp.ClientSession()

    async def close(self) -> None:
        if self._owns_session and self._session:
            await self._session.close()
            self._session = None

    # -- HTTP helpers ----------------------------------------------------

    async def _get_json(self, url: str,
                        params: Optional[dict] = None) -> Optional[dict]:
        try:
            async with self._session.get(
                    url, params=params, timeout=self._timeout) as r:
                if r.status == 200:
                    return await r.json()
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError, ValueError):
            pass
        return None

    async def _post_json(self, url: str, body: dict) -> bool:
        try:
            async with self._session.post(
                    url, json=body, timeout=self._timeout) as r:
                return r.status == 200
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError):
            return False

    # -- the tick --------------------------------------------------------

    async def tick(self, now: Optional[float] = None) -> List[dict]:
        """Process every new incident once; returns the remediation
        records (executed AND suppressed) for the decision log."""
        now = self._now() if now is None else now
        if self._session is None:
            await self.start()
        data = await self._get_json(
            f"{self.obsplane_url}/fleet/incidents",
            params={"since": repr(self._since_captured_at),
                    "role": ",".join(self.policy.target_roles)})
        if data is None:
            return []
        out: List[dict] = []
        for row in data.get("incidents", []):
            incident_id = row.get("incident_id")
            if not incident_id or incident_id in self._seen:
                continue
            self._seen.add(incident_id)
            out.append(await self._handle(row, now))
        return out

    def _window_count(self, now: float) -> int:
        cutoff = now - self.policy.window_s
        while self._executed_at and self._executed_at[0] < cutoff:
            self._executed_at.popleft()
        return len(self._executed_at)

    async def _handle(self, row: dict, now: float) -> dict:
        attribution = row.get("attribution") or {}
        record = {
            "incident_id": row.get("incident_id"),
            "alert": row.get("alert"),
            "target": attribution.get("process"),
            "role": attribution.get("role"),
            "phase": attribution.get("phase"),
            "confidence": attribution.get("confidence"),
            "action": ("drain_restart" if self.restart_fn is not None
                       else "breaker_reset"),
        }
        pol = self.policy
        confidence = attribution.get("confidence") or "none"
        # guards, cheapest first; each refusal is an explicit outcome
        if not pol.enabled:
            record.update(outcome="suppressed_killswitch",
                          detail="remediation disabled (--remediate "
                                 "not set)")
            return self._finish(record)
        if _CONFIDENCE_RANK.get(confidence, 0) < \
                _CONFIDENCE_RANK[pol.confidence_floor]:
            record.update(outcome="suppressed_confidence",
                          detail=f"attribution confidence "
                                 f"{confidence!r} below floor "
                                 f"{pol.confidence_floor!r}")
            return self._finish(record)
        target = (attribution.get("process") or "").rstrip("/")
        role = attribution.get("role")
        if not target or role not in pol.target_roles:
            record.update(outcome="suppressed_role",
                          detail=f"attributed role {role!r} is not "
                                 f"remediable")
            return self._finish(record)
        if self._engine_urls_fn is not None:
            managed = {u.rstrip("/") for u in self._engine_urls_fn()}
            if target not in managed:
                record.update(outcome="suppressed_unmanaged",
                              detail=f"{target} is not a managed "
                                     f"endpoint")
                return self._finish(record)
        if self._last_executed_at is not None and \
                now - self._last_executed_at < pol.cooldown_s:
            record.update(outcome="suppressed_cooldown",
                          detail=f"{pol.cooldown_s:.0f}s cooldown "
                                 f"since the last remediation")
            return self._finish(record)
        if self._window_count(now) >= pol.max_per_window:
            record.update(outcome="suppressed_rate_limit",
                          detail=f"{pol.max_per_window} remediation(s)"
                                 f" already executed in the last "
                                 f"{pol.window_s:.0f}s")
            return self._finish(record)

        # every bound passed: execute, then verify
        self._executed_at.append(now)
        self._last_executed_at = now
        record["executed_at"] = round(self._wall(), 3)
        try:
            await self._execute(record, target)
        except Exception as e:      # a half-done runbook is an outcome
            logger.exception("remediation of %s failed", target)
            record.update(outcome="failed",
                          detail=f"{type(e).__name__}: {e}")
        return self._finish(record)

    def _finish(self, record: dict) -> dict:
        level = (logger.warning
                 if record["outcome"].startswith(("failed",
                                                  "unresolved"))
                 else logger.info)
        level("remediation %s: %s (%s) — %s",
              record.get("incident_id"), record["outcome"],
              record.get("target"), record.get("detail", ""))
        return record

    async def _execute(self, record: dict, target: str) -> None:
        steps: List[str] = []
        record["steps"] = steps
        # 1. drain at every router (idempotent; end_drain is always
        # re-entered in `finally`-style below even on failure paths)
        for router in self.router_urls:
            ok = await self._post_json(f"{router}/admin/drain",
                                       {"url": target, "drain": True})
            steps.append(f"drain@{router}:{'ok' if ok else 'FAIL'}")
        try:
            # 2. bounded wait for the victim's in-flight to reach zero
            drained = await self._wait_drained(target)
            steps.append("drained" if drained else "drain_timeout")
            # 3. restart (injected) or breaker reset only
            if self.restart_fn is not None:
                restarted = bool(await self.restart_fn(target))
                steps.append("restart" if restarted
                             else "restart_FAIL")
                if not restarted:
                    record.update(outcome="failed",
                                  detail="restart hook returned "
                                         "failure")
                    return
        finally:
            # 4. routing resumes whatever happened above: a drained
            # flag left behind would be a remediation-caused outage
            for router in self.router_urls:
                await self._post_json(f"{router}/admin/drain",
                                      {"url": target, "drain": False})
                await self._post_json(f"{router}/admin/breaker",
                                      {"url": target,
                                       "action": "reset"})
            steps.append("undrain+breaker_reset")
        # 5. verify the triggering alert actually leaves the firing set
        resolved = await self._verify_resolved(record.get("alert"))
        record.update(
            outcome="resolved" if resolved else "unresolved",
            detail=("alert cleared within verify window" if resolved
                    else f"alert still firing after "
                         f"{self.policy.verify_timeout_s:.0f}s"))

    async def _wait_drained(self, target: str) -> bool:
        deadline = self._now() + self.policy.drain_timeout_s
        while self._now() < deadline:
            load = await self._get_json(f"{target}/load")
            if load is not None:
                in_flight = (float(load.get("queue_depth") or 0)
                             + float(load.get("running") or 0))
                if in_flight <= 0:
                    return True
            await asyncio.sleep(self.policy.drain_poll_s)
        return False

    async def _verify_resolved(self, alert: Optional[str]) -> bool:
        if not alert:
            return False
        deadline = self._now() + self.policy.verify_timeout_s
        while self._now() < deadline:
            fleet = await self._get_json(f"{self.obsplane_url}/fleet")
            if fleet is not None:
                firing = {a.get("name")
                          for a in fleet.get("firing_alerts") or ()}
                if alert not in firing:
                    return True
            await asyncio.sleep(self.policy.verify_poll_s)
        return False
