{{/*
Helper templates (reference: helm/templates/_helpers.tpl). Names keep the
`chart.` prefix so golden values files port over mechanically.
*/}}

{{- define "chart.engineLabels" -}}
{{- with .Values.servingEngineSpec.labels }}
{{- toYaml . }}
{{- end }}
{{- end }}

{{- define "chart.routerLabels" -}}
{{- with .Values.routerSpec.labels }}
{{- toYaml . }}
{{- end }}
{{- end }}

{{- define "chart.cacheserverLabels" -}}
{{- with .Values.cacheserverSpec.labels }}
{{- toYaml . }}
{{- end }}
{{- end }}

{{/* Engine container resources: host cpu/memory + google.com/tpu chips.
     modelSpec is passed as the dict context. */}}
{{- define "chart.engineResources" -}}
requests:
  {{- if .requestCPU }}
  cpu: {{ .requestCPU | quote }}
  {{- end }}
  {{- if .requestMemory }}
  memory: {{ .requestMemory | quote }}
  {{- end }}
  {{- if .requestTPU }}
  google.com/tpu: {{ .requestTPU | quote }}
  {{- end }}
limits:
  {{- if .limitCPU }}
  cpu: {{ .limitCPU | quote }}
  {{- end }}
  {{- if .limitMemory }}
  memory: {{ .limitMemory | quote }}
  {{- end }}
  {{- if .requestTPU }}
  {{/* TPU chips must be limited == requested (extended resource) */}}
  google.com/tpu: {{ .requestTPU | quote }}
  {{- end }}
{{- end }}

{{/* Zero-downtime rolling update (reference _helpers.tpl:44-53): bring
     the full replacement capacity up before taking any replica down. */}}
{{- define "chart.engineStrategy" -}}
{{- if .Values.servingEngineSpec.strategy }}
{{- toYaml .Values.servingEngineSpec.strategy }}
{{- else }}
rollingUpdate:
  maxSurge: 100%
  maxUnavailable: 0
type: RollingUpdate
{{- end }}
{{- end }}

{{/* tpukv:// URL of the chart's cache-server service (reference
     cacheserver.formatRemoteUrl -> lm://name:port). */}}
{{- define "chart.kvRemoteUrl" -}}
tpukv://{{ .Release.Name }}-cache-server-service:{{ .Values.cacheserverSpec.servicePort }}
{{- end }}

{{/* --kv-transfer-config JSON from a modelSpec.kvCacheConfig block
     (context: dict with "root" = $ and "spec" = kvCacheConfig).
     Reference equivalent: the LMCache env block + --kv-transfer-config
     (deployment-vllm-multi.yaml:94-99,154-178). */}}
{{- define "chart.kvTransferJson" -}}
{{- $cfg := dict "kv_role" (.spec.role | default "kv_both") -}}
{{- if .spec.hostOffloadGiB -}}
{{- $_ := set $cfg "local_cpu_gb" .spec.hostOffloadGiB -}}
{{- end -}}
{{- if .spec.diskPath -}}
{{- $_ := set $cfg "local_disk_path" .spec.diskPath -}}
{{- $_ := set $cfg "local_disk_gb" (.spec.diskGiB | default 16) -}}
{{- end -}}
{{- if .spec.useRemote -}}
{{- if not .root.Values.cacheserverSpec.enabled -}}
{{- fail "kvCacheConfig.useRemote requires cacheserverSpec.enabled=true (the tpukv service would not exist)" -}}
{{- end -}}
{{- $_ := set $cfg "remote_url" (include "chart.kvRemoteUrl" .root) -}}
{{- end -}}
{{- toJson $cfg -}}
{{- end }}

{{/* Label selector string the router passes to --k8s-label-selector,
     derived from servingEngineSpec.labels (reference
     deployment-router.yaml:41-77). */}}
{{- define "chart.engineLabelSelector" -}}
{{- $pairs := list -}}
{{- range $k, $v := .Values.servingEngineSpec.labels -}}
{{- $pairs = append $pairs (printf "%s=%s" $k $v) -}}
{{- end -}}
{{- join "," $pairs -}}
{{- end }}
