"""Serving-engine benchmark: prints ONE JSON line with decode throughput.

Measures end-to-end continuous-batching generation throughput (output
tokens/sec) of the TPU-native engine on a TinyLlama-1.1B-geometry model
(random weights — throughput is weight-value-independent), batch 32
(the paged engine's best verified config; --batch 8 for the legacy
compatibility point), 128-token prompts, 128 generated tokens per
request, greedy.

Failure model (this harness must produce a verifiable number in EVERY
world — two of the first three rounds lost their perf record to a wedged
TPU tunnel that hangs `jax.devices()` forever):
- the parent process NEVER imports jax. All backend work happens in
  child processes with hard timeouts.
- TPU liveness is probed in a subprocess (bounded retries). Only a
  passing probe admits a TPU attempt; a hung probe is killed, not waited
  on.
- the TPU bench run itself has a hard timeout and one retry; any
  failure falls back to a CPU run (JAX_PLATFORMS=cpu, --small model)
  recording platform "cpu" and "tpu_unavailable": true.
- if even CPU fails, a JSON line with "value": 0 and the error is
  printed. Exit code is 0 in every path.

vs_baseline: ratio against the value recorded in BENCH_REF.json for this
(mode, platform) pair — first run of a pair records the baseline (ratio
1.0); later rounds show the improvement factor. The reference repo
publishes no absolute numbers (see BASELINE.md), so the trajectory is
measured against ourselves.

Usage: python bench.py [--small] [--batch N] [--gen-len N]
                       [--quantization int8] [--spec N] [--kv-pool-frac F]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
REF_PATH = os.path.join(REPO, "BENCH_REF.json")

PROBE_TIMEOUT_S = 90        # one jax.devices() probe
PROBE_TRIES = 3             # bounded probe window: <= ~5 min total
PROBE_GAP_S = 20
TPU_RUN_TIMEOUT_S = 2700    # full bench incl. first-compile (~20-40s/exe)
CPU_RUN_TIMEOUT_S = 1500    # both cover the default untimed warm pass,
                            # which roughly doubles post-compile wall


def parse_cli(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="tiny model (CPU-viable quick check)")
    ap.add_argument("--child", action="store_true",
                    help="internal: run the bench in-process (no "
                         "supervision); used by the parent orchestrator")
    ap.add_argument("--batch", type=int, default=None,
                    help="concurrent batch slots (default: 32 full mode "
                         "— the paged engine's best verified config — "
                         "8 small mode)")
    ap.add_argument("--gen-len", type=int, default=0,
                    help="tokens generated per request (0 = mode default)")
    ap.add_argument("--requests", type=int, default=0,
                    help="total requests (0 = 2x batch)")
    ap.add_argument("--prompt-len", type=int, default=0,
                    help="prompt tokens per request (0 = mode default)")
    ap.add_argument("--quantization", choices=["int8"], default=None)
    ap.add_argument("--kv-cache-dtype",
                    choices=["bfloat16", "float32", "int8"],
                    default=None,
                    help="KV cache precision (int8 halves long-context "
                         "decode KV HBM traffic)")
    ap.add_argument("--spec", type=int, default=0,
                    help="n-gram speculative draft length (0 = off)")
    ap.add_argument("--prompt-repeat", type=int, default=0,
                    help="build each prompt by tiling a short per-"
                         "request phrase this many times (repetitive "
                         "multi-round-QA-like histories — the workload "
                         "n-gram speculation is FOR; 0 = the synthetic "
                         "near-random default, adversarial for spec)")
    ap.add_argument("--kv-pool-frac", type=float, default=1.0,
                    help="KV pool size as a fraction of the worst-case "
                         "batch*max_model_len reservation (paged KV)")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="chunked-prefill chunk size (0 = mode default; "
                         "long-context TTFT sweeps)")
    ap.add_argument("--window", type=int, default=0,
                    help="fused decode-window length (0 = mode default; "
                         "per window the host pays one dispatch + one "
                         "sync, so longer windows amortize tunnel/"
                         "dispatch latency)")
    ap.add_argument("--pipeline-depth", type=int, default=0,
                    help="decode windows queued on the device at once "
                         "(0 = config default 2; 3 can hide more tunnel "
                         "RTT behind device work)")
    ap.add_argument("--cold", action="store_true",
                    help="skip the untimed warm pass (measure a cold "
                         "engine, lazy compiles land in the timed region)")
    return ap.parse_args(argv)


def run_bench(args) -> dict:
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.scheduler import SamplingOptions

    batch = args.batch or (8 if args.small else 32)
    if args.small:
        cfg_kw = dict(model="debug-tiny", max_model_len=512,
                      max_num_seqs=batch, prefill_chunk=128,
                      decode_window=16)
        prompt_len, gen_len = 64, 32
    else:
        # decode_window 32: one dispatch + one host sync per 32 tokens
        # per slot; 128-token answers pack into exactly 4 windows
        cfg_kw = dict(model="tinyllama-1.1b", max_model_len=1024,
                      max_num_seqs=batch, prefill_chunk=512,
                      decode_window=32, prefill_buckets=(128, 512))
        prompt_len, gen_len = 128, 128
    if args.prompt_len:
        prompt_len = args.prompt_len
    if args.gen_len:
        gen_len = args.gen_len
    # the cache must hold prompt + generation; grow it to the covering
    # multiple of 256 for long-context / long-generation sweeps. A
    # power-of-two covering doubles the KV pool for just-past-a-bucket
    # spans (8320 -> 16384 pins ~3 GB of pool instead of ~1.5 and blew
    # HBM at batch 8 x 8k bf16); the top kv bucket lands on
    # max_model_len either way, so attention cost stays ~ live prefix.
    span = prompt_len + gen_len
    if span > cfg_kw["max_model_len"]:
        cfg_kw["max_model_len"] = -(-span // 256) * 256
    if args.prefill_chunk:
        cfg_kw["prefill_chunk"] = args.prefill_chunk
        cfg_kw["prefill_buckets"] = (args.prefill_chunk,)
    if args.window:
        cfg_kw["decode_window"] = args.window
    n_requests = args.requests or 2 * batch
    if args.quantization:
        cfg_kw["quantization"] = args.quantization
    if args.kv_cache_dtype:
        cfg_kw["kv_dtype"] = args.kv_cache_dtype
    if args.spec:
        cfg_kw["speculative_ngram_tokens"] = args.spec
    if args.kv_pool_frac < 1.0:
        worst = cfg_kw["max_num_seqs"] * cfg_kw["max_model_len"]
        cfg_kw["kv_pool_tokens"] = int(worst * args.kv_pool_frac)
    if args.pipeline_depth:
        cfg_kw["pipeline_depth"] = args.pipeline_depth
    cfg = EngineConfig(**cfg_kw)

    eng = LLMEngine(cfg)
    compile_s = eng.runner.warmup()

    opts = SamplingOptions(temperature=0.0, max_tokens=gen_len,
                           ignore_eos=True)
    if args.prompt_repeat:
        # repetitive histories (multi-round QA re-sends the growing
        # conversation every round): a short per-request phrase tiled
        # across the prompt, so n-gram lookup finds real continuations
        rng_tokens = []
        for i in range(n_requests):
            phrase = [(13 * i + j) % 1000 + 1
                      for j in range(max(4, prompt_len
                                         // max(1, args.prompt_repeat)))]
            tiled = (phrase * (prompt_len // len(phrase) + 1))[:prompt_len]
            rng_tokens.append(tiled)
    else:
        rng_tokens = [[(7 * i + j) % 1000 + 1 for j in range(prompt_len)]
                      for i in range(n_requests)]

    def run_pass():
        ids = [eng.add_request(toks, opts) for toks in rng_tokens]
        done = set()
        while len(done) < len(ids):
            for out in eng.step():
                if out.finished:
                    done.add(out.seq_id)
        return ids

    warm_s = 0.0
    if not args.cold:
        # untimed warm pass over the exact workload: warmup() compiles
        # the hot executables, but sweep configs (long-context kv
        # buckets, spec/guided variants) can still compile lazily —
        # that belongs to warm_s, not the measurement
        t0 = time.time()
        run_pass()
        warm_s = time.time() - t0

    t0 = time.time()
    ids = run_pass()
    wall = time.time() - t0

    out_tokens = sum(len(eng.seqs[i].output_tokens) for i in ids)
    in_tokens = sum(len(t) for t in rng_tokens)
    spec_stats = {}
    if cfg.speculative_ngram_tokens:
        steps = eng.metrics.spec_macro_steps._value.get()
        accepted = eng.metrics.spec_accepted_tokens._value.get()
        spec_stats = {
            # accepted draft tokens per macro-step (0..spec): the
            # workload-dependent quantity that decides whether
            # speculation pays for its (spec+1)-wide verify forwards
            "spec_acceptance": round(accepted / steps, 4) if steps
            else 0.0,
            "spec_macro_steps": int(steps),
        }
    return {
        **spec_stats,
        "output_tokens_per_s": out_tokens / wall,
        "total_tokens_per_s": (out_tokens + in_tokens) / wall,
        "wall_s": wall,
        "compile_s": compile_s,
        "warm_s": warm_s,
        # pre-r4 baselines were recorded cold (lazy compiles could land
        # in the timed region); compare vs_baseline across methodologies
        # with that in mind
        "methodology": "cold" if args.cold else "warm",
        "out_tokens": out_tokens,
        "model": cfg.model,
        "batch_slots": cfg.max_num_seqs,
        "prompt_len": prompt_len,
        "gen_len": gen_len,
        "quantization": cfg.quantization,
        "kv_dtype": cfg.kv_dtype,
        "speculative": cfg.speculative_ngram_tokens,
        "decode_window": cfg.decode_window,
    }


def record_line(args, stats: dict, platform: str) -> dict:
    value = round(stats["output_tokens_per_s"], 2)
    batch = stats["batch_slots"]
    # baselines keyed by (mode, platform, batch) so vs_baseline always
    # compares a config against ITS OWN prior record — batch 32 against
    # the verified round-4 batch-32 number, never against the round-1
    # batch-8 cold point. Legacy (pre-r5) entries were unkeyed by batch
    # and recorded at batch 8; fall back to them for batch-8 runs.
    mode = "small" if args.small else "full"
    key = f"{mode}-{platform}-b{batch}"
    refs = {}
    if os.path.exists(REF_PATH):
        try:
            with open(REF_PATH) as f:
                refs = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            refs = {}
    ref = refs.get(key)
    if ref is None and batch == 8:
        ref = refs.get(f"{mode}-{platform}")
    standard = (not args.quantization
                and not args.kv_cache_dtype
                and not args.spec and not args.gen_len
                and not args.prompt_len and not args.requests
                and not args.prefill_chunk and not args.cold
                and not args.window and not args.prompt_repeat
                and not args.pipeline_depth
                and args.kv_pool_frac == 1.0)
    if ref is None and standard:
        # only standard configs may set the baseline for a pair
        refs[key] = ref = value
        try:
            with open(REF_PATH, "w") as f:
                json.dump(refs, f)
        except OSError:
            pass
    return {
        "metric": "engine decode throughput (TinyLlama-1.1B geometry, "
                  f"batch {batch}, {stats['prompt_len']}+"
                  f"{stats['gen_len']} tok, single chip)"
        if not args.small else "engine decode throughput (debug-tiny)",
        "value": value,
        "unit": "out_tok/s",
        "vs_baseline": round(value / ref, 3) if ref else 1.0,
        "platform": platform,
        "detail": {k: (round(v, 2) if isinstance(v, float) else v)
                   for k, v in stats.items()},
    }


def child_main(args) -> None:
    # Make JAX_PLATFORMS authoritative before backend init: with the TPU
    # tunnel wedged, the sitecustomize-registered plugin can hang even a
    # JAX_PLATFORMS=cpu run at backend discovery unless the config is
    # pinned first — same call every server entry point makes.
    from production_stack_tpu.utils import honor_platform_env
    honor_platform_env()
    import jax
    platform = jax.devices()[0].platform
    stats = run_bench(args)
    print(json.dumps(record_line(args, stats, platform)))


# ----------------------------------------------------------------------
# parent orchestration (no jax imports here, ever)
# ----------------------------------------------------------------------

# the probe must pin JAX_PLATFORMS before backend init, exactly like
# utils.honor_platform_env(): the environment registers a TPU PJRT
# plugin via sitecustomize that can hang even a JAX_PLATFORMS=cpu run
# at backend discovery otherwise
_PROBE_SRC = (
    "import os, jax\n"
    "w = os.environ.get('JAX_PLATFORMS')\n"
    "if w: jax.config.update('jax_platforms', w)\n"
    "d = jax.devices()\n"
    "print('PLATFORM=' + d[0].platform)\n")


def probe_platform(timeout_s: float) -> str:
    """Backend liveness in a killable subprocess: 'tpu', 'cpu', or ''."""
    try:
        p = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                           capture_output=True, text=True,
                           timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired:
        return ""
    if p.returncode != 0:
        return ""
    for line in p.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1].strip()
    return ""


def run_child(extra_args, env_over, timeout_s: float):
    """Run `bench.py --child ...`; return its parsed JSON line or None."""
    env = dict(os.environ, **env_over)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"]
            + extra_args,
            capture_output=True, text=True, timeout=timeout_s, cwd=REPO,
            env=env)
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"bench child timed out after {timeout_s}s\n")
        return None
    if p.stderr:
        sys.stderr.write(p.stderr[-4000:])
    for line in reversed(p.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    sys.stderr.write(f"bench child rc={p.returncode}, no JSON line\n")
    return None


def forward_args(args) -> list:
    out = []
    if args.small:
        out.append("--small")
    if args.batch is not None:
        out += ["--batch", str(args.batch)]
    if args.gen_len:
        out += ["--gen-len", str(args.gen_len)]
    if args.prompt_len:
        out += ["--prompt-len", str(args.prompt_len)]
    if args.requests:
        out += ["--requests", str(args.requests)]
    if args.quantization:
        out += ["--quantization", args.quantization]
    if args.kv_cache_dtype:
        out += ["--kv-cache-dtype", args.kv_cache_dtype]
    if args.spec:
        out += ["--spec", str(args.spec)]
    if args.prompt_repeat:
        out += ["--prompt-repeat", str(args.prompt_repeat)]
    if args.pipeline_depth:
        out += ["--pipeline-depth", str(args.pipeline_depth)]
    if args.kv_pool_frac != 1.0:
        out += ["--kv-pool-frac", str(args.kv_pool_frac)]
    if args.prefill_chunk:
        out += ["--prefill-chunk", str(args.prefill_chunk)]
    if args.window:
        out += ["--window", str(args.window)]
    if args.cold:
        out.append("--cold")
    return out


def main() -> None:
    args = parse_cli()
    if args.child:
        child_main(args)
        return

    fwd = forward_args(args)

    # 1) bounded TPU probe window
    platform = ""
    for i in range(PROBE_TRIES):
        platform = probe_platform(PROBE_TIMEOUT_S)
        if platform:
            break
        sys.stderr.write(f"backend probe {i + 1}/{PROBE_TRIES} failed\n")
        if i + 1 < PROBE_TRIES:
            time.sleep(PROBE_GAP_S)

    # 2) probed backend attempt (TPU gets a retry: a live probe with a
    #    failed run can be a transient tunnel stall)
    if platform:
        tries = 2 if platform == "tpu" else 1
        timeout = (TPU_RUN_TIMEOUT_S if platform == "tpu"
                   else CPU_RUN_TIMEOUT_S)
        for _ in range(tries):
            result = run_child(fwd, {}, timeout)
            if result is not None:
                print(json.dumps(result))
                return
            if platform == "tpu" and not probe_platform(PROBE_TIMEOUT_S):
                break   # tunnel died mid-run; no point retrying

    # 3) CPU fallback: tiny model, pinned CPU backend, flagged output.
    # Strip PYTHONPATH entries that inject a sitecustomize module: a
    # wedged PJRT-plugin tunnel registered that way hangs backend
    # discovery even under JAX_PLATFORMS=cpu, which would turn the CPU
    # fallback into a timeout instead of a number.
    sys.stderr.write("falling back to CPU bench (--small)\n")
    clean_pp = os.pathsep.join(
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and not os.path.exists(os.path.join(p, "sitecustomize.py")))
    cpu_args = [a for a in fwd if a != "--small"]
    result = run_child(["--small"] + cpu_args,
                       {"JAX_PLATFORMS": "cpu", "PYTHONPATH": clean_pp},
                       CPU_RUN_TIMEOUT_S)
    if result is not None:
        result["tpu_unavailable"] = True
        result["metric"] += " [CPU FALLBACK: TPU unavailable]"
        print(json.dumps(result))
        return

    # 4) last resort: still one parsable JSON line, rc 0
    print(json.dumps({
        "metric": "engine decode throughput",
        "value": 0.0,
        "unit": "out_tok/s",
        "vs_baseline": 0.0,
        "platform": "none",
        "tpu_unavailable": True,
        "error": "backend init failed on both TPU and CPU within the "
                 "probe/run timeout budget",
    }))


if __name__ == "__main__":
    main()
