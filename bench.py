"""Serving-engine benchmark: prints ONE JSON line with decode throughput.

Measures end-to-end continuous-batching generation throughput (output
tokens/sec) of the TPU-native engine on a TinyLlama-1.1B-geometry model
(random weights — throughput is weight-value-independent), batch 8,
128-token prompts, 128 generated tokens per request, greedy.

vs_baseline: ratio against the value recorded in BENCH_REF.json for this
(mode, platform) pair — first run of a pair records the baseline (ratio
1.0); later rounds show the improvement factor. The reference repo
publishes no absolute numbers (see BASELINE.md), so the trajectory is
measured against ourselves.

Usage: python bench.py [--small]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
REF_PATH = os.path.join(REPO, "BENCH_REF.json")

# Make JAX_PLATFORMS authoritative before backend init (no-op when the
# env var is unset, i.e. on the driver's real-TPU run): with the TPU
# tunnel wedged, the sitecustomize-registered plugin can hang even a
# JAX_PLATFORMS=cpu run at backend discovery unless the config is
# pinned first — same call every server entry point makes.
from production_stack_tpu.utils import honor_platform_env  # noqa: E402
honor_platform_env()


def run_bench(small: bool) -> dict:
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.scheduler import SamplingOptions

    if small:
        cfg = EngineConfig(model="debug-tiny", max_model_len=512,
                           max_num_seqs=8, prefill_chunk=128,
                           decode_window=16)
        prompt_len, gen_len, n_requests = 64, 32, 16
    else:
        # decode_window 32: one dispatch + one host sync per 32 tokens
        # per slot; 128-token answers pack into exactly 4 windows
        cfg = EngineConfig(model="tinyllama-1.1b", max_model_len=1024,
                           max_num_seqs=8, prefill_chunk=512,
                           decode_window=32, prefill_buckets=(128, 512))
        prompt_len, gen_len, n_requests = 128, 128, 16

    eng = LLMEngine(cfg)
    compile_s = eng.runner.warmup()

    opts = SamplingOptions(temperature=0.0, max_tokens=gen_len,
                           ignore_eos=True)
    rng_tokens = [[(7 * i + j) % 1000 + 1 for j in range(prompt_len)]
                  for i in range(n_requests)]

    t0 = time.time()
    ids = [eng.add_request(toks, opts) for toks in rng_tokens]
    done = set()
    while len(done) < len(ids):
        for out in eng.step():
            if out.finished:
                done.add(out.seq_id)
    wall = time.time() - t0

    out_tokens = sum(len(eng.seqs[i].output_tokens) for i in ids)
    in_tokens = sum(len(t) for t in rng_tokens)
    return {
        "output_tokens_per_s": out_tokens / wall,
        "total_tokens_per_s": (out_tokens + in_tokens) / wall,
        "wall_s": wall,
        "compile_s": compile_s,
        "out_tokens": out_tokens,
        "model": cfg.model,
        "batch_slots": cfg.max_num_seqs,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="tiny model (CPU-viable quick check)")
    args = ap.parse_args()

    import jax
    platform = jax.devices()[0].platform
    stats = run_bench(args.small)

    value = round(stats["output_tokens_per_s"], 2)
    # baselines keyed by (mode, platform) so runs never clobber each other
    key = f"{'small' if args.small else 'full'}-{platform}"
    refs = {}
    if os.path.exists(REF_PATH):
        try:
            with open(REF_PATH) as f:
                refs = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            refs = {}
    ref = refs.get(key)
    if ref is None:
        refs[key] = ref = value
        with open(REF_PATH, "w") as f:
            json.dump(refs, f)

    print(json.dumps({
        "metric": "engine decode throughput (TinyLlama-1.1B geometry, "
                  "batch 8, 128+128 tok, single chip)"
        if not args.small else "engine decode throughput (debug-tiny)",
        "value": value,
        "unit": "out_tok/s",
        "vs_baseline": round(value / ref, 3) if ref else 1.0,
        "platform": platform,
        "detail": {k: (round(v, 2) if isinstance(v, float) else v)
                   for k, v in stats.items()},
    }))


if __name__ == "__main__":
    main()
