#!/usr/bin/env python3
"""Lint: the generated alert rules must resolve and stay in sync.

Three contracts over ``observability/alert-rules.yaml`` (wired into
the ci.yml lint job next to check_metrics_documented.py, and into
tier-1 via tests/test_observability.py):

1. **No drift** — the committed file must byte-match a fresh
   ``tools/gen_alert_rules.py`` compilation of the SLO definitions in
   ``production_stack_tpu/slo.py`` (the in-process engine and the
   cluster rules share one source).
2. **Metrics resolve** — every ``tpu:``/``vllm:`` family an alert
   expression references must be a family the code actually registers
   (same literal scan as check_metrics_documented.py): a renamed
   gauge cannot leave a rule silently matching nothing.
3. **Runbooks exist** — every alert must carry a ``runbook``
   annotation pointing at a ``docs/runbooks.md`` anchor whose heading
   exists: an alert that fires at 3am must come with its diagnosis
   steps.
4. **Fleet evidence linked** — every alert's runbook section must
   link the ``#incident-bundle`` anchor (and that anchor's heading
   must exist): with the obsplane deployed, the alert's firing
   transition already captured the fleet-wide evidence, and a runbook
   that does not say so sends the responder scraping 2R+N endpoints
   by hand.
5. **Automation stated** — every alert's runbook section must carry
   an ``**Automated:** yes/no/partial`` line: with the fleet pilot's
   remediation loop deployable (autoscaler/remediator.py), the first
   question a 3am responder asks is "is a robot already on this?" —
   a runbook that does not answer it invites double-driving.
"""

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
RULES = REPO / "observability" / "alert-rules.yaml"
RUNBOOKS = REPO / "docs" / "runbooks.md"

METRIC_RE = re.compile(r"((?:tpu|vllm):[a-z][a-z0-9_]*)")
AUTOMATED_RE = re.compile(r"\*\*Automated:\*\*\s+(yes|no|partial)\b")


def _registered_metrics() -> set:
    import importlib.util
    path = REPO / "tools" / "check_metrics_documented.py"
    spec = importlib.util.spec_from_file_location("check_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.registered_metrics()


def _anchor(title: str) -> str:
    """GitHub-style anchor slug of one heading title."""
    return re.sub(r"[^a-z0-9_\- ]", "", title.strip().lower()) \
        .replace(" ", "-")


def _runbook_sections(text: str) -> dict:
    """{anchor: section body} — every heading (any level) up to the
    next heading; the anchor set and the section map are derived from
    the SAME heading walk so checks 3 and 4 cannot disagree about
    which headings exist."""
    sections = {}
    matches = list(re.finditer(r"^#+\s+(.+?)\s*$", text, re.M))
    for i, m in enumerate(matches):
        end = matches[i + 1].start() if i + 1 < len(matches) \
            else len(text)
        sections[_anchor(m.group(1))] = text[m.end():end]
    return sections


def main() -> int:
    problems = []

    sys.path.insert(0, str(REPO / "tools"))
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "gen_alert_rules", REPO / "tools" / "gen_alert_rules.py")
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)
    expected = gen.render()
    if not RULES.exists():
        problems.append(f"{RULES} is missing — run "
                        f"python tools/gen_alert_rules.py")
    elif RULES.read_text() != expected:
        problems.append(f"{RULES} drifted from slo.py — run "
                        f"python tools/gen_alert_rules.py")

    import yaml
    doc = yaml.safe_load(RULES.read_text()) if RULES.exists() else None
    registered = _registered_metrics()
    runbook_text = RUNBOOKS.read_text() if RUNBOOKS.exists() else ""
    sections = _runbook_sections(runbook_text)
    anchors = set(sections)
    if not RUNBOOKS.exists():
        problems.append(f"{RUNBOOKS} is missing")
    if "incident-bundle" not in anchors:
        problems.append("docs/runbooks.md has no 'Incident bundle' "
                        "section (#incident-bundle)")

    n_rules = 0
    for group in (doc or {}).get("groups", []):
        for rule in group.get("rules", []):
            n_rules += 1
            name = rule.get("alert", "?")
            for metric in METRIC_RE.findall(rule.get("expr", "")):
                base = re.sub(r"_(bucket|sum|count|total)$", "", metric)
                if not {metric, base, metric + "_total",
                        base + "_total"} & registered:
                    problems.append(
                        f"alert {name}: expr references unregistered "
                        f"metric {metric}")
            runbook = (rule.get("annotations") or {}).get("runbook", "")
            m = re.fullmatch(r"docs/runbooks\.md#([a-z0-9_\-]+)",
                             runbook)
            if not m:
                problems.append(
                    f"alert {name}: runbook annotation {runbook!r} is "
                    f"not a docs/runbooks.md#anchor link")
            elif m.group(1) not in anchors:
                problems.append(
                    f"alert {name}: runbook anchor #{m.group(1)} has "
                    f"no matching heading in docs/runbooks.md")
            else:
                body = sections.get(m.group(1), "")
                if "#incident-bundle" not in body:
                    problems.append(
                        f"alert {name}: runbook section #{m.group(1)} "
                        f"does not link the fleet evidence "
                        f"(#incident-bundle)")
                if not AUTOMATED_RE.search(body):
                    problems.append(
                        f"alert {name}: runbook section #{m.group(1)} "
                        f"has no '**Automated:** yes/no/partial' line "
                        f"(is a robot already on this?)")
    if doc is not None and n_rules == 0:
        problems.append("alert-rules.yaml contains zero rules")

    if problems:
        print(f"{len(problems)} alert-rule problems:", file=sys.stderr)
        for pr in problems:
            print(f"  - {pr}", file=sys.stderr)
        return 1
    print(f"ok: {n_rules} alert rules in sync, all metrics registered, "
          f"all runbook anchors present")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
